"""Serving cell with session affinity + zero-downtime drain (ISSUE 11).

A 3-replica in-process cell serves a multi-turn session. The router
pins the session to the replica that first served it (KV affinity:
turn N+1 hits that replica's prefix cache / host tier instead of
re-prefilling the transcript). Mid-conversation the pinned replica is
DRAINED — its session KV migrates to a sibling in the host tier's
transfer format, new work routes away instantly — and the session
resumes elsewhere with byte-identical greedy output and a host-tier
restore instead of a full re-prefill.

Run (CPU, no checkpoint needed):

    python -m examples.cell_serving.main

Over HTTP the same cell serves through ``APIServer(cell)`` — one
``/v1/chat/completions`` front door, ``/healthz`` and ``/slo.json``
aggregated across replicas (docs/SERVING.md "Serving cell").
"""

import asyncio

from pilottai_tpu.core.config import LLMConfig
from pilottai_tpu.distributed import ServingCell
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.types import GenerationParams
from pilottai_tpu.utils.metrics import global_metrics

TURNS_BEFORE_DRAIN = 2
TURNS_AFTER_DRAIN = 2

PREAMBLE = (
    "Session demo memory: persona planner-1; goals g7, g11. "
    "Analyze the task and respond with JSON. "
)


def _cfg() -> LLMConfig:
    return LLMConfig(
        model_name="llama-tiny",
        provider="cpu",
        dtype="float32",
        engine_slots=4,
        engine_max_seq=512,
        engine_chunk=8,
        # Small hot store + host tier: session turns exercise the
        # spill/restore path the migration rides on.
        engine_prefix_cache=2,
        engine_kvcache_host_mb=128,
    )


async def main() -> None:
    cell = ServingCell([LLMHandler(_cfg()) for _ in range(3)])
    await cell.start()
    params = GenerationParams(max_new_tokens=12, temperature=0.0)
    history = ""
    try:
        for turn in range(TURNS_BEFORE_DRAIN):
            prompt = PREAMBLE + history + f"\nuser: step {turn}?\nassistant:"
            reply = await cell.apredict(
                prompt, params=params, session_id="demo"
            )
            history += f"\nuser: step {turn}?\nassistant: {reply}"
            print(f"turn {turn}: served by {cell.sessions['demo']}")

        pinned = cell.sessions["demo"]
        print(f"\nsession pinned to {pinned}; draining it ...")
        report = await cell.drain(pinned, grace_s=2.0)
        print(
            f"drained {report['replica_id']} in {report['drain_s']}s: "
            f"{report['migrated_sessions']} session(s) migrated, "
            f"{report['readmitted']} request(s) re-admitted"
        )

        restores0 = global_metrics.get("engine.kvcache.restores")
        for turn in range(TURNS_BEFORE_DRAIN,
                          TURNS_BEFORE_DRAIN + TURNS_AFTER_DRAIN):
            prompt = PREAMBLE + history + f"\nuser: step {turn}?\nassistant:"
            reply = await cell.apredict(
                prompt, params=params, session_id="demo"
            )
            history += f"\nuser: step {turn}?\nassistant: {reply}"
            print(f"turn {turn}: served by {cell.sessions['demo']}")
        assert cell.sessions["demo"] != pinned
        restored = global_metrics.get("engine.kvcache.restores") - restores0

        print(
            f"\nresumed on {cell.sessions['demo']} with "
            f"{int(restored)} host-tier restore(s) — the migrated KV "
            f"served the resume instead of a full re-prefill"
        )
        health = cell.health_snapshot()
        print(
            f"cell health: routable {health['routable']}/"
            f"{health['replicas']} (draining: {health['draining']})"
        )
        cellm = cell.get_metrics()["cell"]
        print(
            f"cell metrics: routed.interactive="
            f"{cellm['routed.interactive']:.0f} "
            f"affinity_hit_rate={cellm['affinity_hit_rate']:.2f} "
            f"migrations={cellm['migrations']:.0f}"
        )
    finally:
        await cell.stop()


if __name__ == "__main__":
    asyncio.run(main())
