"""Session resume through the global KV cache tier (ISSUE 10).

A multi-turn conversation re-sends its whole transcript every turn —
the exact workload where prefill dominates. This example runs several
interleaved sessions against an engine whose device-resident prefix
store is deliberately tiny, so each session's entry is evicted between
its turns; with the host-RAM cold tier enabled
(``engine_kvcache_host_mb``) the eviction spills instead of discarding,
and the resume restores from host memory — only the new tail prefills.

Run (CPU, no checkpoint needed):

    python -m examples.session_resume.main

Over HTTP the same behavior is driven by the ``session_id`` body field
or the ``x-session-id`` header on ``/v1/chat/completions``
(docs/SERVING.md).
"""

import asyncio

from pilottai_tpu.core.config import LLMConfig
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.types import GenerationParams
from pilottai_tpu.utils.metrics import global_metrics

SESSIONS = 4
TURNS = 3

KV_COUNTERS = (
    "lookups", "hits", "host_hits", "spills", "restores",
    "prefill_tokens_saved",
)


def _snapshot():
    return {k: global_metrics.get(f"engine.kvcache.{k}") for k in KV_COUNTERS}


async def main() -> None:
    handler = LLMHandler(LLMConfig(
        model_name="llama-tiny",
        provider="cpu",
        dtype="float32",
        engine_slots=4,
        engine_max_seq=512,
        engine_chunk=8,
        # Two hot entries vs four sessions: resumes always land after
        # eviction — the cold tier is what makes them cheap anyway.
        engine_prefix_cache=2,
        engine_kvcache_host_mb=128,
    ))
    await handler.start()
    before = _snapshot()
    try:
        history = {s: "" for s in range(SESSIONS)}
        for turn in range(TURNS):
            for s in range(SESSIONS):
                # Distinct per-session preamble = distinct KV lineage.
                prompt = (
                    f"Session {s:03d} memory: persona agent-{s}; "
                    f"goals g{s * 7}, g{s * 11}. You are a planning "
                    f"assistant; answer in one short sentence."
                    + history[s]
                    + f"\nuser: what is step {turn + 1}?\nassistant:"
                )
                reply = await handler.apredict(
                    prompt,
                    params=GenerationParams(
                        max_new_tokens=24, temperature=0.0,
                        session_id=f"demo-session-{s}",
                    ),
                )
                history[s] += (
                    f"\nuser: what is step {turn + 1}?"
                    f"\nassistant: {reply}"
                )
                print(f"[session {s} turn {turn + 1}] {reply[:60]!r}")
    finally:
        after = _snapshot()
        await handler.stop()

    delta = {k: int(after[k] - before[k]) for k in KV_COUNTERS}
    rate = delta["hits"] / delta["lookups"] if delta["lookups"] else 0.0
    print("\nKV cache tier over this run:")
    for k, v in delta.items():
        print(f"  {k:>22}: {v}")
    print(f"  {'prefix_hit_rate':>22}: {rate:.2f}")
    if delta["restores"]:
        print(
            "\nSession resumes restored spilled KV from host RAM instead "
            "of re-prefilling the transcript."
        )


if __name__ == "__main__":
    asyncio.run(main())
