"""Distributed swarm demo: one orchestrator, N worker hosts, real TCP.

    python examples/distributed_swarm/main.py [--workers 2]
                                              [--provider mock|cpu|tpu]
                                              [--kill-one]

The orchestrator runs :class:`~pilottai_tpu.serve.Serve` with a
:class:`~pilottai_tpu.distributed.ServeEndpoint` listener. Each worker is
a REAL subprocess hosting agents behind its own LLM engine
(``--provider cpu|tpu`` boots the in-tree JAX engine inside every worker
— the TPU-VM deployment story, where each host serves its agents from
its local chips). Tasks fan out over the wire; results, heartbeats and
load stats flow back.

``--kill-one`` SIGKILLs a worker mid-run to demonstrate the BASELINE
config #5 behavior: its in-flight tasks fail into Serve's retry path and
complete on the surviving workers.

No reference counterpart — the reference declared networking intent it
never implemented (websockets dep, ``pilott/pyproject.toml:19``).
"""

from __future__ import annotations

import argparse
import asyncio
import signal
import subprocess
import sys
import tempfile
import textwrap
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

WORKER_SCRIPT = textwrap.dedent(
    """
    import asyncio, os, sys
    PROVIDER_ENV = {provider!r}
    if PROVIDER_ENV != "tpu":  # tpu workers must keep the real backend
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, {repo!r})
    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from pilottai_tpu.core.agent import BaseAgent
    from pilottai_tpu.core.config import AgentConfig, LLMConfig, SamplingConfig
    from pilottai_tpu.distributed import AgentWorker
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.mock import MockBackend

    PROVIDER = {provider!r}
    WORKER_IX = {ix}

    def make_llm():
        if PROVIDER == "mock":
            # A little latency so tasks overlap and routing/load stats
            # are visible in the demo.
            return LLMHandler(
                LLMConfig(provider="mock"), backend=MockBackend(latency=0.3)
            )
        return LLMHandler(LLMConfig(
            model_name="llama-tiny", provider=PROVIDER, engine_slots=4,
            engine_max_seq=256, engine_chunk=4,
            dtype="float32" if PROVIDER == "cpu" else "bfloat16",
            sampling=SamplingConfig(max_new_tokens=32, temperature=0.0),
        ))

    async def main():
        agents = [
            BaseAgent(
                config=AgentConfig(role=f"worker{{WORKER_IX}}-agent{{i}}"),
                llm=make_llm(),
            )
            for i in range(2)
        ]
        w = AgentWorker("127.0.0.1", {port}, agents, heartbeat_interval=0.5)
        await w.start()
        print(f"worker {{WORKER_IX}} up with {{len(agents)}} agents", flush=True)
        await w.run_until_stopped()

    asyncio.run(main())
    """
)


async def run(n_workers: int, provider: str, kill_one: bool) -> None:
    from pilottai_tpu.core.config import LLMConfig, ServeConfig
    from pilottai_tpu.distributed import ServeEndpoint
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.mock import MockBackend
    from pilottai_tpu.serve import Serve

    serve = Serve(
        name="swarm",
        manager_llm=LLMHandler(LLMConfig(provider="mock"), backend=MockBackend()),
        config=ServeConfig(
            decomposition_enabled=False, fault_tolerance_enabled=True,
            max_retry_attempts=3,
        ),
    )
    await serve.start()
    endpoint = ServeEndpoint(serve)
    await endpoint.start()
    print(f"orchestrator listening on 127.0.0.1:{endpoint.port}")

    repo = str(Path(__file__).resolve().parents[2])
    procs = []
    tmp = Path(tempfile.mkdtemp())
    for ix in range(n_workers):
        script = tmp / f"worker{ix}.py"
        script.write_text(WORKER_SCRIPT.format(
            repo=repo, port=endpoint.port, provider=provider, ix=ix,
        ))
        procs.append(subprocess.Popen([sys.executable, str(script)]))

    try:
        want = n_workers * 2
        deadline = time.time() + 300
        while len(serve.agents) < want and time.time() < deadline:
            await asyncio.sleep(0.2)
        print(f"registered {len(serve.agents)}/{want} remote agents")

        tasks = [
            await serve.add_task(f"analyze shard {i} of the quarterly data")
            for i in range(3 * want)
        ]
        if kill_one and procs:
            await asyncio.sleep(0.5)
            print("SIGKILLing worker 0 mid-run …")
            procs[0].send_signal(signal.SIGKILL)

        results = await asyncio.gather(
            *[serve.wait_for(t.id, timeout=300) for t in tasks]
        )
        ok = sum(r.success for r in results)
        agents_used = sorted({t.agent_id[:8] for t in tasks if t.agent_id})
        print(f"{ok}/{len(results)} tasks completed")
        print(f"executed across agents: {agents_used}")
        m = serve.get_metrics()
        print("orchestrator metrics:", {
            k: m[k] for k in ("tasks_completed", "tasks_failed", "tasks_retried")
        })
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        await endpoint.stop()
        await serve.stop()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--provider", default="mock", choices=["mock", "cpu", "tpu"])
    ap.add_argument("--kill-one", action="store_true")
    args = ap.parse_args()
    asyncio.run(run(args.workers, args.provider, args.kill_one))


if __name__ == "__main__":
    main()
