"""Serving-endpoint demo: the framework as an OpenAI-compatible provider.

Starts an ``APIServer`` over the in-tree engine (mock provider by
default so it runs anywhere; ``--provider tpu`` for the real chip), then
drives it the way an external client would — plain HTTP, no SDK:

1. a chat completion (``POST /v1/chat/completions``),
2. the same request streamed over SSE,
3. an orchestrator task with its live lifecycle feed
   (``POST /v1/tasks {"stream": true}``).

Run::

    python examples/serving_endpoint/main.py
    python examples/serving_endpoint/main.py --provider tpu --model llama3-1b-byte
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from pilottai_tpu.core.agent import BaseAgent          # noqa: E402
from pilottai_tpu.core.config import (                 # noqa: E402
    AgentConfig,
    LLMConfig,
    ServeConfig,
)
from pilottai_tpu.engine.handler import LLMHandler     # noqa: E402
from pilottai_tpu.serve import Serve                   # noqa: E402
from pilottai_tpu.server import APIServer              # noqa: E402


async def _http(port: int, method: str, path: str, body: dict | None = None):
    """Tiny HTTP/1.1 client (what any non-Python consumer would do)."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    payload = json.dumps(body).encode() if body is not None else b""
    writer.write(
        f"{method} {path} HTTP/1.1\r\nHost: demo\r\n"
        f"Content-Length: {len(payload)}\r\nConnection: close\r\n\r\n".encode()
        + payload
    )
    await writer.drain()
    raw = await reader.read()
    writer.close()
    await writer.wait_closed()
    head, _, data = raw.partition(b"\r\n\r\n")
    return int(head.split(b" ")[1]), data


def _sse_events(body: bytes):
    events = [
        line[len("data: "):]
        for line in body.decode().split("\n")
        if line.startswith("data: ")
    ]
    # Mid-stream failures arrive in-band (the 200 status line is already
    # on the wire) as {"error": {"message", "type"}} events — surface the
    # server's message instead of a KeyError. task.result events carry a
    # plain "error" string/null field; only the dict form is the error
    # event.
    for e in events:
        if e == "[DONE]":
            continue
        parsed = json.loads(e)
        if isinstance(parsed.get("error"), dict):
            raise RuntimeError(f"server stream error: {parsed['error']}")
    return events


async def main(provider: str, model: str) -> int:
    llm = LLMHandler(LLMConfig(
        model_name=model, provider=provider,
        engine_slots=4, engine_max_seq=512,
        **({"quantize": "int8", "dtype": "bfloat16"}
           if provider == "tpu" else {}),
    ))
    agents = [
        BaseAgent(
            config=AgentConfig(role=f"worker{i}", specializations=["generic"],
                               max_iterations=2),
            llm=llm,
        )
        for i in range(2)
    ]
    serve = Serve(name="endpoint-demo", agents=agents, manager_llm=llm,
                  config=ServeConfig(decomposition_enabled=False))
    server = None
    try:
        await serve.start()
        server = await APIServer(llm, serve=serve).start()
        print(f"endpoint up on http://127.0.0.1:{server.port}/v1\n")
        # 1. Plain chat completion.
        status, body = await _http(server.port, "POST", "/v1/chat/completions", {
            "messages": [{"role": "user",
                          "content": "Summarize the quarterly report."}],
            "max_tokens": 48, "temperature": 0,
        })
        assert status == 200, body
        msg = json.loads(body)["choices"][0]["message"]["content"]
        print(f"chat completion  -> {msg[:80]!r}")

        # 2. The same, streamed: deltas arrive as each fused decode chunk
        # folds on the host.
        status, body = await _http(server.port, "POST", "/v1/chat/completions", {
            "messages": [{"role": "user",
                          "content": "Summarize the quarterly report."}],
            "max_tokens": 48, "temperature": 0, "stream": True,
        })
        assert status == 200, body
        events = _sse_events(body)
        assert events[-1] == "[DONE]"
        deltas = [
            json.loads(e)["choices"][0]["delta"].get("content", "")
            for e in events[:-1]
        ]
        print(f"SSE stream       -> {len(events) - 1} chunks, "
              f"{sum(len(d) for d in deltas)} chars")

        # 3. An orchestrator task with its live lifecycle.
        status, body = await _http(server.port, "POST", "/v1/tasks", {
            "task": "check inventory levels for warehouse 7",
            "stream": True,
        })
        assert status == 200, body
        events = [json.loads(e) for e in _sse_events(body)[:-1]]
        lifecycle = [e["event"] for e in events if "event" in e]
        result = events[-1]
        print(f"task lifecycle   -> {' → '.join(lifecycle)}")
        print(f"task result      -> success={result['success']} "
              f"output={str(result['output'])[:60]!r}")
        return 0
    finally:
        if server is not None:
            await server.stop()
        await serve.stop()
        await llm.stop()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--provider", default="mock",
                    choices=["mock", "cpu", "tpu"])
    ap.add_argument("--model", default="llama3-1b-byte")
    args = ap.parse_args()
    sys.exit(asyncio.run(main(args.provider, args.model)))
