"""Run the document pipeline demo.

    python examples/document_pipeline/main.py [--provider mock|cpu|tpu]
                                              [--embedder] [path] [question]

Reference counterpart: ``docs/examples/pdf_processing/main.py:79``
(``process_pdf``) — the only end-to-end workload the reference ships.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from examples.document_pipeline.pipeline import SAMPLE_DOC, run_pipeline  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("path", nargs="?", default=str(SAMPLE_DOC))
    ap.add_argument(
        "question", nargs="?",
        default="What are the key findings and the main risk?",
    )
    ap.add_argument("--provider", default="mock", choices=["mock", "cpu", "tpu"])
    ap.add_argument(
        "--embedder", action="store_true",
        help="attach the on-device embedding encoder to semantic memory",
    )
    args = ap.parse_args()

    out = asyncio.run(
        run_pipeline(
            path=args.path, question=args.question,
            provider=args.provider, use_embedder=args.embedder,
        )
    )
    print(json.dumps(out, indent=2, default=str))


if __name__ == "__main__":
    main()
