"""Flagship demo: the hierarchical document-processing pipeline.

This is the TPU-native counterpart of the reference's only end-to-end
workload (``/root/reference/docs/examples/pdf_processing/main.py:21-104``,
``example_agents.py:29-416``): a manager agent coordinating
extract -> evaluate -> summarize workers over a document, with the
extracted sections stored in semantic memory and the summary grounded in
a memory search. It is also BASELINE config #3's ``complex_workflow``
([extract, analyze, summarize]).

Differences from the reference, by design:

* the reference manager busy-polls child task dicts every 100 ms
  (``example_agents.py:85-102``); here the three stages are Tasks with
  real dependencies and the orchestrator schedules them — the manager
  agent participates through its ``select_agent`` hook instead;
* the reference's semantic search is substring matching
  (``enhanced_memory.py:110``); here it's an on-device embedding top-k
  (``pilottai_tpu/memory/semantic.py``) when an embedder is attached;
* all LLM calls run through the in-tree engine (mock/cpu/tpu providers) —
  zero external API calls.

Run it:  ``python examples/document_pipeline/main.py``            (mock)
         ``python examples/document_pipeline/main.py --provider tpu``
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from pilottai_tpu.core.agent import BaseAgent
from pilottai_tpu.core.config import AgentConfig, LLMConfig, ServeConfig
from pilottai_tpu.core.task import Task
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.mock import MockBackend
from pilottai_tpu.memory.semantic import EnhancedMemory
from pilottai_tpu.serve import Serve
from pilottai_tpu.tools.tool import Tool

SAMPLE_DOC = Path(__file__).parent / "sample_report.md"


# --------------------------------------------------------------------- #
# Tools (reference: PDFExtractorTool, ``pdf_extractor.py:7-40`` — here
# markdown/text-native, with sections as the unit of memory storage)
# --------------------------------------------------------------------- #

def read_document(path: str) -> str:
    """Plain text/markdown read; PDFs supported when pypdf is available."""
    p = Path(path)
    if p.suffix.lower() == ".pdf":
        try:
            from pypdf import PdfReader  # optional; not a framework dep
        except ImportError as exc:
            raise RuntimeError(
                "PDF input needs pypdf, which is not installed; "
                "use a .md/.txt document"
            ) from exc
        return "\n".join(page.extract_text() or "" for page in PdfReader(p).pages)
    return p.read_text(encoding="utf-8")


def split_sections(text: str) -> List[Tuple[str, str]]:
    """(heading, body) pairs from markdown ``##`` headings; one section
    for heading-less documents."""
    parts = re.split(r"^##\s+(.+)$", text, flags=re.MULTILINE)
    if len(parts) == 1:
        return [("document", text.strip())]
    out = []
    for i in range(1, len(parts), 2):
        body = parts[i + 1].strip() if i + 1 < len(parts) else ""
        out.append((parts[i].strip(), body))
    return out


def make_tools(
    memory: EnhancedMemory,
    default_path: Optional[str] = None,
) -> Dict[str, Tool]:
    """The worker toolset, closed over the shared semantic memory.

    ``default_path`` binds the pipeline's own document: a model that
    invokes a stage tool with bare ``{}`` arguments (the protocol
    model's trained shape) still acts on the right document — the
    binding lives in the pipeline wiring, not in fragile prompt
    echoing."""

    async def extract_sections(path: Optional[str] = None) -> Dict[str, Any]:
        path = path or default_path or str(SAMPLE_DOC)
        text = read_document(path)
        sections = split_sections(text)
        for heading, body in sections:
            await memory.store_semantic(
                f"{heading}: {body}",
                data={"heading": heading, "source": str(path)},
                tags={"extract", "section"},
            )
        return {
            "sections": len(sections),
            "characters": len(text),
            "headings": [h for h, _ in sections],
        }

    async def validate_extraction(min_sections: int = 1) -> Dict[str, Any]:
        stored = await memory.keyword_search("", tags={"extract"}, limit=100)
        issues = []
        if len(stored) < min_sections:
            issues.append(f"only {len(stored)} stored sections")
        for item in stored:
            if len(item["text"].strip()) < 20:
                issues.append(
                    f"section {(item['data'] or {}).get('heading')!r} is empty-ish"
                )
        return {"valid": not issues, "sections": len(stored), "issues": issues}

    # Memory search needs no hand-built tool anymore: agents constructed
    # with ``memory=`` auto-register a ``memory_search`` tool and get
    # retrieved context in step planning (core/agent.py, VERDICT r4 #5) —
    # the per-keyword fallback this example used to hand-roll now lives in
    # EnhancedMemory's no-embedder path.
    return {
        "extract_sections": Tool(
            name="extract_sections",
            function=extract_sections,
            description="Read a document and store its sections in memory",
            parameters={"properties": {"path": {"type": "string"}}},
        ),
        "validate_extraction": Tool(
            name="validate_extraction",
            function=validate_extraction,
            description="Structurally validate the extracted sections in memory",
            parameters={"properties": {"min_sections": {"type": "integer"}}},
        ),
    }


# --------------------------------------------------------------------- #
# Mock scripting: drive the same plan/act protocol a real model follows
# (the default mock never calls tools; the demo must exercise them)
# --------------------------------------------------------------------- #

def _pipeline_responder(prompt: str) -> Optional[Dict[str, Any]]:
    """step_planning responses that actually invoke the stage's tool once,
    then declare completion — the deterministic analogue of what the
    JSON-constrained real model produces."""
    if '"task_complete"' not in prompt:
        return None
    acted = "step 0:" in prompt  # history line present -> tool already ran
    m = re.search(r"Payload: ({.*})", prompt)
    payload: Dict[str, Any] = {}
    if m:
        try:
            payload = json.loads(m.group(1).replace("'", '"'))
        except json.JSONDecodeError:
            payload = {}
    if "Type: extract" in prompt and not acted:
        return {
            "task_complete": False, "action": "extract_sections",
            "arguments": {"path": payload.get("path", str(SAMPLE_DOC))},
            "reasoning": "extract first",
        }
    if "Type: evaluate" in prompt and not acted:
        return {
            "task_complete": False, "action": "validate_extraction",
            "arguments": {"min_sections": 2}, "reasoning": "validate next",
        }
    if "Type: summarize" in prompt and not acted:
        return {
            "task_complete": False, "action": "memory_search",
            "arguments": {"query": payload.get("question", "key findings, risks")},
            "reasoning": "ground the summary in memory",
        }
    if acted:
        # No "output" key: the agent then keeps the tool result as the
        # stage output (core/agent.py step loop), which is the artifact.
        return {
            "task_complete": True, "action": "respond", "arguments": {},
            "reasoning": "tool produced the stage artifact",
        }
    return None


# --------------------------------------------------------------------- #
# Pipeline assembly (reference ``main.py:21-74`` setup_pipeline)
# --------------------------------------------------------------------- #

def _handler(provider: str) -> LLMHandler:
    if provider == "mock":
        return LLMHandler(
            LLMConfig(provider="mock"),
            backend=MockBackend(responders=[_pipeline_responder]),
        )
    # Real engines serve the in-tree-trained protocol model (greedy,
    # grammar-constrained): the agents' decisions come from real decoded
    # tokens AND the tasks actually succeed (train/protocol.py).
    from pilottai_tpu.core.config import SamplingConfig
    from pilottai_tpu.train.protocol import (
        DEFAULT_CHECKPOINT,
        SERVE_MAX_NEW,
        SERVE_MAX_SEQ,
        has_checkpoint,
    )

    ckpt = DEFAULT_CHECKPOINT
    has_ckpt = has_checkpoint(ckpt)
    return LLMHandler(
        LLMConfig(
            model_name="protocol-s",
            provider=provider,
            checkpoint_path=str(ckpt) if has_ckpt else None,
            engine_slots=8,
            engine_max_seq=SERVE_MAX_SEQ,
            engine_chunk=24,
            engine_speculate=4,
            dtype="bfloat16" if provider == "tpu" else "float32",
            sampling=SamplingConfig(
                temperature=0.0, max_new_tokens=SERVE_MAX_NEW
            ),
        )
    )


def build_pipeline(
    provider: str = "mock",
    use_embedder: bool = False,
    doc_path: Optional[str | Path] = None,
) -> Tuple[Serve, EnhancedMemory]:
    """Manager + extractor/evaluator/generator hierarchy over one Serve.

    ``doc_path`` binds the stage tools' default document — a run over a
    user document must never silently fall back to the bundled sample
    when the model invokes a tool with bare arguments."""
    embedder = None
    if use_embedder:
        from pilottai_tpu.memory.embedder import Embedder

        embedder = Embedder(model_name="llama-tiny")
    memory = EnhancedMemory(embedder=embedder)
    tools = make_tools(memory, default_path=str(doc_path or SAMPLE_DOC))
    llm = _handler(provider)

    extractor = BaseAgent(
        config=AgentConfig(
            role="extractor", goal="extract document content into memory",
            specializations=["extract"],
        ),
        llm=llm, tools=[tools["extract_sections"]], memory=memory,
    )
    evaluator = BaseAgent(
        config=AgentConfig(
            role="evaluator", goal="validate extraction quality",
            specializations=["evaluate"],
        ),
        llm=llm, tools=[tools["validate_extraction"]], memory=memory,
    )
    generator = BaseAgent(
        config=AgentConfig(
            role="generator", goal="produce grounded summaries",
            specializations=["summarize"],
        ),
        llm=llm, memory=memory,  # memory_search auto-registers
    )
    manager = BaseAgent(
        config=AgentConfig(
            role="manager", goal="coordinate the document pipeline",
            role_type="manager",
        ),
        llm=llm,
    )
    for worker in (extractor, evaluator, generator):
        manager.add_child_agent(worker)

    serve = Serve(
        name="document-pipeline",
        agents=[extractor, evaluator, generator],
        manager_agent=manager,
        manager_llm=llm,
        config=ServeConfig(
            decomposition_enabled=False,  # the stage graph is explicit below
            evaluation_enabled=False,
            max_concurrent_tasks=4,
        ),
    )
    return serve, memory


def stage_tasks(path: str, question: str) -> List[Task]:
    """The explicit extract -> evaluate -> summarize dependency chain
    (BASELINE config #3's workflow)."""
    extract = Task(
        description=f"Extract every section of {path} into semantic memory",
        type="extract", tools=["extract_sections"], payload={"path": str(path)},
    )
    evaluate = Task(
        description="Validate the extracted sections are complete and non-empty",
        type="evaluate", tools=["validate_extraction"],
        dependencies=[extract.id],
    )
    summarize = Task(
        description=f"Answer from the extracted document: {question}",
        type="summarize", tools=["memory_search"],
        dependencies=[evaluate.id], payload={"question": question},
    )
    return [extract, evaluate, summarize]


async def run_pipeline(
    path: str | Path = SAMPLE_DOC,
    question: str = "What are the key findings and the main risk?",
    provider: str = "mock",
    use_embedder: bool = False,
) -> Dict[str, Any]:
    """End-to-end run; returns the stage results and final answer."""
    serve, memory = build_pipeline(
        provider=provider, use_embedder=use_embedder, doc_path=path
    )
    await serve.start()
    try:
        tasks = stage_tasks(str(path), question)
        results = await serve.execute(list(tasks))
        grounding = await memory.semantic_search(question, limit=3, tags={"extract"})
        if not grounding:
            grounding = await memory.keyword_search("risk", tags={"extract"}, limit=3)
        return {
            "stages": {
                t.type: {"success": r.success, "output": r.output}
                for t, r in zip(tasks, results)
            },
            "answer": results[-1].output,
            "grounding": [g["text"][:120] for g in grounding],
            "memory_items": memory.get_metrics()["semantic_items"],
            "serve_metrics": dict(serve.metrics),
        }
    finally:
        await serve.stop()
