"""Byte-level JSON grammar masking for constrained decoding.

SURVEY.md §7 hard part #3: the agent protocol (prompts/rules.yaml) is
strict JSON, so *well-formed-by-construction* output beats retry-parse
loops. With the byte tokenizer the grammar automaton is a pushdown
machine over single bytes: a ~30-state DFA for the token structure plus
a container stack (one bit per nesting level — object vs array) packed
into an int32.

Everything is table-driven so the per-step device work is three gathers:

* ``ALLOWED[state, top]``      -> [256] byte validity mask
* ``NEXT[state, top, byte]``   -> next state
* ``DDEPTH[state, top, byte]`` -> stack push(+1)/pop(-1)

and the masking/advance run *inside* the jitted decode chunk
(``engine/decode.py``) — no host round trip per token, which is the whole
point on a ~100 ms-RTT remote-TPU link.

Guarantees (for byte tokenizers): the generated prefix is always a
prefix of a valid JSON document whose top level is an object or array;
when the document closes, only EOS (or padding spaces) can follow.
Strings are restricted to printable ASCII with standard single-char
escapes (no \\uXXXX), which also guarantees valid UTF-8. Budget
exhaustion mid-document is the one unavoidable failure mode — callers
pick adequate ``max_new_tokens``.

Subword tokenizers (every real checkpoint's vocab) run the **token→byte
product construction** (VERDICT r2 next-step 5): each vocab entry's byte
string is precomputed host-side (``token_byte_table``), and the mask step
simulates the byte automaton over every candidate token's whole byte
path — a token is legal iff every byte stays legal. Budget feasibility
(``remaining - 1 >= FINISH_COST[final] + depth``) replaces the byte
path's forced-closure margin: single-byte tokens always exist in real
vocabs (byte-level BPE bases / SentencePiece byte fallback), so a
feasible document can always be closed one byte per token.

No reference counterpart: the reference hopes the remote API returns
parseable JSON and retries (``pilott/pilott.py:603-639``).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

# ------------------------------ states ------------------------------- #

(
    S_START,      # top level: '{' or '[' (or space)
    S_OBJ_OPEN,   # after '{': key string or '}'
    S_KEY,        # inside a key string
    S_KEY_ESC,    # after '\' in a key
    S_COLON,      # after key: ':'
    S_VALUE,      # expecting a value (after ':' or array ',')
    S_ARR,        # after '[': value or immediate ']'
    S_STR,        # inside a value string
    S_STR_ESC,    # after '\' in a value string
    S_NUM_NEG,    # after '-': first digit
    S_NUM_ZERO,   # after a leading 0: no more int digits (strict JSON)
    S_NUM_INT,    # in 1-9... integer digits
    S_NUM_DOT,    # after '.': first fraction digit
    S_NUM_FRAC,   # fraction digits
    S_NUM_ESGN,   # after e/E: sign or digit
    S_NUM_EDIG,   # after exponent sign: first digit
    S_NUM_EXP,    # exponent digits
    S_AFTER,      # after a complete value: ',' or the container's closer
    S_COMMA_OBJ,  # after ',' inside an object: next key string
    S_T1, S_T2, S_T3,          # t-rue
    S_F1, S_F2, S_F3, S_F4,    # f-alse
    S_N1, S_N2, S_N3,          # n-ull
    S_DONE,       # document closed: EOS (or padding space)
) = range(30)

N_STATES = 30
MAX_DEPTH = 30  # stack bits in an int32, with headroom

_DIGITS = [ord(c) for c in "0123456789"]
_PRINTABLE = [b for b in range(0x20, 0x7F)]  # valid-UTF-8 by construction
_ESCAPES = [ord(c) for c in '"\\/bfnrt']
# No whitespace transitions: under arbitrary (e.g. random-weight) logits a
# ws self-loop can dominate forever and emit nothing but spaces. Compact
# JSON is equally valid and always makes progress. The one exception is
# S_DONE, which pads with spaces only when the slot has no EOS token.
_WS: list = []

TOP_OBJ, TOP_ARR = 0, 1


def _build_tables() -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    allowed = np.zeros((N_STATES, 2, 256), np.bool_)
    nxt = np.zeros((N_STATES, 2, 256), np.int8)
    ddepth = np.zeros((N_STATES, 2, 256), np.int8)

    def rule(state, byte, to, tops=(TOP_OBJ, TOP_ARR), dd=0):
        for top in tops:
            allowed[state, top, byte] = True
            nxt[state, top, byte] = to
            ddepth[state, top, byte] = dd

    def ws_self(state):
        for b in _WS:
            rule(state, b, state)

    # Value starters shared by S_VALUE and S_ARR.
    def value_starts(state):
        rule(state, ord('"'), S_STR)
        rule(state, ord("{"), S_OBJ_OPEN, dd=+1)
        rule(state, ord("["), S_ARR, dd=+1)
        rule(state, ord("-"), S_NUM_NEG)
        rule(state, ord("0"), S_NUM_ZERO)
        for d in _DIGITS[1:]:
            rule(state, d, S_NUM_INT)
        rule(state, ord("t"), S_T1)
        rule(state, ord("f"), S_F1)
        rule(state, ord("n"), S_N1)
        ws_self(state)

    # A value just ended; ',' and closers depend on the container. `dd`
    # for closers is applied before computing the post-pop state, which
    # the advance step resolves via the depth (see json_advance).
    def value_end(state):
        rule(state, ord(","), S_COMMA_OBJ, tops=(TOP_OBJ,))
        rule(state, ord(","), S_VALUE, tops=(TOP_ARR,))
        rule(state, ord("}"), S_AFTER, tops=(TOP_OBJ,), dd=-1)
        rule(state, ord("]"), S_AFTER, tops=(TOP_ARR,), dd=-1)
        ws_self_to_after(state)

    def ws_self_to_after(state):
        for b in _WS:
            rule(state, b, S_AFTER)

    rule(S_START, ord("{"), S_OBJ_OPEN, dd=+1)
    rule(S_START, ord("["), S_ARR, dd=+1)
    ws_self(S_START)

    rule(S_OBJ_OPEN, ord('"'), S_KEY)
    rule(S_OBJ_OPEN, ord("}"), S_AFTER, dd=-1)
    ws_self(S_OBJ_OPEN)

    for b in _PRINTABLE:
        rule(S_KEY, b, S_KEY)
        rule(S_STR, b, S_STR)
    rule(S_KEY, ord("\\"), S_KEY_ESC)
    rule(S_KEY, ord('"'), S_COLON)
    rule(S_STR, ord("\\"), S_STR_ESC)
    rule(S_STR, ord('"'), S_AFTER)
    for b in _ESCAPES:
        rule(S_KEY_ESC, b, S_KEY)
        rule(S_STR_ESC, b, S_STR)

    rule(S_COLON, ord(":"), S_VALUE)
    ws_self(S_COLON)

    value_starts(S_VALUE)
    value_starts(S_ARR)
    rule(S_ARR, ord("]"), S_AFTER, tops=(TOP_ARR,), dd=-1)

    rule(S_NUM_NEG, ord("0"), S_NUM_ZERO)
    for d in _DIGITS[1:]:
        rule(S_NUM_NEG, d, S_NUM_INT)
    for st in (S_NUM_ZERO, S_NUM_INT):
        rule(st, ord("."), S_NUM_DOT)
        rule(st, ord("e"), S_NUM_ESGN)
        rule(st, ord("E"), S_NUM_ESGN)
        value_end(st)
    for d in _DIGITS:
        rule(S_NUM_INT, d, S_NUM_INT)
        rule(S_NUM_DOT, d, S_NUM_FRAC)
        rule(S_NUM_FRAC, d, S_NUM_FRAC)
        rule(S_NUM_ESGN, d, S_NUM_EXP)
        rule(S_NUM_EDIG, d, S_NUM_EXP)
        rule(S_NUM_EXP, d, S_NUM_EXP)
    rule(S_NUM_ESGN, ord("+"), S_NUM_EDIG)
    rule(S_NUM_ESGN, ord("-"), S_NUM_EDIG)
    rule(S_NUM_FRAC, ord("e"), S_NUM_ESGN)
    rule(S_NUM_FRAC, ord("E"), S_NUM_ESGN)
    value_end(S_NUM_FRAC)
    value_end(S_NUM_EXP)  # no second exponent: e/E not re-allowed here

    value_end(S_AFTER)
    for b in _WS:
        rule(S_AFTER, b, S_AFTER)

    rule(S_COMMA_OBJ, ord('"'), S_KEY)
    ws_self(S_COMMA_OBJ)

    for chain, word in ((S_T1, "true"), (S_F1, "false"), (S_N1, "null")):
        states = {
            S_T1: [S_T1, S_T2, S_T3, S_AFTER],
            S_F1: [S_F1, S_F2, S_F3, S_F4, S_AFTER],
            S_N1: [S_N1, S_N2, S_N3, S_AFTER],
        }[chain]
        for i, ch in enumerate(word[1:]):
            rule(states[i], ord(ch), states[i + 1])

    for b in [ord(" ")]:
        allowed[S_DONE, :, b] = True
        nxt[S_DONE, :, b] = S_DONE  # harmless padding when EOS is disabled

    return allowed, nxt, ddepth


ALLOWED_NP, NEXT_NP, DDEPTH_NP = _build_tables()
_OPENERS_NP = np.zeros((256,), np.bool_)
_OPENERS_NP[[ord("{"), ord("[")]] = True

# ---------------------- budget-aware forced closure -------------------- #
# With degenerate logits (random weights) a self-loop state — digits, or
# string content — can dominate until the budget runs out mid-document.
# When the remaining budget approaches the shortest path to a closed
# document, the mask collapses to that path's single next byte.
#
# FINISH_COST[state]: bytes needed to reach a closer-capable state (where
# the current container's closer is legal). The shortest full close is
# FINISH_COST[state] + depth closers.
# FORCE_BYTE[state, top]: the byte that walks that shortest path.

FINISH_COST_NP = np.zeros((N_STATES,), np.int32)
FORCE_BYTE_NP = np.zeros((N_STATES, 2), np.int32)
_CLOSER = {TOP_OBJ: ord("}"), TOP_ARR: ord("]")}


def _init_force_tables() -> None:
    cost = {
        S_START: 1,         # '{' then an empty object closes
        S_OBJ_OPEN: 0, S_ARR: 0, S_AFTER: 0,
        S_NUM_ZERO: 0, S_NUM_INT: 0, S_NUM_FRAC: 0, S_NUM_EXP: 0,
        S_STR: 1, S_STR_ESC: 2, S_KEY: 3, S_KEY_ESC: 4,
        S_COLON: 2, S_VALUE: 1, S_COMMA_OBJ: 4,
        S_NUM_NEG: 1, S_NUM_DOT: 1, S_NUM_ESGN: 1, S_NUM_EDIG: 1,
        S_T1: 3, S_T2: 2, S_T3: 1,
        S_F1: 4, S_F2: 3, S_F3: 2, S_F4: 1,
        S_N1: 3, S_N2: 2, S_N3: 1,
        S_DONE: 0,
    }
    force = {
        S_START: ord("{"),
        S_OBJ_OPEN: ord("}"), S_ARR: ord("]"),
        S_STR: ord('"'), S_KEY: ord('"'), S_COLON: ord(":"),
        S_STR_ESC: ord("n"), S_KEY_ESC: ord("n"),
        S_VALUE: ord("0"), S_NUM_NEG: ord("0"), S_NUM_DOT: ord("0"),
        S_NUM_ESGN: ord("0"), S_NUM_EDIG: ord("0"),
        S_COMMA_OBJ: ord('"'),
        S_T1: ord("r"), S_T2: ord("u"), S_T3: ord("e"),
        S_F1: ord("a"), S_F2: ord("l"), S_F3: ord("s"), S_F4: ord("e"),
        S_N1: ord("u"), S_N2: ord("l"), S_N3: ord("l"),
        S_DONE: ord(" "),
    }
    for state in range(N_STATES):
        FINISH_COST_NP[state] = cost[state]
        for top in (TOP_OBJ, TOP_ARR):
            # Closer-capable states emit their container's closer; others
            # walk toward one.
            FORCE_BYTE_NP[state, top] = force.get(state, _CLOSER[top])
    # Sanity: every forced byte must be legal in its (reachable) state —
    # S_OBJ_OPEN always has an object on top and S_ARR an array, so the
    # crossed combinations never occur.
    unreachable = {(S_OBJ_OPEN, TOP_ARR), (S_ARR, TOP_OBJ)}
    for state in range(N_STATES):
        for top in (TOP_OBJ, TOP_ARR):
            if state == S_DONE or (state, top) in unreachable:
                continue
            b = FORCE_BYTE_NP[state, top]
            assert ALLOWED_NP[state, top, b], (state, top, b)


_init_force_tables()


def json_allowed_bytes(state, stack, depth, remaining=None):
    """[B] automaton coords -> [B, 256] allowed-byte mask (traced).

    ``remaining`` (tokens of budget left, [B]) enables forced closure:
    once it cannot cover the shortest path to a closed document plus a
    small margin, the mask collapses to that path's next byte.
    """
    import jax.numpy as jnp

    allowed = jnp.asarray(ALLOWED_NP)
    openers = jnp.asarray(_OPENERS_NP)
    top = jnp.where(depth > 0, (stack >> jnp.maximum(depth - 1, 0)) & 1, 0)
    mask = allowed[state, top]                        # [B, 256]
    # Depth cap: no new containers once the stack bits run out.
    mask = jnp.where(
        (depth >= MAX_DEPTH)[:, None] & openers[None, :], False, mask
    )
    if remaining is not None:
        # Margin 5 > the worst single-step FINISH_COST jump (+4, e.g.
        # S_AFTER --','--> S_COMMA_OBJ): while unforced, remaining - need
        # can shrink by at most 5 per step, so the invariant
        # remaining >= shortest-close is maintained and forcing always
        # closes the document in time.
        need = jnp.asarray(FINISH_COST_NP)[state] + depth + 5
        forced = jnp.asarray(FORCE_BYTE_NP)[state, top]
        onehot = jnp.arange(256)[None, :] == forced[:, None]
        mask = jnp.where((remaining <= need)[:, None], onehot, mask)
    return mask


def _byte_step(state, stack, depth, byte):
    """ONE byte's automaton transition (traced; shared by the byte path
    and the token→byte product so the semantics exist exactly once).
    Returns ``(legal, state', stack', depth')`` — callers decide what an
    illegal byte means (byte path: unreachable under the mask; token
    path: the whole token is masked out)."""
    import jax.numpy as jnp

    allowed = jnp.asarray(ALLOWED_NP)
    nxt = jnp.asarray(NEXT_NP)
    dd = jnp.asarray(DDEPTH_NP)
    openers = jnp.asarray(_OPENERS_NP)
    top = jnp.where(depth > 0, (stack >> jnp.maximum(depth - 1, 0)) & 1, 0)
    legal = allowed[state, top, byte] & ~(
        (depth >= MAX_DEPTH) & openers[byte]
    )
    ns = nxt[state, top, byte].astype(jnp.int32)
    delta = dd[state, top, byte].astype(jnp.int32)
    push_type = (byte == ord("[")).astype(jnp.int32)
    new_stack = jnp.where(delta > 0, stack | (push_type << depth), stack)
    new_depth = depth + delta
    # A pop that empties the stack closes the document.
    ns = jnp.where((delta < 0) & (new_depth <= 0), S_DONE, ns)
    return legal, ns, new_stack, jnp.maximum(new_depth, 0)


def json_advance(state, stack, depth, token):
    """Advance per-slot automaton coords by one sampled token (traced).
    Non-byte tokens (EOS/pad/bos) leave the coords unchanged."""
    import jax.numpy as jnp

    byte = jnp.clip(token, 0, 255)
    is_byte = token < 256
    _, ns, new_stack, new_depth = _byte_step(state, stack, depth, byte)
    state = jnp.where(is_byte, ns, state)
    stack = jnp.where(is_byte, new_stack, stack)
    depth = jnp.where(is_byte, new_depth, depth)
    return state, stack, depth


# -------------------- subword (token→byte) product --------------------- #

MAX_TOKEN_BYTES = 16


def closure_byte_set():
    """The single bytes forced closure walks through (FORCE_BYTE plus the
    container closers). The feasibility induction in json_allowed_tokens
    assumes each exists as a single-byte token — token_byte_table
    validates that."""
    req = set(int(b) for b in FORCE_BYTE_NP.flatten())
    req.update((ord("}"), ord("]")))
    return req


def token_byte_table(
    tokenizer, max_bytes: int = MAX_TOKEN_BYTES, validate: bool = True
):
    """Host-side precompute: every vocab entry's byte string.

    Returns ``(token_bytes [V, max_bytes] uint8, token_len [V] int32)``.
    Entries with ``len == 0`` are never legal under the JSON mask:
    specials, tokens whose bytes can't be derived, and tokens longer than
    ``max_bytes`` (shorter alternatives always exist — real BPE vocabs
    contain all single-byte tokens, so excluding long tokens only costs a
    little compression inside strings, never expressiveness).

    The tokenizer must expose ``token_bytes(i) -> bytes | None``
    (``engine/tokenizer.py`` implements it for both in-tree tokenizers).
    """
    get = getattr(tokenizer, "token_bytes", None)
    if get is None:
        raise TypeError(
            f"{type(tokenizer).__name__} has no token_bytes(i); cannot "
            "build the JSON token mask table"
        )
    V = tokenizer.vocab_size
    tb = np.zeros((V, max_bytes), np.uint8)
    tl = np.zeros((V,), np.int32)
    for i in range(V):
        b = get(i)
        if not b or len(b) > max_bytes:
            continue
        tb[i, : len(b)] = np.frombuffer(b, np.uint8)
        tl[i] = len(b)
    if validate:
        # Without every closure byte as a single-byte token, the budget
        # feasibility induction breaks (a document could become
        # uncloseable) — refuse the table so the engine falls back to
        # unconstrained sampling instead of masking everything out.
        singles = {int(tb[i, 0]) for i in range(V) if tl[i] == 1}
        missing = closure_byte_set() - singles
        if missing:
            raise ValueError(
                "vocab lacks single-byte tokens for closure bytes "
                f"{sorted(chr(b) for b in missing)}; JSON token masking "
                "would not be able to guarantee document closure"
            )
    return tb, tl


def _sim_token_bytes(state, stack, depth, token_bytes, token_len):
    """Run the byte automaton over token byte strings (traced).

    ``state/stack/depth`` broadcast against the leading dims of
    ``token_bytes [..., L]`` / ``token_len [...]``. Returns
    ``(ok, state', stack', depth')`` — ``ok`` is False iff any byte of
    the token was illegal from its position on the path; coords stop
    advancing at the first illegal byte (their values are then only
    meaningful where ``ok``).
    """
    import jax.numpy as jnp

    L = token_bytes.shape[-1]
    s, st, d = state, stack, depth
    ok = token_len > 0
    # Static unroll over the (small) max token byte length: each step is
    # three tiny-table gathers + elementwise ops, fused by XLA.
    for l in range(L):
        b = token_bytes[..., l].astype(jnp.int32)
        active = l < token_len
        legal, ns, nst, nd = _byte_step(s, st, d, b)
        ok = ok & jnp.where(active, legal, True)
        adv = active & ok
        s = jnp.where(adv, ns, s)
        st = jnp.where(adv, nst, st)
        d = jnp.where(adv, nd, d)
    return ok, s, st, d


def json_allowed_tokens(
    state, stack, depth, token_bytes, token_len, remaining=None
):
    """[B] automaton coords × [V, L] vocab byte table -> [B, V] mask.

    A token is legal iff its whole byte path stays grammar-legal AND
    (with ``remaining``) the document can still close within budget
    afterwards — ``remaining - 1 >= FINISH_COST[state'] + depth'``.
    Single-byte force tokens reduce that bound by exactly 1 per step, so
    the feasibility invariant is self-maintaining: a legal token always
    exists until the document is closed.
    """
    import jax.numpy as jnp

    B = state.shape[0]
    V = token_bytes.shape[0]
    ok, s_f, _, d_f = _sim_token_bytes(
        state[:, None],
        stack[:, None],
        depth[:, None],
        token_bytes[None, :, :],
        token_len[None, :],
    )
    assert ok.shape == (B, V)
    if remaining is not None:
        need = jnp.asarray(FINISH_COST_NP)[s_f] + d_f
        ok = ok & ((remaining[:, None] - 1) >= need)
    return ok


def json_advance_tokens(state, stack, depth, tokens, token_bytes, token_len):
    """Advance per-slot coords over the SAMPLED token's byte string
    (traced). Zero-length entries (EOS/specials) leave coords unchanged."""
    tb = token_bytes[tokens]  # [B, L]
    tl = token_len[tokens]    # [B]
    _, s, st, d = _sim_token_bytes(state, stack, depth, tb, tl)
    return s, st, d
