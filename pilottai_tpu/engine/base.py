"""Backend protocol every provider implements.

Reference parity: the surface of ``LLMHandler`` the rest of the reference
calls — ``generate_response`` (``pilott/engine/llm.py:38``), ``apredict``
(:181), ``apredict_messages`` (:201) — distilled to one async ``generate``
primitive; the convenience forms live on the ``LLMHandler`` facade.
"""

from __future__ import annotations

import abc
from typing import Any, Dict, List, Optional, Sequence

from pilottai_tpu.engine.types import (
    ChatMessage,
    GenerationParams,
    LLMResponse,
    ToolSpec,
)


class LLMBackend(abc.ABC):
    """An in-tree inference provider."""

    name: str = "base"

    @abc.abstractmethod
    async def generate(
        self,
        messages: Sequence[ChatMessage],
        tools: Optional[Sequence[ToolSpec]] = None,
        params: Optional[GenerationParams] = None,
    ) -> LLMResponse:
        """Run one chat generation."""

    async def start(self) -> None:  # noqa: B027 - optional lifecycle hook
        """Bring up device resources (compile, load weights)."""

    async def stop(self) -> None:  # noqa: B027 - optional lifecycle hook
        """Release device resources."""

    def get_metrics(self) -> Dict[str, Any]:
        return {"backend": self.name}


def render_chat(messages: Sequence[ChatMessage]) -> str:
    """Canonical plain-text chat transcript used by providers without a
    model-specific chat template."""
    parts: List[str] = []
    for m in messages:
        parts.append(f"<|{m.role}|>\n{m.content}")
    parts.append("<|assistant|>\n")
    return "\n".join(parts)
