"""Backend protocol every provider implements.

Reference parity: the surface of ``LLMHandler`` the rest of the reference
calls — ``generate_response`` (``pilott/engine/llm.py:38``), ``apredict``
(:181), ``apredict_messages`` (:201) — distilled to one async ``generate``
primitive; the convenience forms live on the ``LLMHandler`` facade.
"""

from __future__ import annotations

import abc
from typing import Any, Dict, List, Optional, Sequence

from pilottai_tpu.engine.types import (
    ChatMessage,
    GenerationParams,
    LLMResponse,
    ToolCall,
    ToolSpec,
)
from pilottai_tpu.utils.json_utils import extract_json


class LLMBackend(abc.ABC):
    """An in-tree inference provider."""

    name: str = "base"

    @abc.abstractmethod
    async def generate(
        self,
        messages: Sequence[ChatMessage],
        tools: Optional[Sequence[ToolSpec]] = None,
        params: Optional[GenerationParams] = None,
    ) -> LLMResponse:
        """Run one chat generation."""

    async def generate_stream(
        self,
        messages: Sequence[ChatMessage],
        tools: Optional[Sequence[ToolSpec]] = None,
        params: Optional[GenerationParams] = None,
        info: Optional[Dict[str, Any]] = None,
    ):
        """Async generator of text deltas; concatenation equals the
        ``generate()`` content for the same request. Default adapter:
        one delta with the whole completion — backends with true
        incremental output (the native engine streams per fused decode
        chunk) override.

        ``info``, when a dict is passed, is filled in place before the
        generator finishes with end-of-stream facts a text stream can't
        carry: ``finish_reason`` ("stop" | "length" | ...) and
        ``completion_tokens``. SSE consumers report truncation from it
        (a stream that hit max_new_tokens must not claim "stop")."""
        response = await self.generate(messages, tools, params)
        if info is not None:
            info["finish_reason"] = response.finish_reason
            info["completion_tokens"] = response.usage.completion_tokens
        if response.content:
            yield response.content

    async def start(self) -> None:  # noqa: B027 - optional lifecycle hook
        """Bring up device resources (compile, load weights)."""

    async def stop(self) -> None:  # noqa: B027 - optional lifecycle hook
        """Release device resources."""

    def get_metrics(self) -> Dict[str, Any]:
        return {"backend": self.name}


def parse_tool_calls(content: str, tool_names: Sequence[str]) -> List[ToolCall]:
    """Extract structured tool invocations from a model reply.

    Two wire forms are honored (the same the mock backend emits and the
    reference's function-calling path consumed, ``pilott/engine/llm.py:
    91-104`` -> ``core/agent.py:331-338``):

    * ``{"tool_call": {"name": ..., "arguments": {...}}}``
    * the step-planning form ``{"action": <tool name>, "arguments": {...}}``
      when ``action`` names one of the offered tools.

    Malformed wire data (non-dict arguments, non-string name) degrades to
    "no tool call" — LLM output is untrusted and must never make
    ``generate()`` itself fail.
    """
    data = extract_json(content)
    if not isinstance(data, dict):
        return []

    def build(name: Any, arguments: Any) -> Optional[ToolCall]:
        if not isinstance(name, str) or not name:
            return None
        if not isinstance(arguments, dict):
            arguments = {}
        return ToolCall(id="tc-0", name=name, arguments=arguments)

    tc = data.get("tool_call")
    action = data.get("action")
    call: Optional[ToolCall] = None
    if isinstance(tc, dict):
        call = build(tc.get("name"), tc.get("arguments"))
    elif isinstance(action, str) and action in tool_names:
        call = build(action, data.get("arguments"))
    return [call] if call is not None else []


def render_chat(messages: Sequence[ChatMessage]) -> str:
    """Canonical plain-text chat transcript used by providers without a
    model-specific chat template."""
    parts: List[str] = []
    for m in messages:
        parts.append(f"<|{m.role}|>\n{m.content}")
    parts.append("<|assistant|>\n")
    return "\n".join(parts)


def tool_preamble(tools: Sequence[ToolSpec]) -> str:
    """The tool-availability header the engine injects for function
    calling. ONE definition shared by the serving path
    (``native.py:_build_request``) and the protocol-model training data
    (``train/protocol.py``) — the model is trained on byte-identical
    framing to what it will see at serve time."""
    tool_desc = "\n".join(f"- {t.name}: {t.description}" for t in tools)
    return (
        f"Available tools:\n{tool_desc}\n\n"
        'To invoke one, reply {"tool_call": {"name": ..., '
        '"arguments": {...}}} or {"action": <tool name>, '
        '"arguments": {...}}.'
    )


def render_generic_request(
    messages: Sequence[ChatMessage],
    tools: Optional[Sequence[ToolSpec]] = None,
) -> str:
    """Full request text on the generic (template-less) path: tool
    preamble + chat transcript. This is exactly what a byte-tokenizer
    engine encodes (modulo left-truncation to the KV budget)."""
    prompt = render_chat(messages)
    if tools:
        prompt = f"{tool_preamble(tools)}\n\n{prompt}"
    return prompt
