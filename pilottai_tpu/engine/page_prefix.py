"""Block-granular automatic prefix caching for the paged KV cache.

The dense prefix store (``engine/prefix_cache.py``) copies whole K/V
panels per cached prompt — capacity measured in a handful of entries,
prompts past its HBM cap never cached, hits pay a panel copy. The paged
pool makes all of that unnecessary: a prompt's K/V already lives in
pages, pages are immutable once the positions they cover are fully
inside the prompt (decode writes start at ``prompt_len``), and the block
table means *mapping* a page into a new slot is free. So cached
prefixes here are just refcounted pages organized in a radix tree keyed
on page-aligned token blocks:

* ``register`` (after any admission) pins the pages that are fully
  covered by the prompt — one radix node per page, keyed by
  (parent node, that block's token ids);
* ``match`` walks a new prompt's blocks down the tree and returns the
  deepest chain — those pages go straight into the new slot's block
  table (``PageAllocator.allocate(prefix_pages=...)``), and only the
  tail is prefilled (``engine/decode.py:admit_group_prefix_paged``);
* sharing is granular per page: two prompts agreeing on the first k
  blocks share exactly k pages, no LCP-derivation pass needed — the
  radix IS the common-prefix structure;
* eviction is LRU over leaf nodes, and admission pressure can reclaim
  cached pages on demand (``evict``), so caching can never starve
  admissions.

Matching is always a PROPER prefix (at least one tail token must remain
to produce the first generated token's logits), enforced by capping the
walk at ``(len(ids) - 1) // page_size`` blocks.

Closes VERDICT.md round-3 next-step 1 (with the paged paths in
``engine/decode.py``): speculation + prefix caching + paged KV compose.
No reference counterpart (the reference has no KV anything —
``pilott/engine/llm.py:59`` calls a remote API); the parity target is
radix/block prefix caching in production paged-KV LLM servers.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple


class PageNode:
    """One cached page: the block of tokens it covers and its chain."""

    __slots__ = ("tokens", "page", "parent", "children", "stamp",
                 "path_pages", "depth")

    def __init__(
        self,
        tokens: Tuple[int, ...],
        page: int,
        parent: Optional["PageNode"],
    ) -> None:
        self.tokens = tokens
        self.page = page
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "PageNode"] = {}
        self.stamp = 0
        parent_path = parent.path_pages if parent is not None else ()
        self.path_pages: Tuple[int, ...] = parent_path + (page,)
        self.depth = len(self.path_pages)


class PagePrefixIndex:
    """Radix tree of pinned prompt-prefix pages (host side, device-thread
    only — same single-thread discipline as ``PageAllocator``)."""

    def __init__(self, page_size: int, capacity_pages: int) -> None:
        self.page_size = page_size
        self.capacity = max(capacity_pages, 0)
        self._root_children: Dict[Tuple[int, ...], PageNode] = {}
        self._nodes: set = set()  # all nodes, for LRU scans
        self._clock = 0
        # Eviction hook (engine/kvcache/index.py): called with the
        # victim's full token path and page BEFORE the unpin, while the
        # page contents are still live — the host tier starts its D2H
        # spill there instead of losing the KV. None = drop (seed
        # behavior).
        self.on_evict = None

    @staticmethod
    def path_tokens(node: PageNode) -> Tuple[int, ...]:
        """Full token prefix covered by ``node``'s chain (walks parents;
        eviction-rate only — nodes don't duplicate their path)."""
        parts: List[Tuple[int, ...]] = []
        walk: Optional[PageNode] = node
        while walk is not None:
            parts.append(walk.tokens)
            walk = walk.parent
        return tuple(t for blk in reversed(parts) for t in blk)

    def __len__(self) -> int:
        return len(self._nodes)

    @property
    def pinned_pages(self) -> int:
        return len(self._nodes)

    def _touch(self, node: PageNode) -> None:
        self._clock += 1
        node.stamp = self._clock

    def _children_of(
        self, node: Optional[PageNode]
    ) -> Dict[Tuple[int, ...], PageNode]:
        return self._root_children if node is None else node.children

    def match(self, ids: Sequence[int]) -> Optional[PageNode]:
        """Deepest cached chain that is a proper prefix of ``ids``.
        Returns the terminal node (its ``path_pages`` are the shared
        pages, ``depth * page_size`` the prefix length) or None."""
        P = self.page_size
        max_blocks = (len(ids) - 1) // P
        node: Optional[PageNode] = None
        for b in range(max_blocks):
            blk = tuple(ids[b * P: (b + 1) * P])
            child = self._children_of(node).get(blk)
            if child is None:
                break
            node = child
        if node is None:
            return None
        # Touch the whole path so LRU eviction can't orphan a hot chain's
        # interior while its leaf stays pinned.
        walk: Optional[PageNode] = node
        while walk is not None:
            self._touch(walk)
            walk = walk.parent
        return node

    def register(
        self, ids: Sequence[int], pages: Sequence[int], alloc,
        protect: frozenset = frozenset(),
    ) -> None:
        """Pin the chain of fully-covered prompt blocks. ``ids`` must be
        exactly the covered tokens (``len(ids) == len(pages) *
        page_size``) and ``pages`` the slot's table entries for them.
        Existing nodes are kept (their pages already hold identical K/V);
        new nodes pin the slot's private pages so they outlive it.
        ``protect`` exempts pages from the capacity eviction this call
        may trigger — the KV-cache tier's restore path protects its own
        freshly restored chain, which would otherwise be the LRU pass's
        first victim before its pool write even lands."""
        P = self.page_size
        assert len(ids) == len(pages) * P
        node: Optional[PageNode] = None
        for b, page in enumerate(pages):
            blk = tuple(ids[b * P: (b + 1) * P])
            children = self._children_of(node)
            child = children.get(blk)
            if child is None:
                child = PageNode(blk, int(page), node)
                alloc.pin(int(page))
                children[blk] = child
                self._nodes.add(child)
            self._touch(child)
            node = child
        if self.capacity and len(self._nodes) > self.capacity:
            self._evict_lru(
                len(self._nodes) - self.capacity, alloc, protect
            )

    def evict(
        self, n_pages: int, alloc,
        protect: frozenset = frozenset(),
    ) -> int:
        """Admission-pressure reclaim: unpin up to ``n_pages`` LRU leaf
        pages (never ones in ``protect`` — the chain a pending admission
        is about to map). Only pages whose SOLE ref is the index are
        eligible: unpinning a page a running slot still maps frees
        nothing — it would just wipe a hot cache entry while the head
        stays blocked (review finding). Returns pages made allocatable."""
        return self._evict_lru(n_pages, alloc, protect, only_free=True)

    def _evict_lru(
        self, n_pages: int, alloc,
        protect: frozenset = frozenset(),
        only_free: bool = False,
    ) -> int:
        dropped = 0
        while dropped < n_pages and self._nodes:
            # One batched pass: eligible leaves oldest-first (evicting a
            # leaf can turn its parent into one — the outer loop catches
            # those on the next pass).
            leaves = sorted(
                (
                    n for n in self._nodes
                    if not n.children and n.page not in protect
                    and (not only_free or alloc.refs[n.page] == 1)
                ),
                key=lambda n: n.stamp,
            )
            if not leaves:
                break
            for victim in leaves[: n_pages - dropped]:
                self._children_of(victim.parent).pop(victim.tokens, None)
                self._nodes.remove(victim)
                if self.on_evict is not None:
                    # Spill BEFORE the unpin: the page is still
                    # referenced, so its contents cannot be overwritten
                    # until the spill's read is enqueued.
                    try:
                        self.on_evict(self.path_tokens(victim), victim.page)
                    except Exception:  # noqa: BLE001 — spill is optional
                        pass
                alloc.unpin(victim.page)
                dropped += 1
        return dropped

    def clear(self, alloc=None) -> None:
        """Drop every node. With ``alloc`` the pages are unpinned; without
        (engine-state rebuild: the pool itself was recreated) the
        bookkeeping is simply reset."""
        if alloc is not None:
            for n in self._nodes:
                alloc.unpin(n.page)
        self._root_children = {}
        self._nodes = set()


__all__ = ["PagePrefixIndex", "PageNode"]
