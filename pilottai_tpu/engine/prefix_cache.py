"""Automatic prefix caching for admission.

Agent workloads re-send near-identical prompts constantly: the protocol
preamble (prompts/rules.yaml) is byte-identical across every call, and
whole analysis prompts repeat across retries and sibling subtasks. On a
single chip the admission prefill is serial with decode, and on llama3-8b
a 2048-position padded prefill (~33 TFLOP) costs more wall time than the
decode chunks it feeds — measured as the dominant share of the 8-way
agent-step wave on v5e (round 3).

The store keeps the K/V panels (and token ids) of recently admitted
prompts on device. A new request that shares a cached prefix admits by
COPYING those panels into its slot and prefilling only the tail with
prefix-aware attention (``engine/decode.py:admit_group_prefix``); an
exact repeat is a one-token tail. Derived least-common-prefix entries
self-organize toward the shared preamble: when two different prompts
share a ≥min_len prefix, that prefix becomes its own entry, so
rules-preamble + varying-task workloads hit without ever seeing the same
full prompt twice.

Entries are plain (non-donated) device arrays — safe to reuse across
dispatches and engine-state rebuilds. Host-side bookkeeping rides the
shared radix index (``engine/kvcache/radix.py``): ``match``/``has`` are
one O(len) tree walk instead of the former O(capacity x len) linear
scan, and eviction removes a single scored victim per overflow instead
of the O(n²) ``list.remove(min(...))`` loop. Eviction is cost-aware by
default under the KV cache tier (``policy="cost"``: recency x prefill
FLOPs saved per byte held) and plain LRU standalone; either way the
victim is handed to ``on_evict`` so the host tier (ISSUE 10) can spill
its panels instead of losing the KV.

No reference counterpart (the reference's prompts leave the process over
HTTPS, ``pilott/engine/llm.py:59``); parity target is the automatic
prefix caching of production LLM servers.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

from pilottai_tpu.engine.kvcache.policy import (
    eviction_score,
    validate_policy,
)
from pilottai_tpu.engine.kvcache.radix import RadixTree


class PrefixEntry:
    __slots__ = ("ids", "ks", "vs", "p_bucket", "stamp")

    def __init__(self, ids: Tuple[int, ...], ks: Any, vs: Any, p_bucket: int):
        self.ids = ids          # true tokens (len <= p_bucket)
        self.ks = ks            # [L, K, p_bucket, H] device array
        self.vs = vs
        self.p_bucket = p_bucket
        self.stamp = 0


class PrefixStore:
    """Radix-indexed store of cached prompt-prefix K/V panels.

    ``min_len`` is the ENTRY FLOOR and a real serving knob
    (``engine_prefix_min_len``, default = the 64-token prefill bucket
    floor): an entry stores the admitted prompt MINUS its last token
    (match() requires a proper prefix — the tail token must produce the
    first-token logits), so only prompts of at least ``min_len + 1``
    tokens ever cache. Workloads of shorter prompts silently never hit;
    the batcher warns once when it sees one (``_warn_min_len``) instead
    of leaving that to a NOTE in the changelog. Lowering the floor
    trades more (smaller, less valuable) entries for coverage of short
    prompts; the cap ``max_len`` bounds per-entry HBM.
    """

    def __init__(self, capacity: int = 8, min_len: int = 64,
                 max_len: int = 1024, policy: str = "lru",
                 on_evict: Optional[Callable[[PrefixEntry], None]] = None,
                 ) -> None:
        self.capacity = capacity
        self.policy = validate_policy(policy, "prefix-store")
        self.min_len = min_len
        self.max_len = max_len
        # Eviction hook (engine/kvcache/index.py): the host tier spills
        # the victim's panels instead of dropping the KV on the floor.
        self.on_evict = on_evict
        self._tree = RadixTree()
        self._clock = 0

    def __len__(self) -> int:
        return len(self._tree)

    def _touch(self, e: PrefixEntry) -> None:
        self._clock += 1
        e.stamp = self._clock

    def match(self, ids: Sequence[int]) -> Optional[PrefixEntry]:
        """Longest entry that is a PROPER prefix of ``ids`` (at least one
        tail token must remain for the first-token logits). One O(len)
        radix walk."""
        node = self._tree.longest_payload_prefix(ids, proper=True)
        if node is None:
            return None
        entry = node.payload
        self._touch(entry)
        return entry

    def has(self, ids: Sequence[int]) -> bool:
        return self._tree.has(ids)

    def lcp_candidates(self, ids: Sequence[int]) -> List[int]:
        """Lengths of longest-common-prefixes with existing entries that
        are worth storing as derived entries (>= min_len, not already
        stored, shorter than the entries they were read off) — read off
        the radix walk's divergence points, no per-entry comparison."""
        return self._tree.lcp_candidates(ids, self.min_len)

    def _score(self, e: PrefixEntry) -> float:
        # ONE scoring formula shared with the host tier
        # (kvcache/policy.py) — the two tiers must never drift.
        return eviction_score(e.stamp, len(e.ids), e.p_bucket, self.policy)

    def store(self, ids: Sequence[int], ks: Any, vs: Any,
              p_bucket: int) -> None:
        ids = tuple(ids)
        if not (self.min_len <= len(ids) <= self.max_len):
            return
        if self._tree.has(ids):
            return
        e = PrefixEntry(ids, ks, vs, p_bucket)
        self._touch(e)
        self._tree.insert(ids, e)
        while len(self._tree) > self.capacity:
            victim = min(
                (entry for _, entry in self._tree.items()),
                key=self._score,
            )
            self._tree.remove(victim.ids)
            if self.on_evict is not None:
                self.on_evict(victim)

    def clear(self) -> None:
        self._tree = RadixTree()
