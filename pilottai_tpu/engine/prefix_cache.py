"""Automatic prefix caching for admission.

Agent workloads re-send near-identical prompts constantly: the protocol
preamble (prompts/rules.yaml) is byte-identical across every call, and
whole analysis prompts repeat across retries and sibling subtasks. On a
single chip the admission prefill is serial with decode, and on llama3-8b
a 2048-position padded prefill (~33 TFLOP) costs more wall time than the
decode chunks it feeds — measured as the dominant share of the 8-way
agent-step wave on v5e (round 3).

The store keeps the K/V panels (and token ids) of recently admitted
prompts on device. A new request that shares a cached prefix admits by
COPYING those panels into its slot and prefilling only the tail with
prefix-aware attention (``engine/decode.py:admit_group_prefix``); an
exact repeat is a one-token tail. Derived least-common-prefix entries
self-organize toward the shared preamble: when two different prompts
share a ≥min_len prefix, that prefix becomes its own entry, so
rules-preamble + varying-task workloads hit without ever seeing the same
full prompt twice.

Entries are plain (non-donated) device arrays — safe to reuse across
dispatches and engine-state rebuilds. Host-side bookkeeping is a tiny
LRU; matching is a linear scan over <= capacity entries.

No reference counterpart (the reference's prompts leave the process over
HTTPS, ``pilott/engine/llm.py:59``); parity target is the automatic
prefix caching of production LLM servers.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple


class PrefixEntry:
    __slots__ = ("ids", "ks", "vs", "p_bucket", "stamp")

    def __init__(self, ids: Tuple[int, ...], ks: Any, vs: Any, p_bucket: int):
        self.ids = ids          # true tokens (len <= p_bucket)
        self.ks = ks            # [L, K, p_bucket, H] device array
        self.vs = vs
        self.p_bucket = p_bucket
        self.stamp = 0


class PrefixStore:
    """LRU store of cached prompt-prefix K/V panels."""

    def __init__(self, capacity: int = 8, min_len: int = 64,
                 max_len: int = 1024) -> None:
        self.capacity = capacity
        self.min_len = min_len
        self.max_len = max_len
        self._entries: List[PrefixEntry] = []
        self._clock = 0

    def __len__(self) -> int:
        return len(self._entries)

    def _touch(self, e: PrefixEntry) -> None:
        self._clock += 1
        e.stamp = self._clock

    def match(self, ids: Sequence[int]) -> Optional[PrefixEntry]:
        """Longest entry that is a PROPER prefix of ``ids`` (at least one
        tail token must remain for the first-token logits)."""
        best = None
        n = len(ids)
        for e in self._entries:
            p = len(e.ids)
            if p < self.min_len or p >= n:
                continue
            if best is not None and p <= len(best.ids):
                continue
            if tuple(ids[:p]) == e.ids:
                best = e
        if best is not None:
            self._touch(best)
        return best

    def has(self, ids: Sequence[int]) -> bool:
        t = tuple(ids)
        return any(e.ids == t for e in self._entries)

    def lcp_candidates(self, ids: Sequence[int]) -> List[int]:
        """Lengths of longest-common-prefixes with existing entries that
        are worth storing as derived entries (>= min_len, not already
        stored, shorter than ids)."""
        out = set()
        for e in self._entries:
            n = min(len(e.ids), len(ids))
            i = 0
            while i < n and e.ids[i] == ids[i]:
                i += 1
            if i >= self.min_len and i < len(e.ids):
                out.add(i)
        return [
            p for p in sorted(out, reverse=True)
            if not self.has(tuple(ids[:p]))
        ]

    def store(self, ids: Sequence[int], ks: Any, vs: Any,
              p_bucket: int) -> None:
        ids = tuple(ids)
        if not (self.min_len <= len(ids) <= self.max_len):
            return
        if self.has(ids):
            return
        e = PrefixEntry(ids, ks, vs, p_bucket)
        self._touch(e)
        self._entries.append(e)
        while len(self._entries) > self.capacity:
            self._entries.remove(min(self._entries, key=lambda x: x.stamp))

    def clear(self) -> None:
        self._entries.clear()
