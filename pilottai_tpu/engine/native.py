"""The in-tree JAX engine backend: ``provider="tpu"`` / ``provider="cpu"``.

This is the component that replaces the reference's remote-API path
(``pilott/engine/llm.py:59`` → litellm → HTTPS): weights live on local
devices, sharded over a ``jax.sharding.Mesh``; generations run through the
continuous batcher's device thread; asyncio callers await futures bridged
from that thread. Zero external API calls.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from pilottai_tpu.core.config import LLMConfig
from pilottai_tpu.engine.base import (
    LLMBackend,
    parse_tool_calls,
    render_generic_request,
    tool_preamble,
)
from pilottai_tpu.engine.batcher import ContinuousBatcher, GenRequest
from pilottai_tpu.engine.tokenizer import (
    ByteTokenizer,
    IncrementalDecoder,
    load_tokenizer,
)
from pilottai_tpu.engine.types import (
    ChatMessage,
    GenerationParams,
    LLMResponse,
    ToolSpec,
    Usage,
)
from pilottai_tpu.models.common import init_params, param_logical_axes
from pilottai_tpu.models.registry import get_model_config
from pilottai_tpu.parallel.mesh import (
    MeshConfig,
    best_mesh_config,
    create_mesh,
    initialize_distributed,
)
from pilottai_tpu.parallel.sharding import shard_params
from pilottai_tpu.reliability import DegradeLadder
from pilottai_tpu.utils.logging import get_logger


class NativeEngine(LLMBackend):
    """JAX/XLA serving engine with continuous batching."""

    def __init__(self, config: LLMConfig, platform: Optional[str] = None) -> None:
        self.config = config
        self.platform = platform  # None = default backend; "cpu" = host jax
        self.name = platform or "tpu"
        self._log = get_logger(f"engine.{self.name}")
        self.batcher: Optional[ContinuousBatcher] = None
        self.tokenizer = load_tokenizer(config.tokenizer_path)
        self.model_cfg = get_model_config(config.model_name)
        # No checkpoint + byte tokenizer → shrink the vocab to the byte
        # tokenizer's so randomly-initialized serving is cheap and coherent.
        if (
            config.checkpoint_path is None
            and isinstance(self.tokenizer, ByteTokenizer)
            and self.model_cfg.vocab_size != self.tokenizer.vocab_size
        ):
            self.model_cfg = self.model_cfg.replace(
                vocab_size=self.tokenizer.vocab_size, tie_embeddings=True
            )
        dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
        self.model_cfg = self.model_cfg.replace(dtype=dtype)
        # Weight quantization mode: engine_quant wins; the legacy
        # ``quantize`` field is an alias ("int8"/"int4"); "none" = dense.
        self.quant_mode = config.engine_quant or config.quantize or "none"
        self.mesh = None
        # Subword JSON grammar tables (built lazily at start; None = byte
        # automaton or tokenizer can't derive token bytes).
        self._json_tables = None
        # Compiled JSON-Schema DFAs for response_format json_schema
        # (byte tokenizers only; engine/json_schema.py).
        self.schema_bank = None
        if isinstance(self.tokenizer, ByteTokenizer):
            from pilottai_tpu.engine.json_schema import SchemaBank

            self.schema_bank = SchemaBank()
        self._start_lock = asyncio.Lock()

    # ------------------------------------------------------------------ #

    async def start(self) -> None:
        # Lock closes the check-then-act race: concurrent first generate()
        # calls must not both run the multi-second init and leak a second
        # device thread.
        async with self._start_lock:
            if self.batcher is not None:
                return
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, self._start_blocking)

    def _start_blocking(self) -> None:
        t0 = time.perf_counter()
        # Persistent compilation cache BEFORE the first dispatch: a warm
        # restart (FaultTolerance respawn, worker redeploy) reloads the
        # prefill ladder + decode chunk executables instead of spending
        # minutes recompiling them (round-3 bench: 141.7 s engine-up).
        from pilottai_tpu.utils.compile_cache import enable_compilation_cache

        if self.config.engine_compile_cache is not None or self.platform != "cpu":
            # Default-on for the real backend; the cpu provider (test
            # suites churning hundreds of tiny engines) opts in by
            # setting the knob explicitly.
            enable_compilation_cache(self.config.engine_compile_cache)
        # Multi-host bring-up over DCN when JAX_COORDINATOR_ADDRESS et al
        # are set; a no-op for single-process serving.
        initialize_distributed()
        devices = (
            jax.local_devices(backend="cpu") if self.platform == "cpu" else jax.devices()
        )
        mesh_cfg = (
            MeshConfig.from_dict(self.config.mesh_shape)
            if self.config.mesh_shape
            else best_mesh_config(len(devices))
        )
        self.mesh = create_mesh(mesh_cfg, devices)
        self._log.info(
            "loading %s (%.2fB params) on mesh %s",
            self.model_cfg.name,
            self.model_cfg.param_count() / 1e9,
            dict(mesh_cfg.shape),
        )
        # Tensor-parallel serving shardability (ISSUE 13): which KV dims
        # will shard on this mesh and which degrade to replication — one
        # loud line at boot instead of a silently replicated pool.
        if self.mesh.devices.size > 1:
            from pilottai_tpu.parallel.sharding import validate_serving_mesh

            report = validate_serving_mesh(
                self.mesh, self.model_cfg, self.config.engine_slots
            )
            self._log.info(
                "serving mesh: kv_heads_sharded=%s data_groups=%d",
                report["kv_heads_sharded"], report["data_groups"],
            )
            for warning in report["warnings"]:
                self._log.warning("serving mesh: %s", warning)
        if self.config.checkpoint_path:
            # Format-dispatching: HF safetensors or a native orbax tree
            # (in-tree trained models, e.g. protocol-s).
            from pilottai_tpu.models.loader import load_checkpoint

            params = load_checkpoint(
                self.model_cfg, self.config.checkpoint_path, mesh=self.mesh,
                dtype=self.model_cfg.dtype,
            )
        else:
            # Random init. Single chip + int8: quantize leaf-by-leaf at
            # generation time — a full bf16 8B tree alone would overflow a
            # 16 GB chip before quantize_params could shrink it. Multi-
            # chip: init dense and shard first (per-chip shards fit), then
            # the quantize pass below shrinks the sharded leaves.
            # int4 always quantizes FROM the dense init (no eager int8
            # intermediate): the packed values must match across the
            # single-chip and sharded boot paths for the byte-identity
            # matrix (tests/test_multichip.py) — a random-init 8B that
            # cannot hold the dense tree on one chip should load a
            # checkpoint or serve int8.
            single = len(devices) == 1
            if single:
                # Eager init ops follow the DEFAULT backend, which is not
                # necessarily this engine's (a cpu-provider engine on a
                # TPU host must not land its params on the TPU) — pin the
                # device for the whole init.
                with jax.default_device(devices[0]):
                    params = init_params(
                        self.model_cfg, jax.random.PRNGKey(self.config.seed),
                        quantize=(self.quant_mode == "int8"),
                    )
                # Commit (default_device arrays are uncommitted and jit
                # would migrate them back to the default backend).
                params = jax.device_put(params, devices[0])
            else:
                params = init_params(
                    self.model_cfg, jax.random.PRNGKey(self.config.seed)
                )
                params = shard_params(
                    params, param_logical_axes(self.model_cfg), self.mesh
                )
        if self.quant_mode in ("int8", "int4"):
            from pilottai_tpu.models.quant import quantize_params

            # Weight-only quantization on device: shrinks the decode
            # weight stream AND the params' HBM footprint (already-
            # quantized leaves from the init path pass through untouched;
            # donation keeps the 8B tree from being double-resident).
            # int4 packs two nibbles per byte with per-group scales and
            # falls sensitive leaves back (lm_head → int8, router →
            # dense); see models/quant.py.
            params = quantize_params(
                params, dtype=self.model_cfg.dtype, donate=True,
                bits=4 if self.quant_mode == "int4" else 8,
                group=self.config.engine_quant_group,
            )
            self._log.info(
                "quantized matmul weights to %s (weight-only%s)",
                self.quant_mode,
                f", group {self.config.engine_quant_group}"
                if self.quant_mode == "int4" else "",
            )
        # Subword vocab → precompute the token→byte product tables so
        # json_mode works for real checkpoints' tokenizers, not just the
        # byte tokenizer (VERDICT r2 missing #2). One linear vocab scan.
        if not isinstance(self.tokenizer, ByteTokenizer):
            from pilottai_tpu.engine.json_mask import token_byte_table

            try:
                self._json_tables = token_byte_table(self.tokenizer)
                self._log.info(
                    "built JSON token mask table (%d usable / %d tokens)",
                    int((self._json_tables[1] > 0).sum()),
                    self.tokenizer.vocab_size,
                )
            except Exception as exc:  # noqa: BLE001 — degrade to retry-parse
                self._log.warning(
                    "JSON token table build failed (%s); json_mode falls "
                    "back to unconstrained sampling", exc,
                )
                self._json_tables = None
        if self.config.engine_kv_quantize not in (None, "int8"):
            raise ValueError(
                f"unknown engine_kv_quantize mode "
                f"{self.config.engine_kv_quantize!r}; supported: 'int8'"
            )
        max_seq = self.config.engine_max_seq or min(self.model_cfg.max_seq_len, 2048)
        # Placement flows from the params' NamedShardings; jit propagates
        # them through the cache and activations, no mesh context needed.
        paged = self.config.engine_paged_kv
        if paged is None:
            paged = max_seq >= 4096
        self.batcher = ContinuousBatcher(
            self.model_cfg,
            params,
            n_slots=self.config.engine_slots,
            admit_batch=self.config.engine_admit_batch,
            max_seq_len=max_seq,
            cache_dtype=self.model_cfg.dtype,
            chunk_size=self.config.engine_chunk,
            chunk_policy=self.config.engine_chunk_policy,
            chunk_buckets=(
                tuple(self.config.engine_chunk_buckets)
                if self.config.engine_chunk_buckets else None
            ),
            on_tpu=(self.platform != "cpu" and devices[0].platform == "tpu"),
            mesh=self.mesh,
            paged=paged,
            page_size=self.config.engine_page_size,
            num_pages=self.config.engine_kv_pages,
            page_strip=self.config.engine_page_strip,
            json_tables=self._json_tables,
            speculate=self.config.engine_speculate,
            prefix_cache=self.config.engine_prefix_cache,
            # Global KV cache tier (engine/kvcache/): host-RAM cold tier
            # budget + cost-aware eviction policy for both tiers.
            kvcache_host_mb=self.config.engine_kvcache_host_mb,
            kvcache_policy=self.config.engine_kvcache_policy,
            # DAG-aware admission scheduling (pilottai_tpu/sched/):
            # priority-ordered backlog + gang admission + aging floor.
            sched_policy=self.config.engine_sched_policy,
            gang_wait_ms=self.config.engine_gang_wait_ms,
            priority_aging_s=self.config.engine_priority_aging_s,
            prefix_min_len=self.config.engine_prefix_min_len,
            kv_quantize=self.config.engine_kv_quantize == "int8",
            # Weight quantization bookkeeping + the fused greedy
            # epilogue knob (ISSUE 14).
            weight_quant=self.quant_mode,
            quant_group=self.config.engine_quant_group,
            fused_epilogue=self.config.engine_fused_epilogue,
            draft_layers=self.config.engine_draft_layers,
            pipeline_depth=self.config.engine_pipeline,
            overlap_admission=self.config.engine_overlap_admission,
            schema_bank=self.schema_bank,
            prefill_chunk=self.config.engine_prefill_chunk,
            max_queue_depth=self.config.reliability.max_queue_depth,
            # Engine fault domain (ReliabilityConfig): bounded in-flight
            # recovery, per-class shedding, the capability ladder and
            # (when configured) the device watchdog.
            recovery_max_attempts=self.config.reliability.recovery_max_attempts,
            watchdog_stall_s=self.config.reliability.watchdog_stall_s,
            mesh_ladder=self.config.engine_mesh_ladder,
            batch_shed_frac=self.config.reliability.batch_shed_frac,
            degrade=DegradeLadder(
                fault_threshold=self.config.reliability.degrade_fault_threshold,
                window_s=self.config.reliability.degrade_window_s,
                promote_s=self.config.reliability.degrade_promote_s,
                enabled=self.config.reliability.degrade_enabled,
            ),
        )
        self.batcher.start()
        self.batcher.warmup()
        # Speculative stage pre-warm (pilottai_tpu/sched/): the global
        # scheduler's predicted next-stage prefixes land here — encoded,
        # clamped to engine_prewarm_depth tokens, and staged on the
        # batcher's prep thread. Depth 0 = stay detached.
        if self.config.engine_prewarm_depth > 0:
            from pilottai_tpu.sched import global_scheduler

            global_scheduler.attach_prewarm(id(self), self._sched_prewarm)
        # Profile-guided configuration (obs/profile.py): tag the global
        # workload profiler with this deployment's store key, and warn
        # once if the active knob vector diverges from a stored
        # recommendation for its recorded workload.
        from pilottai_tpu.obs import global_profile

        global_profile.configure(self.config.model_name)
        self._warn_knob_divergence()
        self._log.info("engine up in %.1fs", time.perf_counter() - t0)

    _warned_knob_divergence = False  # one-shot boot warning guard

    def _warn_knob_divergence(self) -> None:
        """One-shot boot warning when the active engine knob vector
        diverges from the recommendation stored for this deployment's
        profile (``scripts/recommend.py`` writes it into the profile
        store next to ``autotune.json``). Mirrors the scheduler's
        one-shot ``min_len`` floor warning: advisory, once, and silent
        when no profile/recommendation is stored — a fresh deployment
        must boot quietly."""
        if self._warned_knob_divergence:
            return
        from pilottai_tpu.utils.compile_cache import load_profile

        blob = load_profile(self.config.model_name) or {}
        recommended = (blob.get("recommendation") or {}).get("knobs") or {}
        diverged = []
        for name, want in sorted(recommended.items()):
            have = getattr(self.config, name, None)
            if have != want:
                diverged.append(f"{name}={have!r} (recommended {want!r})")
        if diverged:
            self._warned_knob_divergence = True
            self._log.warning(
                "knob vector diverges from the stored recommendation for "
                "deployment %r: %s — scripts/recommend.py re-derives it "
                "from the current workload profile",
                self.config.model_name, ", ".join(diverged),
            )

    def _sched_prewarm(self, prompt, session_id=None) -> bool:
        """Scheduler pre-warm entry point (any thread): render the
        predicted prefix through the SAME chat framing as
        ``_build_request`` — the structured ``{"system", "user"}`` form
        re-renders via the chat template / generic transcript, so the
        pre-warmed token prefix byte-matches the admission that follows
        (a raw-text pre-warm would key the radix on different tokens
        and never hit) — then hand it to the batcher's advisory
        queue."""
        batcher = self.batcher
        if batcher is None:
            return False
        if isinstance(prompt, dict):
            # Mirror _build_request's assembly EXACTLY per path: the
            # chat template frames the tool preamble as the first
            # system turn; the generic (template-less) path prepends it
            # RAW ahead of the transcript (render_generic_request's
            # tools kwarg). Framing it as a system turn on the generic
            # path would diverge at byte 0 and the pre-warm would never
            # match a tool-bearing admission.
            tool_text = prompt.get("tools")
            msgs = [
                {"role": role, "content": str(prompt[role])}
                for role in ("system", "user") if prompt.get(role)
            ]
            msg_dicts = (
                [{"role": "system", "content": str(tool_text)}]
                if tool_text else []
            ) + msgs
            rendered = self.tokenizer.render_chat(msg_dicts)
            if rendered is not None:
                ids = self.tokenizer.encode(rendered, add_bos=False)
            else:
                text = render_generic_request(
                    [ChatMessage(**m) for m in msgs]
                )
                if tool_text:
                    text = f"{tool_text}\n\n{text}"
                ids = self.tokenizer.encode(text)
        else:
            ids = self.tokenizer.encode(str(prompt))
        return batcher.prewarm(
            ids[: self.config.engine_prewarm_depth], session_id=session_id
        )

    async def stop(self) -> None:
        from pilottai_tpu.sched import global_scheduler

        global_scheduler.detach_prewarm(id(self))
        if self.batcher is not None:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, self.batcher.stop)
            self.batcher = None

    # ------------------------------------------------------------------ #

    def _build_request(
        self,
        messages: Sequence[ChatMessage],
        tools: Optional[Sequence[ToolSpec]],
        params: GenerationParams,
    ) -> GenRequest:
        tool_text = tool_preamble(tools) if tools else None
        # Checkpoint-native chat rendering first (HF chat_template via
        # the tokenizer; instruct models are fine-tuned on their own
        # header format) — the tool preamble rides as a system turn.
        # Byte tokenizers and template-less checkpoints fall back to the
        # generic transcript, byte-identical to previous behavior (and to
        # the protocol-model training data, train/protocol.py).
        msg_dicts = [{"role": m.role, "content": m.content} for m in messages]
        if tool_text:
            msg_dicts = [{"role": "system", "content": tool_text}] + msg_dicts
        rendered = self.tokenizer.render_chat(msg_dicts)
        if rendered is not None:
            # Templates emit their own BOS text; add_bos would double it.
            prompt_ids = self.tokenizer.encode(rendered, add_bos=False)
        else:
            prompt_ids = self.tokenizer.encode(
                render_generic_request(messages, tools)
            )
        # Schema-constrained decoding: compile/look up in the bank
        # (byte tokenizers only). Unsupported schemas, full banks and
        # subword vocabs degrade to the generic grammar — still valid
        # JSON by construction, just not shape-checked.
        schema_id = -1
        want_json = params.json_mode
        if params.json_schema is not None:
            want_json = True
            if self.schema_bank is not None:
                from pilottai_tpu.engine.json_schema import UnsupportedSchema

                try:
                    schema_id = self.schema_bank.register(params.json_schema)
                except UnsupportedSchema as exc:
                    self._log.warning(
                        "json_schema not enforceable (%s); falling back "
                        "to generic JSON grammar", exc,
                    )
            else:
                self._log.warning(
                    "json_schema requires a byte tokenizer; falling back "
                    "to generic JSON grammar"
                )
        return GenRequest(
            prompt_ids=prompt_ids,
            max_new_tokens=params.max_new_tokens,
            temperature=params.temperature,
            top_k=params.top_k,
            top_p=params.top_p,
            seed=params.seed if params.seed is not None else 0,
            eos_id=self.tokenizer.eos_id,
            # Byte tokenizers use the byte automaton; subword tokenizers
            # the token→byte product tables. Only a tokenizer whose table
            # build failed falls back to free sampling + tolerant parsing.
            json_mode=want_json and (
                isinstance(self.tokenizer, ByteTokenizer)
                or self._json_tables is not None
            ),
            json_schema_id=schema_id,
            deadline=params.deadline,
            # Per-class engine shedding: batch-class traffic sheds at a
            # lower backlog depth than interactive (and outright at the
            # degradation ladder's last rung).
            slo_class=params.slo_class,
            # KV-cache session lineage: the batcher's prefix lookup pins
            # this session's host-tier entries against eviction.
            session_id=params.session_id,
            # DAG-aware scheduling: the full priority lattice + gang
            # tag, into the batcher's priority-ordered backlog.
            priority=params.priority if params.priority is not None else 1,
            gang_id=params.gang_id,
            gang_size=params.gang_size,
            # Flight-recorder correlation: the batcher marks admission /
            # token phases against the flight id and emits its span
            # against the trace id.
            trace_id=params.trace_id,
            flight_id=params.flight_id,
            parent_span_id=params.parent_span_id,
        )

    def schema_support(self, schema: Dict[str, Any]) -> Optional[str]:
        """None when ``schema`` can be enforced by constrained decoding
        on this engine; else a human-readable reason. Used by the HTTP
        server to reject strict-mode requests up front (OpenAI returns
        400 for unsupported strict schemas) instead of degrading
        silently. A successful check registers the schema, so the
        subsequent generation reuses the same bank row."""
        if self.schema_bank is None:
            return "json_schema enforcement requires a byte tokenizer"
        from pilottai_tpu.engine.json_schema import UnsupportedSchema

        try:
            self.schema_bank.register(schema)
        except UnsupportedSchema as exc:
            return str(exc)
        return None

    async def generate(
        self,
        messages: Sequence[ChatMessage],
        tools: Optional[Sequence[ToolSpec]] = None,
        params: Optional[GenerationParams] = None,
    ) -> LLMResponse:
        if self.batcher is None:
            await self.start()
        assert self.batcher is not None
        params = params or GenerationParams()
        start = time.perf_counter()

        request = self._build_request(messages, tools, params)
        prompt_ids = request.prompt_ids
        future = self.batcher.submit(request)
        try:
            token_ids = await _to_asyncio_future(future)
        except asyncio.CancelledError:
            # Caller timed out / cancelled: tell the device loop to free the
            # slot instead of decoding dead work to max_new_tokens.
            request.cancelled = True
            raise
        text = self.tokenizer.decode(token_ids)
        cut = _stop_cut(text, params.stop)
        if cut is not None:
            text = text[:cut]
        # Structured function calling on the native path (VERDICT r1 #5):
        # the same wire contract as the mock backend and the reference
        # (``pilott/engine/llm.py:91-104``).
        tool_calls = (
            parse_tool_calls(text, [t.name for t in tools]) if tools else []
        )
        return LLMResponse(
            content=text,
            tool_calls=tool_calls,
            model=self.model_cfg.name,
            usage=Usage(
                prompt_tokens=len(prompt_ids), completion_tokens=len(token_ids)
            ),
            latency=time.perf_counter() - start,
            finish_reason="stop" if len(token_ids) < params.max_new_tokens else "length",
            schema_enforced=(
                request.json_schema_id >= 0
                if params.json_schema is not None else None
            ),
        )

    async def generate_stream(
        self,
        messages: Sequence[ChatMessage],
        tools: Optional[Sequence[ToolSpec]] = None,
        params: Optional[GenerationParams] = None,
        info: Optional[Dict[str, Any]] = None,
    ):
        """Async generator of text deltas: tokens surface as each fused
        decode chunk folds on the host (every ``engine_chunk`` device
        steps — streaming granularity IS the chunk, the latency/dispatch
        trade the engine already makes), detokenized incrementally. The
        concatenated deltas equal ``generate()``'s content for the same
        request (same slot path, same sampler); stop-string truncation
        included. Exiting the generator early cancels the request — the
        device loop frees its slot at the next chunk boundary."""
        if self.batcher is None:
            await self.start()
        assert self.batcher is not None
        params = params or GenerationParams()
        request = self._build_request(messages, tools, params)

        loop = asyncio.get_running_loop()
        q: "asyncio.Queue[Optional[list]]" = asyncio.Queue()
        request.on_tokens = lambda ids: loop.call_soon_threadsafe(
            q.put_nowait, list(ids)
        )
        future = self.batcher.submit(request)
        afut = _to_asyncio_future(future)
        # Wake the drain loop when generation ends (the final fold may
        # emit nothing, e.g. a lone EOS).
        afut.add_done_callback(lambda _f: q.put_nowait(None))

        decoder = IncrementalDecoder(self.tokenizer)
        # Stop strings can span delta boundaries: hold back the longest
        # stop's len-1 tail until the stream ends.
        holdback = max((len(s) for s in params.stop), default=0)
        emitted = 0  # chars of decoder.text already yielded
        n_seen = 0   # token ids already pushed into the decoder

        try:
            stopped = False
            while True:
                item = await q.get()
                final = item is None and afut.done()
                if item:
                    n_seen += len(item)
                    decoder.push(item)
                if final:
                    # The done sentinel can BEAT the last token batch into
                    # this queue: the batcher resolves the future inside
                    # its fold lock but fires ``on_tokens`` after
                    # releasing it, and the event loop may run the
                    # done-callback in the gap (observed on the real-TPU
                    # path). The future's result is the authoritative
                    # stream content (same ids, same filtering), so
                    # reconcile against it instead of trusting arrival
                    # order.
                    if not afut.cancelled() and afut.exception() is None:
                        ids = afut.result()
                        if n_seen < len(ids):
                            decoder.push(ids[n_seen:])
                            n_seen = len(ids)
                    decoder.flush()
                text = decoder.text
                # Same ``_stop_cut`` as generate(), so parity holds by
                # construction. Streamed text can discover occurrences
                # out of start-position order — a longer stop may
                # complete later yet start earlier — but any occurrence
                # not yet complete must start within the last
                # ``holdback`` chars, so a cut at or before
                # ``len(text) - holdback`` is committed.
                cut = _stop_cut(text, params.stop)
                if final:
                    stopped = cut is not None
                    safe = cut if cut is not None else len(text)
                elif cut is not None and cut <= len(text) - holdback:
                    stopped = True
                    safe = cut
                else:
                    bound = len(text) if not holdback else max(
                        emitted, len(text) - holdback
                    )
                    safe = bound if cut is None else min(cut, bound)
                if safe > emitted:
                    yield text[emitted:safe]
                    emitted = safe
                if stopped or final:
                    break
            if info is not None:
                # generate() parity: a stream that consumed the full
                # token budget finished for "length" unless a stop
                # string truncated it first.
                info["finish_reason"] = (
                    "stop" if stopped or n_seen < params.max_new_tokens
                    else "length"
                )
                info["completion_tokens"] = n_seen
                if params.json_schema is not None:
                    info["schema_enforced"] = request.json_schema_id >= 0
            # Surface generation errors (engine stopped, device failure).
            if afut.done() and not afut.cancelled():
                exc = afut.exception()
                if exc is not None:
                    raise exc
        finally:
            if not afut.done():
                request.cancelled = True

    # ------------------------------------------------------------------ #
    # Serving-cell surface (distributed/cell.py, ISSUE 11)
    # ------------------------------------------------------------------ #

    def routing_signals(self) -> Dict[str, Any]:
        """Replica routing signals (queue/degrade/health); empty dict
        before the engine booted (the cell treats that as idle)."""
        return (
            self.batcher.routing_signals() if self.batcher is not None
            else {}
        )

    def export_session_kv(self, session_id: str):
        """Migration source: the session's KV in the host tier's
        transfer format (blocking device→host gathers — a control-plane
        operation, run it off the event loop)."""
        return (
            self.batcher.export_session_kv(session_id)
            if self.batcher is not None else None
        )

    def import_session_kv(self, export) -> Dict[str, int]:
        return (
            self.batcher.import_session_kv(export)
            if self.batcher is not None else {"accepted": 0, "tokens": 0}
        )

    def render_request_ids(
        self,
        messages: Sequence[ChatMessage],
        tools: Optional[Sequence[ToolSpec]],
        params: GenerationParams,
    ) -> Tuple[List[int], bool]:
        """``(prompt_ids, truncated)`` for a request WITHOUT submitting
        it — the exact token ids ``generate`` would run, plus whether
        the batcher's keep-window would truncate them. The handoff path
        (ISSUE 19) needs both: the ids key the KV export, and a
        truncated prompt is a non-migratable shape — the prefill and
        decode legs could truncate differently (their ``max_new_tokens``
        differ by construction), so handoff is gated to prompts that fit
        whole."""
        if self.batcher is None:
            raise RuntimeError("engine not started")
        ids = list(self._build_request(messages, tools, params).prompt_ids)
        # Mirror submit()'s keep-window clamp (engine/batcher.py): room
        # for one generated token, never a non-positive slice.
        keep = self.batcher.max_seq_len - 1 - params.max_new_tokens
        keep = min(max(keep, 1), self.batcher.max_seq_len - 2)
        return ids, len(ids) > keep

    def export_request_kv(self, prompt_ids, session_id=None):
        """Handoff source (ISSUE 19): a just-prefilled request's KV in
        the wire transfer format, keyed by its prompt ids (blocking
        device→host gathers — run off the event loop)."""
        return (
            self.batcher.export_request_kv(prompt_ids, session_id)
            if self.batcher is not None else None
        )

    def import_request_kv(self, export) -> Dict[str, int]:
        """Handoff target: land a prefilled request's KV so admission
        here decode-resumes instead of re-prefilling."""
        return (
            self.batcher.import_request_kv(export)
            if self.batcher is not None
            else {"accepted": 0, "tokens": 0, "rejected": 0}
        )

    def get_metrics(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"backend": self.name, "model": self.model_cfg.name}
        if self.batcher is not None:
            out.update(self.batcher.get_metrics())
        return out


def _stop_cut(text: str, stops) -> Optional[int]:
    """Truncation point for stop strings: the EARLIEST occurrence of any
    stop in ``text``, or None. One definition shared by ``generate`` and
    ``generate_stream`` — the parity contract (streamed deltas
    concatenate to the non-streamed content) holds by construction, and
    the semantics are order-independent: with stops ["cd", "bc"] over
    "abcd", the cut is at "bc" (position 1) regardless of list order,
    where a list-order truncation loop would depend on which stop is
    checked first when one occurrence straddles another's cut."""
    cut = None
    for stop in stops:
        pos = text.find(stop)
        if pos >= 0:
            cut = pos if cut is None else min(cut, pos)
    return cut


def _to_asyncio_future(fut) -> "asyncio.Future":
    """Bridge a concurrent.futures.Future without blocking the loop."""
    return asyncio.wrap_future(fut) if not isinstance(fut, asyncio.Future) else fut


def register_native_backends() -> None:
    from pilottai_tpu.engine.handler import register_backend

    register_backend("tpu", lambda cfg: NativeEngine(cfg, platform=None))
    register_backend("cpu", lambda cfg: NativeEngine(cfg, platform="cpu"))
