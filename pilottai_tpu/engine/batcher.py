"""Continuous batcher: many concurrent small generations on one device loop.

The workload shape (SURVEY.md §3.4): agent steps are bursty, short,
JSON-bound generations — dozens in flight, each a few hundred tokens. The
batcher multiplexes them onto fixed-shape device computations:

* a dedicated *device thread* runs prefill/decode (never the asyncio loop —
  the reference's blocking-psutil-in-async-loop bug, SURVEY §2.12-h, is the
  cautionary tale);
* decode runs as **fused multi-token chunks** (``engine/decode.py``): one
  dispatch per CHUNK tokens, with sampling + EOS/budget tracking on
  device, because each host<->device round trip costs ~100 ms through a
  remote-TPU tunnel — per-token syncing was the 20x p50 miss of
  VERDICT.md Weak #2;
* the chunk LENGTH is a scheduling decision (``_pick_chunk_blocks``):
  adaptive sizing from remaining budgets + the acceptance EMA,
  quantized to a small bucket ladder so executables stay bounded —
  slots finishing mid-chunk fold (and early-release their pages) at
  the nearest useful boundary instead of riding out a
  straggler-sized chunk (PERF_NOTES round 7);
* chunk dispatches are **pipelined** (depth 2): the host reads chunk N-1's
  tokens while chunks N and N+1 compute, so even the once-per-chunk sync
  overlaps device work;
* admissions happen between chunks in **batched groups**: one prefill for
  up to ``admit_batch`` prompts (padded to a fixed group size so compile
  variants stay bounded), KV written by one batched scatter, first token
  sampled on device with the slot's own sampling params (no host-side
  sampling duplicate — VERDICT.md Weak #9);
* admission **prep is overlapped** (PERF_NOTES round 8): bucket/slot
  selection, page allocation, prefix matching and staging-buffer
  packing run on a dedicated prep thread (``_prep_loop``), so between
  decode dispatches the device thread only *enqueues* the already-built
  prefill behind the in-flight chunks — it never sits building host
  arrays while the TPU drains (``overlap_admission=False`` restores the
  inline path, byte-identical output either way);
* the per-admission scalar metadata rides **one packed staging buffer**
  per dtype (``decode.pack_admit_meta``) instead of ~10 tiny H2D
  transfers, each of which paid a dispatch/transfer-setup floor;
* folds are **non-blocking**: every dispatch starts its D2H copy
  immediately (``_HostCopy``), and the reader materializes the
  already-in-flight copy — chunk N−1 folds from its completed copy
  while chunk N executes; ``jax.device_get`` never runs on the
  dispatch/fold path (tests/test_no_blocking_hotpath.py trips on
  reintroduction);
* prefills compile per power-of-two length bucket; the decode chunk
  compiles once.

All shapes static → zero recompiles at steady state.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from types import SimpleNamespace
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pilottai_tpu.engine.decode import (
    AI_BUDGET,
    AI_EOS,
    AI_JSON,
    AI_LEN,
    AI_SCHEMA,
    AI_PLEN,
    AI_SEED,
    AI_SLOT,
    AI_TOPK,
    AF_TEMP,
    AF_TOPP,
    DecodeState,
    _paged_kernel_for,
    admit_group,
    admit_group_prefix,
    admit_group_prefix_paged,
    decode_chunk,
    decode_chunk_spec,
    export_prefix,
    extend_prompt_paged,
    pack_admit_meta,
    release_decode,
)
from pilottai_tpu.engine.kvcache import KVCacheIndex, SpillCopy
from pilottai_tpu.engine.page_prefix import PagePrefixIndex
from pilottai_tpu.engine.prefix_cache import PrefixStore
from pilottai_tpu.engine.sampling import SamplingState
from pilottai_tpu.models.common import ModelConfig
from pilottai_tpu.models.quant import weight_stream_bytes
from pilottai_tpu.ops.kvcache import KVCache, free_slots
from pilottai_tpu.ops.paged import PageAllocator, PagedKVCache
from pilottai_tpu.ops.pallas.decode_attention import decode_shapes_ok
from pilottai_tpu.ops.pallas.paged_attention import paged_sharding_ok
from pilottai_tpu.parallel.collectives import CollectiveModel
from pilottai_tpu.parallel.meshplan import (
    MeshLadderExhausted,
    MeshPlanLadder,
    ShardLossError,
    classify_device_error,
    plan_label,
)
from pilottai_tpu.parallel.sharding import kv_shard_axes, place_kv_cache
from pilottai_tpu.obs import (
    global_attribution,
    global_blackbox,
    global_flight,
    global_steps,
)
from pilottai_tpu.reliability import (
    DeadlineExceeded,
    DegradeLadder,
    EngineOverloaded,
    PoisonedOutput,
    Watchdog,
    global_injector,
)
from pilottai_tpu.reliability import degrade as degrade_levels
from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import global_metrics
from pilottai_tpu.utils.tracing import global_tracer


#: Priority-rung names for the per-priority backlog-wait histograms
#: (index = the 0..3 lattice; mirrors core.task.TaskPriority).
_PRIO_NAMES = ("low", "normal", "high", "critical")


@dataclass
class GenRequest:
    prompt_ids: List[int]
    max_new_tokens: int = 256
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    eos_id: int = -1
    # Grammar-constrained JSON decoding (engine/json_mask.py): byte
    # automaton for byte tokenizers, token→byte product for subword ones
    # (the batcher's json_tables).
    json_mode: bool = False
    # Schema-constrained decoding: row into the engine's SchemaBank
    # (engine/json_schema.py), -1 = generic grammar. Byte tokenizers
    # only; implies json_mode.
    json_schema_id: int = -1
    stop_ids: List[int] = field(default_factory=list)
    future: Future = field(default_factory=Future)
    submitted_at: float = field(default_factory=time.perf_counter)
    # Set by the caller (any thread) to abandon the request; the device loop
    # frees its slot at the next chunk boundary instead of decoding dead work.
    cancelled: bool = False
    # End-to-end deadline: absolute ``time.monotonic()`` time. Checked at
    # submit, again at admission (a request that expired in the backlog
    # never costs a prefill), and swept every device-loop cycle so an
    # occupied slot whose deadline passes mid-decode is force-released
    # (its future fails with DeadlineExceeded). None = no deadline.
    deadline: Optional[float] = None
    # Streaming: called from the READER thread with each batch of newly
    # folded output tokens (eos/stop ids already filtered — exactly the
    # ids the future's final result will contain, in order). Must be
    # cheap and non-blocking (bridge to asyncio via
    # ``loop.call_soon_threadsafe``); exceptions are swallowed.
    on_tokens: Optional[Any] = None
    # Flight-recorder correlation (obs/flight.py): admission and token
    # folds mark phases against ``flight_id`` (unique per request; falls
    # back to trace_id for direct submitters), the request's engine span
    # is emitted under ``trace_id``/``parent_span_id``, and black-box
    # dumps on deadline expiry cite the trace. None (warmup, direct
    # batcher tests) = untracked.
    trace_id: Optional[str] = None
    flight_id: Optional[str] = None
    parent_span_id: Optional[str] = None
    # SLO service class (obs/slo.py): per-class shed thresholds — batch
    # traffic sheds at a lower queue depth than interactive, and the
    # degradation ladder's last rung sheds it outright. None =
    # interactive semantics.
    slo_class: Optional[str] = None
    # DAG-aware scheduling (pilottai_tpu/sched/): the full task-priority
    # lattice (0=LOW … 3=CRITICAL), threaded Task.priority →
    # GenerationParams.priority → here. Under sched_policy="dag" the
    # backlog is priority-ordered (with an aging floor so LOW cannot
    # starve); under "fifo" the field is carried but ignored.
    priority: int = 1
    # Gang admission: sibling fan-out branches from one decompose stage
    # share a gang_id and are admitted as a group when slots+pages
    # suffice for all ``gang_size`` members (bounded wait, then partial
    # admit) — a task's slowest branch stops straggling behind
    # unrelated backlog. None = ungoverned (FIFO/priority only).
    gang_id: Optional[str] = None
    gang_size: int = 0
    # KV-cache session handle (engine/kvcache/): multi-turn agent
    # conversations send the same id every turn, pinning their KV
    # lineage in the host tier across device-cache evictions — a resume
    # restores from host RAM instead of re-prefilling the whole
    # history. None = anonymous (cacheable, but not eviction-pinned).
    session_id: Optional[str] = None
    # In-flight recovery bookkeeping (engine fault domain): on a
    # device/reader failure the batcher snapshots this request's
    # progress and re-admits it — ``recovered_tokens`` carries the
    # already-accepted output (prepended to the final result and never
    # re-emitted to ``on_tokens``), ``recovery_attempts`` bounds the
    # strikes before the request fails with the original exception, and
    # ``recovery_started_at`` times the snapshot→re-admission span for
    # the ``engine.recovery_ms`` histogram.
    recovery_attempts: int = 0
    recovered_tokens: List[int] = field(default_factory=list)
    recovery_started_at: Optional[float] = None
    # engine.kvcache.lookups/hits are per-REQUEST counters: a
    # page-blocked backlog head re-runs the prefix lookup every prep
    # cycle (~20 ms), and counting each attempt would inflate the
    # bench's prefix_hit_rate arbitrarily. Set by the first counted
    # lookup.
    kv_counted: bool = field(default=False, repr=False)
    # Aging-floor rungs already granted (and counted) by the priority
    # backlog — sched.priority_aged must count each promotion once, not
    # once per selection cycle.
    aged_rungs: int = field(default=0, repr=False)

    @property
    def flight_key(self) -> Optional[str]:
        return self.flight_id or self.trace_id


@dataclass
class _Slot:
    request: GenRequest
    generated: List[int] = field(default_factory=list)
    prompt_len: int = 0
    # First generated token still living on device (read lazily with the
    # admission group's array; None once folded into ``generated``).
    first_pending: bool = True
    # In-flight chunk accounting. A dispatched-but-unread chunk will
    # deliver between 1 and D tokens per block (D = 1 without
    # speculation): ``est_pending`` carries the rate-EMA estimate the
    # device loop uses to decide whether ANOTHER chunk would still be
    # useful, ``hi_pending`` the hard maximum the prefix-bound
    # computation needs. Both are reduced when the reader folds the
    # chunk and the slot's ``generated`` absorbs the actual tokens, so
    # estimates self-correct every read: an over-estimate can pause
    # dispatching for at most one fold cycle (the fold wakes the loop),
    # never hang it.
    est_pending: float = 0.0
    hi_pending: int = 0


# Handle for a device→host read whose transfer was STARTED at dispatch
# time (``copy_to_host_async``) and is only awaited at fold time — the
# reader materializes an already-in-flight copy instead of issuing a
# fresh blocking round trip (``jax.device_get`` would). ONE definition
# shared with the KV cache tier's spill path (the same discipline at
# eviction time); the AST tripwire (tests/test_no_blocking_hotpath.py)
# sanctions exactly this shape on both surfaces.
_HostCopy = SpillCopy


@dataclass
class _PreparedAdmission:
    """One admission group with every host-side input prebuilt (numpy
    staging buffers packed, slots reserved, pages allocated) — all that
    remains for the device thread is the jnp upload + jitted dispatch.
    ``epoch`` stamps the allocator generation the pages came from: a
    device-state rebuild invalidates older preps (their block-table rows
    mean nothing in the fresh allocator), which requeue instead of
    dispatching garbage."""

    kind: str                       # "full" | "prefix" | "prefix_paged"
    group: List[Tuple[int, GenRequest]]
    entry: Any
    epoch: int
    meta_i32: np.ndarray
    meta_f32: np.ndarray
    tokens: Optional[np.ndarray] = None       # full-prefill [A, T]
    tail_tokens: Optional[np.ndarray] = None  # prefix paths [A, Tt]
    full_tokens: Optional[np.ndarray] = None  # prefix paths [A, Tf]
    pages_arr: Optional[np.ndarray] = None    # paged-prefix chain pages
    page_rows: Optional[np.ndarray] = None    # [A, max_pages]
    n_prefix_bucket: int = 1
    has_json: bool = False
    has_schema: bool = False


@dataclass
class _SegmentStart:
    """Prep-queue marker: a chunked-prefill admission whose pages are
    allocated; the device thread installs it as ``_segmenting`` and
    advances one segment per loop cycle."""

    seg: List[Any]                  # [slot_idx, request, tokens_done]
    epoch: int


class ContinuousBatcher:
    """Slot-based continuous batching over jitted prefill / fused-decode."""

    def __init__(
        self,
        cfg: ModelConfig,
        params: Any,
        n_slots: int = 8,
        max_seq_len: Optional[int] = None,
        min_bucket: int = 64,
        cache_dtype=jnp.bfloat16,
        chunk_size: int = 16,
        admit_batch: int = 8,
        use_pallas: Optional[bool] = None,
        on_tpu: Optional[bool] = None,
        mesh: Optional[Any] = None,
        paged: bool = False,
        page_size: int = 128,
        num_pages: Optional[int] = None,
        page_strip: Optional[int] = None,  # pages per paged-kernel grid
                                           # cell (None = autotune at warmup)
        json_tables: Optional[Tuple[Any, Any]] = None,
        speculate: int = 0,
        prefix_cache: int = 4,  # mirrors LLMConfig.engine_prefix_cache
        kv_quantize: bool = False,  # int8 cache panels + per-token scales
        draft_layers: int = 0,  # shallow-layer self-drafting (adaptive)
        pipeline_depth: int = 2,  # decode chunks in flight (tunnel hiding)
        schema_bank: Optional[Any] = None,  # json_schema.SchemaBank
        prefill_chunk: Optional[int] = None,  # chunked-prefill segment size
        max_queue_depth: Optional[int] = None,  # admission control (shed)
        chunk_policy: str = "adaptive",  # "fixed" | "adaptive" chunk sizing
        chunk_buckets: Optional[Tuple[int, ...]] = None,  # adaptive sizes
        overlap_admission: bool = True,  # prep admissions off the device
                                         # thread's critical path
        recovery_max_attempts: int = 2,  # in-flight re-admissions per
                                         # request before the original
                                         # exception wins (0 = off)
        watchdog_stall_s: Optional[float] = None,  # heartbeat-staleness
                                                   # bound (None = no dog)
        mesh_ladder: Any = "auto",      # degraded-mesh plans: "auto"
                                        # (halving ladder), "off", or an
                                        # explicit list of plan dicts
                                        # (parallel/meshplan.py)
        degrade: Optional[DegradeLadder] = None,  # capability ladder
                                                  # (None = default knobs)
        batch_shed_frac: float = 0.5,   # batch-class shed depth as a
                                        # fraction of max_queue_depth
        kvcache_host_mb: int = 0,       # host-RAM cold tier for evicted
                                        # prefix KV (0 = off)
        kvcache_policy: str = "cost",   # tier eviction: "cost" | "lru"
        sched_policy: str = "fifo",     # backlog order: "fifo" | "dag"
                                        # (priority + gang + aging)
        gang_wait_ms: float = 50.0,     # bounded wait for gang siblings
                                        # / capacity before partial admit
        priority_aging_s: float = 2.0,  # seconds of backlog wait per
                                        # aged priority rung (starvation
                                        # floor; 0 = no aging)
        prefix_min_len: Optional[int] = None,  # dense-store entry floor
                                               # (None = min_bucket)
        weight_quant: str = "none",     # weight quantization mode the
                                        # params carry ("none"|"int8"|
                                        # "int4") — autotune keys and the
                                        # QUANT bench read it here
        quant_group: int = 128,         # int4 scale-group width (part of
                                        # the autotune key)
        fused_epilogue: bool = True,    # fuse projection+greedy sampling
                                        # on all-greedy non-JSON chunks
    ) -> None:
        self.cfg = cfg
        self.params = params
        self.n_slots = n_slots
        # Weight-quantization bookkeeping (ISSUE 14): the mode/group ride
        # the page-strip autotune key (a winner timed under bf16 weights
        # must never be reused under int4 — different HBM contention
        # around the kernel), and the measured weight-stream bytes land
        # in gauges so the bytes-halved claim is a series, not a
        # docstring. Gauge values are GLOBAL logical bytes (divide by
        # the TP shard count for per-chip).
        self.weight_quant = weight_quant
        self.quant_group = int(quant_group)
        self.fused_epilogue = bool(fused_epilogue)
        wb = weight_stream_bytes(params)
        self.weight_bytes = wb["total"]
        self.weight_bytes_per_token = wb["per_token"]
        global_metrics.set_gauge("engine.weight_bytes", float(wb["total"]))
        global_metrics.set_gauge(
            "engine.weight_bytes_per_token", float(wb["per_token"])
        )
        self.PIPELINE_DEPTH = max(1, pipeline_depth)
        self.max_seq_len = min(max_seq_len or cfg.max_seq_len, cfg.max_seq_len)
        self.min_bucket = min_bucket
        self.chunk_size = chunk_size
        # Adaptive chunk scheduling (PERF_NOTES r7): the decode chunk
        # length becomes a per-dispatch scheduling decision driven by the
        # live slots' remaining-token budgets and the acceptance EMA,
        # quantized to a small bucket set so the compiled-executable
        # count stays bounded at len(buckets) per prefix-bound rung
        # (pinned by tests/test_compile_cache.py). "fixed" restores the
        # constant chunk_size.
        if chunk_policy not in ("fixed", "adaptive"):
            raise ValueError(
                f"unknown chunk_policy {chunk_policy!r}; "
                f"supported: 'fixed', 'adaptive'"
            )
        self.chunk_policy = chunk_policy
        if chunk_policy == "adaptive":
            if chunk_buckets:
                buckets = {int(b) for b in chunk_buckets}
                bad = sorted(b for b in buckets if not 1 <= b <= chunk_size)
                if bad:
                    # Silently dropping these would degrade "adaptive"
                    # to fixed with no signal why utilization never
                    # moves.
                    raise ValueError(
                        f"chunk_buckets {bad} outside [1, chunk_size="
                        f"{chunk_size}]"
                    )
            else:
                # Quartile ladder: {4, 8, 12, 16} at the default chunk 16.
                buckets = {
                    max(1, (chunk_size * q) // 4) for q in (1, 2, 3, 4)
                }
            # The largest bucket must cover a full fixed chunk, or a
            # saturated wave would need several dispatches where one did.
            self.chunk_buckets = sorted(buckets | {chunk_size})
        else:
            self.chunk_buckets = [chunk_size]
        # Warmup's compile sweep pins the bucket per request via this
        # override so every (bucket x prefix-bound) decode executable
        # compiles before serving (None = policy decides).
        self._force_chunk: Optional[int] = None
        # Wall-seconds per dispatched block EMA (each chunk's
        # dispatch→fold latency over its blocks), the deadline-budget
        # term of the sizing policy: blocks past a slot's deadline are
        # never worth dispatching. 0 = unknown yet.
        self._block_seconds = 0.0
        self.admit_batch = min(admit_batch, n_slots)
        # Overload shedding: submits beyond this many queued-not-admitted
        # requests raise EngineOverloaded instead of growing the queue
        # unboundedly (the HTTP edge maps it to 429). None = unbounded.
        # Batch-class requests shed at batch_shed_frac of the depth —
        # backlog pressure drops the traffic nobody is watching first.
        self.max_queue_depth = max_queue_depth
        self.batch_shed_frac = batch_shed_frac
        # DAG-aware backlog scheduling (pilottai_tpu/sched/, ROADMAP
        # item 4): "dag" orders admission by effective priority
        # (request priority + aging-floor promotions, gang siblings
        # grouped), "fifo" keeps the seed's submission order. Greedy
        # output is byte-identical either way — ordering changes WHEN a
        # request admits, never what it computes (tests/test_sched.py).
        if sched_policy not in ("fifo", "dag"):
            raise ValueError(
                f"unknown sched_policy {sched_policy!r}; "
                f"supported: 'fifo', 'dag'"
            )
        self.sched_policy = sched_policy
        self.gang_wait_ms = max(0.0, gang_wait_ms)
        self.priority_aging_s = max(0.0, priority_aging_s)
        # Gang bookkeeping: first-seen stamp per gang (the bounded-wait
        # clock; pruned when the gang's last member leaves the
        # backlog), the gangs the LAST ordering pass deferred
        # (selection blocks on them instead of admitting a sibling
        # subset early), and a bounded memory of gangs that ALREADY
        # dispatched (metrics fire once per gang, and a late or
        # fault-recovered sibling of a gang that already went must
        # admit at its own priority immediately — re-deferring it
        # behind the whole backlog for another wait bound would be the
        # exact inversion the feature exists to remove).
        self._gang_seen: Dict[str, float] = {}
        self._gang_counted: "OrderedDict[str, bool]" = OrderedDict()
        self._gang_deferred: set = set()
        # Speculative stage pre-warm (sched/ → prep thread): predicted
        # next-stage prompt prefixes waiting for a KV-tier lookup whose
        # host hit stages the restore before the real request arrives.
        # Bounded — pre-warm is advisory, a full queue just drops.
        self._prewarm_queue: deque = deque(maxlen=32)
        # One-shot dense-store floor warning (see _warn_min_len).
        self._warned_min_len = False
        # Engine fault domain: bounded in-flight recovery, the capability
        # ladder, and (optionally) the device watchdog.
        self.recovery_max_attempts = max(0, recovery_max_attempts)
        self.degrade = degrade if degrade is not None else DegradeLadder()
        # Device-thread rebuild request from other threads' failure paths
        # (reader errors, failed failure-path rebuilds): consumed at the
        # top of the device loop, where rebuilds are safe.
        self._rebuild_requested: Optional[str] = None
        self._watchdog: Optional[Watchdog] = None
        if watchdog_stall_s:
            self._watchdog = Watchdog(
                stall_s=watchdog_stall_s,
                has_work=self._watchdog_has_work,
                on_stall=self._on_watchdog_stall,
                # Unique health-registry source per batcher: in a
                # multi-engine process, one engine recovering must not
                # clear a sibling's stall from /healthz.
                name=f"{cfg.name}:{id(self) & 0xFFFF:04x}",
            )
        # Whether this batcher's computations actually run on a TPU (the
        # cpu provider can run on a machine whose default backend IS a
        # TPU, so the process-level check is not enough for the Pallas
        # prefill/decode kernels).
        if on_tpu is None:
            on_tpu = jax.default_backend() == "tpu"
        self.on_tpu = on_tpu
        # int8 KV: doubles resident context per HBM GB (~1e-3 relative
        # attention error). The decode-bandwidth win lands on the paged
        # Pallas kernel (in-VMEM dequant, int8-sized HBM streams); XLA
        # paths dequantize panels at chunk scope, so their win is
        # capacity, not per-step traffic. The dense Pallas kernel
        # (opt-in A/B only) predates scales — force the XLA path.
        self.kv_quantize = bool(kv_quantize)
        if self.kv_quantize and not paged and use_pallas:
            use_pallas = False
        if use_pallas is None:
            if paged:
                # The paged kernel is the point of paging on TPU: its VMEM
                # need is one page (K*P*H), and the XLA fallback gathers
                # dense slots×bound panels per layer — the footprint the
                # paged cache exists to avoid.
                use_pallas = self.on_tpu
            else:
                # Dense mode. Measured on v5e: with the cache read-only
                # inside the chunk scan, XLA's dense attention beats the
                # Pallas prefix kernel at both S=512 and S=2048 — the
                # kernel stays available for A/B via
                # PILOTTAI_DECODE_PALLAS=1.
                use_pallas = (
                    os.environ.get("PILOTTAI_DECODE_PALLAS", "").lower()
                    in ("1", "true", "yes")
                    and self.on_tpu
                    and not self.kv_quantize
                    and decode_shapes_ok(
                        self.max_seq_len, cfg.head_dim,
                        jnp.dtype(cache_dtype).itemsize,
                    )
                )
        self.use_pallas = use_pallas
        # Multi-chip serving mesh (ISSUE 13) + degraded-mesh fault
        # domain (ISSUE 16). All mesh-derived state — flash/kv meshes,
        # kv-head sharding, data groups, the collective model and the
        # attribution config — is computed by _apply_mesh_plan so a
        # shard-loss rebuild can re-derive it for the surviving
        # sub-mesh exactly the way boot derived it for the full one.
        self._log = get_logger("engine.batcher")
        self._apply_mesh_plan(mesh, paged=paged)
        # Degraded-mesh ladder: the ordered mesh plans this engine may
        # fall back to when a shard dies (parallel/meshplan.py). Only a
        # real multi-chip mesh gets one — a single-chip engine has no
        # rung to fall to, and "off" pins the boot plan (a shard loss
        # then follows the plain PR 8 device_loop_error path).
        self._mesh_ladder: Optional[MeshPlanLadder] = None
        if (
            mesh is not None and mesh.devices.size > 1
            and mesh_ladder != "off"
        ):
            self._mesh_ladder = MeshPlanLadder(
                mesh,
                rungs=(
                    mesh_ladder
                    if isinstance(mesh_ladder, (list, tuple)) else None
                ),
                name=cfg.name,
            )
            global_metrics.set_gauge("engine.mesh_plan", 0.0)
        # Subword JSON grammar tables (token_bytes [V, L], token_len [V])
        # from json_mask.token_byte_table — None for byte tokenizers,
        # whose 256-entry byte mask is cheaper.
        self.json_tables = (
            tuple(jnp.asarray(t) for t in json_tables)
            if json_tables is not None else None
        )
        # Schema-constrained decoding: compiled DFA bank shared by all
        # slots; device copies refresh lazily when the bank version moves
        # (a few MB uploaded once per NEW schema, not per dispatch).
        self.schema_bank = schema_bank
        self._schema_dev: Optional[Tuple[Any, Any, Any]] = None
        self._schema_seen = -1

        # Speculative decoding: verify-blocks of ``speculate`` tokens per
        # weight pass (engine/decode.py:decode_chunk_spec) — both caches
        # (the paged chunk reads its prefix through the block table).
        self.speculate = speculate if speculate >= 2 else 0
        # Warmup sweeps must compile the FULL-prefill buckets — gate the
        # paged index during warmup so warmup prompts (which share
        # prefixes by construction) don't short-circuit into the
        # tail-prefill path.
        self._warming = False
        # HBM budget for transiently materialized dense prefix panels on
        # the paged path (see _dispatch_chunk); beyond it the Pallas
        # per-page kernel takes over.
        self._gather_budget = int(
            os.environ.get("PILOTTAI_GATHER_BUDGET", 5 * 1024**3)
        )
        # Observed tokens-per-block EMA (1.0 = no acceptance; up to D).
        # Drives the in-flight token estimates: dispatching assuming no
        # acceptance wastes whole weight passes on no-op chunks (measured
        # 4x wave time on v5e), assuming full acceptance stalls the
        # pipeline when drafts miss.
        self._spec_rate = 1.0
        # Adaptive draft source (engine/decode.py:_model_drafts): slots
        # whose PER-SLOT acceptance EMA collapses under n-gram drafting
        # (novel text — nothing in history to copy) switch to
        # shallow-layer model drafting; hysteresis keeps flappers stable.
        self.draft_layers = (
            min(draft_layers, cfg.n_layers - 1)
            if draft_layers > 0 and self.speculate else 0
        )
        self._slot_rate = np.full(
            (n_slots,), float(max(self.speculate, 1)), np.float32
        )
        self._draft_on = np.zeros((n_slots,), bool)

        self.cache_dtype = cache_dtype
        # Paged KV: shared page pool + host-side block table/allocator
        # (ops/paged.py). Slots reserve only the pages their prompt+budget
        # needs, so long per-slot capacity doesn't multiply HBM by slots.
        self.paged = paged
        self.page_size = page_size
        if paged:
            # Default pool: the HBM a dense cache would spend on
            # min(max_seq, 2048)-wide slots (+ the scratch page).
            self.num_pages = num_pages or (
                n_slots * min(self.max_seq_len, 2048) // page_size + 1
            )
            # The pool must at least hold one full-capacity request, or
            # admission can never make progress (degenerate configs like a
            # page bigger than the whole pool would otherwise clamp
            # max_seq to 0 and hang every request with no error).
            min_pages = -(-min(self.max_seq_len, 2 * page_size) // page_size)
            if self.num_pages - 1 < min_pages:
                raise ValueError(
                    f"paged KV pool of {self.num_pages} pages x {page_size} "
                    f"can't hold a single request; raise engine_kv_pages "
                    f"or lower engine_page_size"
                )
            # A single request can never need more pages than the pool
            # holds — without this clamp an oversized request blocks
            # admission forever (its can_allocate is never true).
            usable = (self.num_pages - 1) * page_size
            if usable < self.max_seq_len:
                self.max_seq_len = usable
            self.max_pages_per_slot = -(-self.max_seq_len // page_size)
        # Paged-kernel strip width: pages per grid cell
        # (ops/pallas/paged_attention.py). The 8K decode path is
        # grid-cell-latency bound, so the per-cell launch/index floor
        # amortizes over the strip. None → warmup() times {1, 2, 4, 8}
        # on the real pool and keeps the winner (persisted alongside the
        # compile cache); until then a VMEM-safe default serves.
        self._strip_autotune_pending = (
            page_strip is None and paged and self.use_pallas and self.on_tpu
        )
        if paged:
            if page_strip is not None:
                self.page_strip = max(1, min(page_strip,
                                             self.max_pages_per_slot))
            elif self.use_pallas and self.on_tpu:
                self.page_strip = self._max_safe_strip(4)
            else:
                self.page_strip = 1
        else:
            self.page_strip = 1
        # Chunked prefill (VERDICT r5 #6): long cold prompts admit in
        # page-aligned segments, one per device-loop cycle, so live
        # slots' decode chunks interleave instead of stalling behind one
        # monolithic multi-thousand-token prefill. Auto-on for the paged
        # pool (where long contexts live); 0 disables.
        if prefill_chunk is None:
            prefill_chunk = 1024 if paged else 0
        self.prefill_chunk = (
            -(-prefill_chunk // page_size) * page_size
            if paged and prefill_chunk > 0 else 0
        )
        # In-flight segmented admission: [slot_idx, request, tokens_done]
        # (device thread only; the slot is excluded from free lists until
        # the final segment installs it). _seg_epoch is the allocator
        # epoch it was prepared against — _advance_segment re-admits from
        # scratch if a rebuild swapped the pool out from under it.
        self._segmenting: Optional[List[Any]] = None
        self._seg_epoch = 0
        # Automatic prefix caching. Dense cache: panel-copy store
        # (engine/prefix_cache.py). Paged cache: block-granular radix of
        # refcounted pages (engine/page_prefix.py) — shared prefixes are
        # MAPPED into new slots' block tables, never copied, and
        # granularity is per page rather than per whole prompt.
        self.prefix_store = None
        self.page_index = None
        if prefix_cache > 0:
            if paged:
                self.page_index = PagePrefixIndex(
                    page_size,
                    # Cap pinned pages at a quarter of the allocatable
                    # pool so caching can never crowd out admissions'
                    # working set (admission pressure can also reclaim
                    # on demand via evict()).
                    capacity_pages=max((self.num_pages - 1) // 4, 1),
                )
            else:
                self.prefix_store = PrefixStore(
                    capacity=prefix_cache,
                    # Entry floor: prompts shorter than this never cache
                    # (engine_prefix_min_len; None = the prefill bucket
                    # floor). Prompts below it get a one-shot warning at
                    # export/pre-warm time instead of silently never
                    # hitting (_warn_min_len).
                    min_len=(
                        prefix_min_len if prefix_min_len is not None
                        else min_bucket
                    ),
                    # Prompt-length cap bounds HBM: a 2048-row 8B entry
                    # is ~540 MB; capacity x 1024 rows keeps the store
                    # around 0.5 GB worst case next to 8 GB of weights
                    # on a 16 GB chip.
                    max_len=min(max_seq_len or cfg.max_seq_len, 1024),
                    policy=kvcache_policy,
                )
        # Global KV cache tier (engine/kvcache/): ONE lookup over the
        # dense store and the paged radix, plus (when kvcache_host_mb >
        # 0) the host-RAM cold tier — evictions spill via async D2H and
        # session resumes restore via async H2D instead of
        # re-prefilling. Greedy output is byte-identical tier on/off
        # (tests/test_kvcache.py).
        self.kvcache: Optional[KVCacheIndex] = None
        if prefix_cache > 0:
            self.kvcache = KVCacheIndex(
                prefix_store=self.prefix_store,
                page_index=self.page_index,
                page_size=page_size,
                host_bytes=int(kvcache_host_mb) * 1024 * 1024,
                policy=kvcache_policy,
                get_cache=lambda: self.cache,
                min_len=prefix_min_len,
                # Host-tier restores upload already split over the
                # 'model' axis when the pool is (ISSUE 13) — the
                # restore scatter then consumes them shard-local.
                place=self._restore_place,
            )
        # Restored page chains awaiting their device-thread pool write
        # (engine/kvcache/index.py:PendingRestore; appended under the
        # slot lock at lookup time, drained by _apply_restores before
        # any dispatch can read the pages).
        self._pending_restores: List[Any] = []
        # Slot table / gen / release / first_reads / allocator are shared
        # between the device thread, the reader thread (completion) and
        # the admission-prep thread (selection) — the lock exists before
        # the first _rebuild_device_state, which swaps the allocator and
        # bumps the epoch under it.
        self._lock = threading.Lock()
        self._alloc_epoch = 0  # bumped by _rebuild_device_state
        self._rebuild_device_state()
        self._slots: List[Optional[_Slot]] = [None] * n_slots
        # Admission generation per slot: chunk results are stamped with the
        # generation vector at dispatch, so a chunk dispatched before a slot
        # was re-admitted can never fold tokens into the new occupant.
        self._gen: List[int] = [0] * n_slots
        self._pending: "queue.Queue[GenRequest]" = queue.Queue()
        # Device-thread FIFO the pending queue drains into (page-gated
        # admission peeks at the head without losing submission order).
        self._backlog: deque = deque()
        self._release: List[int] = []  # slots to force-stop at next admission
        # (group_slots, first_tokens device array) awaiting lazy host read
        self._first_reads: deque = deque()
        self._drain_queued = False  # a drain sentinel is in _results
        # Dispatched chunks awaiting host read. Bounded so the device
        # thread can't run unboundedly ahead of completions. The depth is
        # the one knob (engine_pipeline): each item carries its own
        # _HostCopy, so any depth ≥ 1 pipelines — nothing about the
        # read-back is structural anymore.
        self._results: "queue.Queue" = queue.Queue(maxsize=self.PIPELINE_DEPTH)
        # Overlapped admission (PERF_NOTES r8): a prep thread runs group
        # selection / page allocation / staging-buffer packing and hands
        # _PreparedAdmission items over this queue, so the device thread
        # only enqueues the prefill dispatch behind in-flight chunks.
        # False = the seed's inline path (same code, same thread).
        self.overlap_admission = bool(overlap_admission)
        self._prepped: "queue.Queue" = queue.Queue()
        self._prep_depth = 2            # prepared waves ahead, max
        self._prep_reserved: set = set()  # slots picked but not installed
        self._prepped_reqs = 0          # requests inside _prepped (approx)
        self._seg_pending = False       # a segmentation owns admission
        self._prep_gate = threading.Lock()  # quiesces prep for requeues
        self._prep_wake = threading.Event()
        # Host-gap telemetry: time from the last fold-complete (or
        # prefill feed) to the next chunk dispatch while NOTHING was in
        # flight — the host-side bubble the overlap work exists to
        # close. 0 whenever the pipeline still held work.
        self._inflight = 0
        self._last_fold_done: Optional[float] = None
        self._last_prefill_t: Optional[float] = None
        # Device-time attribution (obs/attribution.py): decode time is
        # estimated as the fold-to-fold interval minus the measured idle
        # gap and the prefill enqueue walls that landed inside it
        # (accumulated here between folds, under the lock).
        self._last_attr_mark: Optional[float] = None
        self._prefill_since_fold = 0.0
        # (Live MFU/attribution gauges configure inside _apply_mesh_plan
        # — the FLOPs formula is constant but n_chips/mesh_axes follow
        # the ACTIVE plan across degradations.)
        # (engine.queue_depth is declared at obs import — the exported
        # surface exists from process boot; the batcher only sets it.)
        if self.max_queue_depth is not None:
            global_metrics.set_gauge(
                "engine.max_queue_depth", float(self.max_queue_depth)
            )
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._reader: Optional[threading.Thread] = None
        self._prep_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------ #
    # Lifecycle
    # ------------------------------------------------------------------ #

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="pilottai-device-loop", daemon=True
        )
        self._reader = threading.Thread(
            target=self._read_loop, name="pilottai-reader", daemon=True
        )
        self._thread.start()
        self._reader.start()
        if self.overlap_admission:
            self._prep_thread = threading.Thread(
                target=self._prep_loop, name="pilottai-admit-prep",
                daemon=True,
            )
            self._prep_thread.start()
        if self._watchdog is not None:
            self._watchdog.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        self._prep_wake.set()
        if self._watchdog is not None:
            self._watchdog.stop()
        if self._prep_thread is not None:
            self._prep_thread.join(timeout=60)
            self._prep_thread = None
        if self._thread is not None:
            self._thread.join(timeout=60)
            self._thread = None
        if self._reader is not None:
            self._reader.join(timeout=60)
            self._reader = None
        # Restores staged but not yet scattered: apply them now (threads
        # are joined — this thread owns the device state) so a restart
        # can never serve a registered chain whose pages were never
        # written.
        try:
            self._apply_restores()
        except Exception:  # noqa: BLE001 — best-effort on shutdown
            pass
        # Quiesce the device: chunks dispatched right before stop may still
        # be executing, and tearing the process down mid-computation
        # crashes the backend's thread pool at exit.
        try:
            if not self.cache.lengths.is_deleted():
                jax.block_until_ready(self.cache.lengths)
        except Exception:  # noqa: BLE001 — best-effort quiesce
            pass
        self._prewarm_queue.clear()  # advisory: staged pre-warms drop
        # Fail any stranded requests.
        stranded = list(self._backlog)
        self._backlog.clear()
        if self._segmenting is not None:  # mid-chunked-prefill request
            stranded.append(self._segmenting[1])
            if self.alloc is not None:
                self.alloc.release(self._segmenting[0])
            self._segmenting = None
        self._seg_pending = False
        while True:  # prepared-but-never-dispatched admissions
            try:
                item = self._prepped.get_nowait()
            except queue.Empty:
                break
            # Release their page allocations too: a stranded prep's
            # pages otherwise survive into the next start() and the
            # first selection that reuses the slot trips allocate()'s
            # held-pages invariant — admission wedges permanently.
            if isinstance(item, _SegmentStart):
                stranded.append(item.seg[1])
                if self.alloc is not None:
                    self.alloc.release(item.seg[0])
            else:
                stranded.extend(req for _, req in item.group)
                if self.alloc is not None:
                    for idx, _ in item.group:
                        self.alloc.release(idx)
        self._prepped_reqs = 0
        self._prep_reserved.clear()
        while True:
            try:
                stranded.append(self._pending.get_nowait())
            except queue.Empty:
                break
        for req in stranded:
            if not req.future.done():
                req.future.set_exception(RuntimeError("engine stopped"))
        for idx, slot in enumerate(self._slots):
            if slot is None:
                continue
            if not slot.request.future.done():
                slot.request.future.set_exception(RuntimeError("engine stopped"))
            if self.alloc is not None:
                self.alloc.release(idx)
        self._slots = [None] * self.n_slots

    # ------------------------------------------------------------------ #
    # Device watchdog (reliability/watchdog.py)
    # ------------------------------------------------------------------ #

    def _beat(self) -> None:
        """Progress heartbeat: folds, prefill installs and segment
        advances call this so the watchdog can tell a hung dispatch from
        a healthy slow one (any thread; a plain float store). The mesh
        ladder's per-shard table beats alongside: a completed fold
        proves the whole active mesh answered, so a shard whose stamp
        stops moving (frozen by the mesh.shard_loss hang variant, or a
        real per-device probe) stands out against beating siblings."""
        wd = self._watchdog
        if wd is not None:
            wd.beat()
        ladder = self._mesh_ladder
        if ladder is not None:
            ladder.beat_all()
            # Shard-stale triage on the HEALTHY path too: a shard whose
            # stamp stopped moving while the engine keeps folding (the
            # chip stopped answering but nothing wedged — the hang
            # variant of mesh.shard_loss, or a production per-device
            # probe) never trips the engine watchdog, so the fold
            # heartbeat is where it stands out against its siblings.
            if wd is not None:
                stale = ladder.stale(wd.stall_s)
                if stale and len(stale) < len(ladder.surviving()):
                    for idx in stale:
                        ladder.mark_lost(idx)
                        global_metrics.inc("engine.shard_losses")
                    self._log.error(
                        "shard heartbeat(s) %s stale while the engine "
                        "keeps serving — treating as shard loss", stale,
                    )
                    self._rebuild_requested = "shard_loss"
                    self._wake.set()

    def _watchdog_has_work(self) -> bool:
        """Anything in flight or queued? (watchdog thread; lock-free
        approximation — a one-poll-late answer only shifts the stall
        clock by poll_s). Warmup is excluded: its compile sweeps stall
        heartbeats for legitimate minutes."""
        if self._warming:
            return False
        return (
            self._inflight > 0
            or any(s is not None for s in self._slots)
            or bool(self._backlog)
            or self._pending.qsize() > 0
            or self._segmenting is not None
            # Prepared-but-not-installed admissions: during a PREFILL
            # dispatch the group's slots live only in _prep_reserved
            # (slots install after admit_group returns, _prepped_reqs
            # decrements at pop) — without these a hung prefill on an
            # otherwise idle engine would never trip the watchdog.
            or bool(self._prep_reserved)
            or self._prepped_reqs > 0
        )

    def _on_watchdog_stall(self, info: Dict[str, Any]) -> None:
        """Stall diagnostics (watchdog thread): the black-box dump is
        the flight recorder for "what was the engine doing when it
        hung"; the ladder counts the stall as a fault.

        Per-shard triage (ISSUE 16): when the mesh ladder's heartbeat
        table shows SOME shards stale while siblings kept beating, the
        stall is a shard loss, not a whole-engine hang — mark the stale
        shards lost and request a shard_loss rebuild. The device thread
        consumes the request at its next cycle (when the hung dispatch
        resolves or raises); until then the watchdog's normal 503
        containment holds."""
        ladder = self._mesh_ladder
        if ladder is not None and self._watchdog is not None:
            stale = ladder.stale(self._watchdog.stall_s)
            info = dict(info, stale_shards=stale)
            if stale and len(stale) < len(ladder.surviving()):
                for idx in stale:
                    ladder.mark_lost(idx)
                    global_metrics.inc("engine.shard_losses")
                self._log.error(
                    "watchdog: shard heartbeat(s) %s stale while "
                    "siblings beat — treating as shard loss", stale,
                )
                self._rebuild_requested = "shard_loss"
        global_steps.record("engine.watchdog_stall", **info)
        global_blackbox.dump("watchdog_stall", **info)
        self.degrade.record_fault("stall")

    # ------------------------------------------------------------------ #
    # Mesh plan (ISSUE 13 boot layout + ISSUE 16 degraded re-planning)
    # ------------------------------------------------------------------ #

    def _apply_mesh_plan(self, mesh: Optional[Any],
                         paged: Optional[bool] = None) -> None:
        """Derive every mesh-dependent piece of engine state from
        ``mesh`` — at boot (the ISSUE 13 layout rules) and again on a
        shard-loss re-plan, so the surviving sub-mesh is configured by
        exactly the code path that configured the boot mesh.

        ``mesh`` drives four things beyond the flash prefill:
        * the KV pool / dense cache panels are CREATED on their
          sharded layout (_rebuild_device_state → place_kv_cache):
          kv-heads over 'model', dense slots over 'data' — the paged
          8B pool stops being resident whole on any one chip;
        * the paged Pallas decode kernel runs per-shard under
          shard_map (kv_mesh → decode_chunk/decode_chunk_spec);
        * admission replicates over the 'data' axis: slots partition
          into ``data_groups`` contiguous groups and
          _free_slot_indices interleaves selection across them;
        * per-dispatch collective time is attributed per axis
          (parallel/collectives.py → engine.collective_frac[.axis]).
        """
        if paged is None:
            paged = self.paged
        cfg = self.cfg
        # Prefill's flash kernel runs per-shard under shard_map
        # (ops/pallas/flash_attention.py). One device → plain
        # single-chip dispatch inside _full_seq_block.
        self.flash_mesh = (
            mesh if mesh is not None and mesh.devices.size > 1 else None
        )
        self.mesh = self.flash_mesh
        kv_axes = kv_shard_axes(
            self.mesh, n_kv_heads=cfg.n_kv_heads, n_slots=self.n_slots
        )
        self.kv_heads_sharded = kv_axes["heads"] is not None
        self.data_groups = int(kv_axes["data_groups"])
        # The dense Pallas decode kernel (opt-in A/B path,
        # PILOTTAI_DECODE_PALLAS) has no shard_map wrapper: on a mesh
        # whose dense panels shard it cannot lower per-shard — demote
        # to the XLA dense path, which GSPMD partitions fine (and which
        # beats the kernel at serving sizes anyway). The demotion only
        # ever turns the kernel OFF, so re-applying on a smaller mesh
        # never resurrects it mid-serving.
        if (
            self.mesh is not None and not paged and self.use_pallas
            and (kv_axes["heads"] is not None or kv_axes["slots"] is not None)
        ):
            self.use_pallas = False
        self.kv_mesh = None
        if (
            self.mesh is not None and paged and self.use_pallas
            and paged_sharding_ok(self.mesh, self.n_slots, cfg.n_kv_heads)
        ):
            self.kv_mesh = self.mesh
        # KV placement mesh: the pool/panels shard per kv_shard_axes —
        # EXCEPT when the paged Pallas kernel will run but cannot run
        # sharded (slots don't divide the data axes, or a seq axis is
        # present): a model-sharded pool under the UNWRAPPED kernel
        # would force a whole-pool gather (or fail to lower) on every
        # dispatch, so the pool stays replicated and only the weights
        # shard. The XLA fallback path partitions any layout.
        self._kv_place_mesh = self.mesh
        if paged and self.use_pallas and self.kv_mesh is None:
            self._kv_place_mesh = None
            if self.kv_heads_sharded:
                # Report the EFFECTIVE placement: an operator debugging
                # HBM pressure must not be told the pool is split across
                # TP shards while it is resident whole on every chip.
                self.kv_heads_sharded = False
                self._log.warning(
                    "paged Pallas kernel cannot run sharded on this "
                    "mesh; KV pool stays replicated — only weights shard"
                )
        self.collective_model = CollectiveModel.for_mesh(
            self.mesh, cfg,
            platform="tpu" if self.on_tpu else "cpu",
            paged=paged, kv_quantize=self.kv_quantize,
        )
        # Live MFU/attribution gauges: the model's FLOPs formula, the
        # platform peak and the ACTIVE mesh shape — the same
        # ModelConfig.flops_per_token() bench.py uses, so live and
        # bench MFU reconcile by construction, and a degraded engine's
        # MFU is normalized to the chips it still has.
        global_attribution.configure(
            flops_per_token=cfg.flops_per_token(),
            platform="tpu" if self.on_tpu else "cpu",
            n_chips=(
                int(self.mesh.devices.size) if self.mesh is not None else 1
            ),
            mesh_axes=(
                tuple(str(a) for a in self.mesh.axis_names)
                if self.mesh is not None else ()
            ),
        )

    def _replan_mesh(self) -> None:
        """Shard-loss re-plan (device thread, inside the rebuild):
        walk the ladder to the first rung fitting the surviving
        devices, re-derive all mesh state for it and re-place the
        weights on the new plan. Raises ``MeshLadderExhausted`` when no
        rung fits — the caller's recovery contract already failed the
        in-flight requests with the original exception by then.

        Weight re-placement re-uses each leaf's own partition spec on
        the new mesh (axis names are constant across rungs). Under
        simulated loss (CPU virtual devices, chaos tests) every shard
        is still readable and the device_put is a plain reshard; a
        production backend that lost the only holder of a 'model' shard
        must reload those leaves from the host checkpoint instead —
        see SERVING.md's failure-domain table."""
        ladder = self._mesh_ladder
        assert ladder is not None
        t0 = time.perf_counter()
        old_plan = plan_label(ladder.plan())
        new_mesh = ladder.replan()
        self._apply_mesh_plan(new_mesh)
        from jax.sharding import NamedSharding

        def _put(leaf):
            spec = getattr(getattr(leaf, "sharding", None), "spec", None)
            if spec is not None:
                return jax.device_put(leaf, NamedSharding(new_mesh, spec))
            # No NamedSharding → the leaf was never committed to the
            # old mesh (boot leaves params uncommitted and lets GSPMD
            # place them). Leave it uncommitted: committing it to any
            # single device here would conflict with the new mesh's
            # committed cache at the next jit dispatch.
            return leaf

        self.params = jax.tree_util.tree_map(_put, self.params)
        # Dense device-resident prefix panels live on the OLD mesh's
        # layout (possibly on the dead shard) — drop them; the host
        # tier's entries survive and restore onto the new layout via
        # _restore_place. (The paged index is cleared by every rebuild
        # already.)
        if self.prefix_store is not None:
            # clear(), not eviction: spilling would D2H panels resident
            # on a device that may be the dead one.
            self.prefix_store.clear()
        global_metrics.set_gauge("engine.mesh_plan", float(ladder.rung))
        global_metrics.observe(
            "engine.mesh_rebuild_ms", (time.perf_counter() - t0) * 1e3
        )
        self._log.warning(
            "mesh degraded: %s -> %s (rung %d, lost=%s)",
            old_plan, plan_label(ladder.plan()), ladder.rung, ladder.lost(),
        )

    def _max_safe_strip(self, want: int) -> int:
        """Largest strip ≤ ``want`` whose double-buffered K/V blocks stay
        within a conservative VMEM budget (the pipeline keeps two strips
        in flight; blowing VMEM fails at compile time, mid-serving)."""
        from pilottai_tpu.ops.pallas.paged_attention import strip_vmem_bytes

        item = 1 if self.kv_quantize else jnp.dtype(self.cache_dtype).itemsize
        budget = 8 * 1024 * 1024  # half of a v5e core's ~16 MB VMEM
        strip = max(1, min(want, self.max_pages_per_slot))
        while strip > 1 and strip_vmem_bytes(
            strip, self.page_size, self.cfg.n_kv_heads, self.cfg.head_dim,
            item, self.kv_quantize,
        ) * 2 > budget:
            strip //= 2
        return strip

    def _strip_autotune_keys(self) -> Tuple[str, str]:
        """(key, wide_key) for the persisted page-strip winner. The
        WEIGHT quantization mode (and the int4 scale-group width) is
        part of both: the strip timing runs with the weight set resident
        in HBM, so a winner timed under bf16 weights reflects different
        bandwidth contention than one under int4 — reusing it silently
        across a quant-mode change was the ISSUE 14 satellite bug.
        'none' adds no tag, so pre-existing cache entries stay valid for
        unquantized deployments."""
        mesh_tag = (
            ":mesh" + "x".join(
                f"{a}{s}" for a, s in sorted(dict(self.kv_mesh.shape).items())
                if s > 1
            )
            if self.kv_mesh is not None else ""
        )
        # The scale group only shapes int4 weights — tagging it under
        # int8 would spuriously invalidate cached winners when an
        # operator carries a group setting across modes.
        if self.weight_quant == "int4":
            wq_tag = f":wq{self.weight_quant}:g{self.quant_group}"
        elif self.weight_quant not in (None, "none"):
            wq_tag = f":wq{self.weight_quant}"
        else:
            wq_tag = ""
        key = (
            f"paged_strip:{self.cfg.name}:P{self.page_size}"
            f":nb{self.max_pages_per_slot}:K{self.cfg.n_kv_heads}"
            f":H{self.cfg.head_dim}:hd{self.cfg.n_heads}"
            f":q{int(self.kv_quantize)}:B{self.n_slots}{mesh_tag}{wq_tag}"
        )
        wide_key = (
            f"paged_strip:{self.cfg.name}:P{self.page_size}"
            f":K{self.cfg.n_kv_heads}:H{self.cfg.head_dim}"
            f":hd{self.cfg.n_heads}:q{int(self.kv_quantize)}"
            f":B{self.n_slots}{mesh_tag}{wq_tag}"
        )
        return key, wide_key

    def _autotune_page_strip(self) -> None:
        """Pick the paged-kernel strip width by timing the real kernel on
        the real pool (device thread idle — called from warmup before the
        compile sweep, so the decode ladder compiles against the winner).
        The result persists alongside the XLA compile cache: a warm
        restart reloads the strip its cached executables were built with
        instead of re-timing and recompiling."""
        from pilottai_tpu.utils.compile_cache import (
            load_autotune,
            store_autotune,
        )
        # The key deliberately carries NO decode-chunk terms: the timing
        # exercises the attention kernel alone, so two deployments that
        # differ only in chunk_size / chunk_policy / chunk buckets must
        # share one persisted winner (re-timing on every chunk retune
        # was a measured cold-start tax). The wide key additionally
        # drops the per-slot block count: the strip winner amortizes a
        # per-cell launch floor that is nb-insensitive, so a max_seq
        # change reuses the winner (clamped to the new VMEM-safe range)
        # instead of re-timing.
        # Sharded dispatch times the shard_map-wrapped kernel over
        # per-shard heads/slots — a different launch grid than single
        # chip, so the winner is keyed by mesh shape (empty off-mesh:
        # existing single-chip cache entries stay valid).
        key, wide_key = self._strip_autotune_keys()
        cached = load_autotune(key)
        if cached is None:
            cached = load_autotune(wide_key)
        if cached is not None:
            self.page_strip = self._max_safe_strip(int(cached))
            self._log.info(
                "paged strip %d (autotune cache)", self.page_strip
            )
            return
        try:
            n_blocks = self.max_pages_per_slot
            B = self.n_slots
            # Full-occupancy worst case: every slot at capacity, pages
            # cycling over the real pool (contents are zeros — timing
            # only; the kernel's work is shape-, not value-, dependent).
            tbl = np.arange(B * n_blocks, dtype=np.int32).reshape(
                B, n_blocks
            ) % max(self.num_pages - 1, 1)
            tbl_j = jnp.asarray(tbl)
            last = jnp.full((B,), self.max_seq_len - 1, jnp.int32)
            q = jnp.zeros(
                (B, self.cfg.n_heads, self.cfg.head_dim), self.cfg.dtype
            )
            k_pool, v_pool = self.cache.layers[0]
            sc = None if self.cache.scales is None else self.cache.scales[0]
            # Time the kernel the dispatch path will actually run: on a
            # serving mesh the pool is model-sharded and the unwrapped
            # pallas_call must never see it (it would gather the whole
            # pool per rep — or fail to lower — and pick the strip from
            # gather-dominated timings).
            kernel = _paged_kernel_for(self.kv_mesh)
            candidates = sorted({
                self._max_safe_strip(s) for s in (1, 2, 4, 8)
            })
            timings = {}
            for strip in candidates:
                def run(strip=strip):
                    return kernel(
                        q, k_pool, v_pool, tbl_j, last,
                        n_blocks=n_blocks, n_strip=strip,
                        softcap=self.cfg.attn_softcap,
                        k_scales=None if sc is None else sc[0],
                        v_scales=None if sc is None else sc[1],
                    )
                jax.block_until_ready(run())  # compile outside the timer
                reps = 10
                t0 = time.perf_counter()
                for _ in range(reps):
                    out = run()
                jax.block_until_ready(out)
                timings[strip] = (time.perf_counter() - t0) / reps
            best = min(timings, key=timings.get)
            self.page_strip = best
            store_autotune(key, best)
            store_autotune(wide_key, best)
            self._log.info(
                "paged strip autotune: %s -> strip %d",
                {s: f"{t * 1e3:.2f}ms" for s, t in sorted(timings.items())},
                best,
            )
        except Exception as exc:  # noqa: BLE001 — tuning is best-effort
            self._log.warning(
                "paged strip autotune failed (%s); keeping strip %d",
                exc, self.page_strip,
            )

    def warmup(self, prompt_lens: Optional[Tuple[int, ...]] = None) -> None:
        """Compile the admission path for EVERY prefill bucket plus the
        decode chunk up front, so steady-state serving never waits on the
        compiler. Groups are padded to ``admit_batch``, so one request per
        bucket compiles the same batched write/sample/admit shapes a full
        production wave hits.

        With chunked prefill active, buckets past the segmentation
        threshold never run as monolithic group prefills at serve time —
        and must not compile as such here either: an admit_batch×8192
        prefill executable alone exceeds a v5e's HBM next to 8B int8
        weights (measured: 17.97G of 15.75G). Instead the sweep stops at
        the threshold and one long prompt warms the segment ladder
        (extend_prompt_paged variants + the final tail admission)."""
        if prompt_lens is None:
            cap = self.max_seq_len
            if self.prefill_chunk:
                cap = min(cap, 2 * self.prefill_chunk)
            prompt_lens = tuple(sorted(
                {self._bucket(n) for n in range(1, cap + 1)}
            ))
            if self.prefill_chunk and self.max_seq_len > cap:
                prompt_lens = prompt_lens + (self.max_seq_len - 8,)
        # Strip autotune BEFORE the sweep: the sweep compiles the decode
        # ladder, and it must compile against the strip that will serve.
        if self._strip_autotune_pending:
            self._strip_autotune_pending = False
            self._autotune_page_strip()
        self._warming = True
        try:
            # Adaptive chunking widens the decode grid to
            # (chunk bucket x prefix bound): each prompt bucket runs one
            # warmup request per chunk bucket (pinned via _force_chunk —
            # the policy alone would pick the smallest bucket for these
            # 2-token requests), so a serve-time bucket switch never
            # waits on the compiler. Prompt ids shift per pass so the
            # repeats don't short-circuit into the prefix-cache tail
            # path, which would skip the full-prefill compile.
            for plen in prompt_lens:
                plen = min(plen, self.max_seq_len - 8)
                for ci, cb in enumerate(self.chunk_buckets):
                    self._force_chunk = cb
                    req = GenRequest(
                        prompt_ids=list(range(2 + ci, 2 + ci + plen)),
                        max_new_tokens=2,
                    )
                    self.submit(req)
                    req.future.result(timeout=900)
        finally:
            self._warming = False
            self._force_chunk = None

    # ------------------------------------------------------------------ #
    # Submission (any thread)
    # ------------------------------------------------------------------ #

    def queue_depth(self) -> int:
        """Requests submitted but not yet admitted to a slot (any thread;
        approximate — the containers move concurrently). Prepared-but-
        not-yet-dispatched admissions still count: they hold no slot."""
        return (
            self._pending.qsize() + len(self._backlog) + self._prepped_reqs
        )

    @property
    def watchdog_source(self):
        """This engine's ``EngineHealth`` source name (None without a
        watchdog) — the serving cell checks per-replica health by it."""
        return self._watchdog.name if self._watchdog is not None else None

    def routing_signals(self) -> Dict[str, Any]:
        """The replica-side routing signals of ISSUE 11, as one cheap
        snapshot: queue depth + shed-limit fraction, degrade rung and
        watchdog verdict. Per-class SLO burn comes from the cell's own
        per-replica tracker (in-process) or the control-plane heartbeat
        (remote) — the engine doesn't know its replica's service
        classes."""
        depth = self.queue_depth()
        # Without admission control there is no hard shed depth; 8 slots'
        # worth of backlog per slot is the soft norm the router uses to
        # compare replicas (never to shed — only max_queue_depth sheds).
        limit = self.max_queue_depth or 8 * self.n_slots
        return {
            "queue_depth": depth,
            "queue_frac": depth / max(limit, 1),
            "degrade_level": self.degrade.level(),
            "healthy": self._watchdog is None or not self._watchdog.stalled,
            # Degraded-mesh rung (0 = boot plan): the cell's router
            # down-scores replicas serving on a sub-mesh, and the cell
            # prefers migrating sessions off them.
            "mesh_rung": (
                self._mesh_ladder.rung
                if self._mesh_ladder is not None else 0
            ),
        }

    def export_session_kv(self, session_id: str):
        """Cross-replica migration, source side (ISSUE 11): the
        session's KV lineage in the host tier's transfer format, taken
        under the slot lock so no spill/restore interleaves. None when
        the KV cache tier is off or the session is unknown — callers
        treat that as 'nothing to move' (the target re-prefills)."""
        if self.kvcache is None or self.kvcache.host is None:
            return None
        with self._lock:
            return self.kvcache.export_session(session_id)

    def import_session_kv(self, export) -> Dict[str, int]:
        """Cross-replica migration, target side: land the exported
        entries in this engine's host tier so the session's next turn
        restores here instead of re-prefilling. Returns the accepted
        entry/token counts (budget pressure may reject some)."""
        if self.kvcache is None or self.kvcache.host is None or not export:
            return {"accepted": 0, "tokens": 0, "rejected": 0}
        with self._lock:
            return self.kvcache.import_session(export)

    def export_request_kv(self, prompt_ids, session_id: Optional[str] = None):
        """Prefill→decode handoff, source side (ISSUE 19): package the
        KV a just-prefilled request left in this engine's cache tier —
        the admission-time dense panel, pinned page chain, or host
        spills covering the prompt — as the same checksummed wire
        frames session migration uses. Copy-only (no session pin
        moves): a failed handoff leaves this replica able to serve the
        colocated fallback from its own warm cache. Taken under the
        slot lock so the export overlaps only between device steps,
        never mid-gather. None when the cache tier is off or holds
        nothing for this prompt — the caller serves colocated."""
        if self.kvcache is None:
            return None
        with self._lock:
            return self.kvcache.export_request(
                tuple(prompt_ids), session_id=session_id
            )

    def import_request_kv(self, export) -> Dict[str, int]:
        """Prefill→decode handoff, target side: land the prefilled
        request's KV in this engine's host tier so admitting the
        request here restores it (``_PreparedAdmission`` in prefix /
        prefix_paged mode — decode resumes, no re-prefill). Same
        integrity gate as session import: a corrupt frame rejects,
        counts ``engine.kvcache.integrity_failures``, and the request
        falls back to colocated serving."""
        if self.kvcache is None or self.kvcache.host is None or not export:
            return {"accepted": 0, "tokens": 0, "rejected": 0}
        # Deliberately NOT under the batcher lock: the import only
        # writes the host tier (which takes its own lock per op), and
        # holding the admission lock through checksums + array copies
        # of a whole prompt's KV would stall the decode loop this tier
        # exists to keep smooth.
        return self.kvcache.import_session(export)

    def saturated(self) -> bool:
        return (
            self.max_queue_depth is not None
            and self.queue_depth() >= self.max_queue_depth
        )

    def _shed_reason(self, request: GenRequest) -> Optional[str]:
        """Why this submit must shed, or None. Per-SLO-class thresholds:
        interactive traffic sheds at the full ``max_queue_depth``; any
        other class (batch) at ``batch_shed_frac`` of it — under backlog
        pressure the fan-out branches nobody is watching drop before the
        stream a human is. The degradation ladder's last rung sheds
        batch outright: a faulting engine's remaining capacity defends
        the interactive SLO class.

        Only the literal ``batch`` class gets the early-shed policy:
        ``slo_class`` is a free-form client string (the HTTP edge
        validates it, direct SDK callers may not), and treating every
        unknown string as batch would silently early-shed typo'd or
        deployment-defined latency-sensitive classes."""
        cls = self._shed_class(request)
        if (
            cls == "batch"
            and self.degrade.level() >= degrade_levels.SHED_BATCH
        ):
            return (
                f"engine degraded to level {degrade_levels.SHED_BATCH} "
                f"({degrade_levels.LEVEL_NAMES[degrade_levels.SHED_BATCH]}); "
                f"shedding {cls}-class requests"
            )
        limit = self.max_queue_depth
        if limit is None:
            return None
        if cls == "batch":
            limit = max(1, int(limit * self.batch_shed_frac))
        depth = self.queue_depth()
        if depth >= limit:
            return (
                f"engine queue depth {depth} at configured "
                f"{cls}-class limit {limit}; shedding"
            )
        return None

    @staticmethod
    def _shed_class(request: GenRequest) -> str:
        """Shed-policy class: ``batch``, ``interactive``, or ``other``
        (unknown strings — interactive semantics, but a bounded metrics
        key so free-form client strings can't grow the registry)."""
        cls = request.slo_class or "interactive"
        return cls if cls in ("interactive", "batch") else "other"

    def submit(self, request: GenRequest) -> Future:
        # Admission control first: a shed request must cost nothing — no
        # queue entry, no truncation work, no future resolution. Raising
        # (rather than failing the future) lets the HTTP edge turn this
        # into a structured 429 before any engine state exists for it.
        shed = self._shed_reason(request)
        if shed is not None:
            cls = self._shed_class(request)
            global_metrics.inc("engine.shed")
            global_metrics.inc(f"engine.shed.{cls}")
            global_metrics.set_gauge(
                "engine.queue_depth", float(self.queue_depth())
            )
            global_steps.record(
                "engine.shed",
                queue_depth=self.queue_depth(),
                max_queue_depth=self.max_queue_depth,
                slo_class=cls,
                trace_id=request.trace_id,
            )
            raise EngineOverloaded(shed)
        # A request born expired (edge queueing, client retry storms)
        # fails immediately instead of wasting a prefill.
        if (
            request.deadline is not None
            and time.monotonic() >= request.deadline
        ):
            global_metrics.inc("engine.expired")
            request.future.set_exception(
                DeadlineExceeded("request deadline expired before submit")
            )
            return request.future
        # An empty prompt would be indistinguishable from an admission
        # padding row (lens <= 0 => dropped) and hang; decode from a single
        # pad token instead.
        if not request.prompt_ids:
            request.prompt_ids = [0]
        # Leave room for at least one generated token; clamp the keep window
        # so it can never be <= 0 (a negative-zero slice would keep the whole
        # oversized prompt and crash the prefill copy).
        keep = self.max_seq_len - 1 - request.max_new_tokens
        keep = min(max(keep, 1), self.max_seq_len - 2)
        if len(request.prompt_ids) > keep:
            request.prompt_ids = request.prompt_ids[-keep:]
        self._pending.put(request)
        # Gauge on EVERY enqueue, not just admit/fold/shed: a backlog
        # building while the device thread is pinned (e.g. segmenting
        # one long prefill) must be visible to the autoscaler's
        # engine_queue_frac signal as it grows, not after it drains.
        global_metrics.set_gauge(
            "engine.queue_depth", float(self.queue_depth())
        )
        self._wake.set()
        self._prep_wake.set()
        return request.future

    # ------------------------------------------------------------------ #
    # Device loop (device thread only)
    # ------------------------------------------------------------------ #

    def _bucket(self, n: int) -> int:
        # Power-of-two buckets only. Finer (1.5x-midpoint) buckets save
        # padded prefill FLOPs but triple the executable count, which
        # thrashes bounded compile/executable caches — measured as
        # multi-second dispatch stalls on every admission.
        b = self.min_bucket
        while b < n:
            b *= 2
        return min(b, self.max_seq_len)

    def _tail_bucket(self, n: int) -> int:
        """Prefix-cache tail ladder: 8-floor power-of-two (the 64-floor
        prompt ladder would spend ~25% of a full 8B prefill on a
        one-token tail)."""
        b = 8
        while b < n:
            b *= 2
        return b

    def _prefix_hit(self, req: GenRequest):
        """Prefix-store match that also fits: the tail write lands at
        [prefix_len, prefix_len + tail_bucket) and dynamic_update_slice
        CLAMPS out-of-range starts — an oversized hit would silently
        shift the tail onto the cached prefix rows (KV corruption), so
        it must fall back to the full-prefill path instead.

        Paged cache: block-granular radix match instead — returns a
        PageNode whose ``path_pages`` get mapped (not copied) into the
        slot's block table. No clamp hazard there (writes go through the
        table), so the only fit check is that the prefix leaves room.

        Both shapes route through ONE lookup — the KV cache tier
        (engine/kvcache/index.py): device-resident hit first, then the
        host-RAM cold tier, whose hit RESTORES the spilled KV (async
        H2D staged here on the prep thread; the pool write for paged
        chains runs on the device thread via _apply_restores) instead
        of re-prefilling. Called under the slot lock."""
        if self.kvcache is None or self._warming:
            # Warmup gate: the sweep's ascending same-start prompts
            # would otherwise hit earlier rungs' entries and admit via
            # the tail path — skipping the full-prefill compile the
            # sweep exists to guarantee.
            return None
        count = not req.kv_counted
        req.kv_counted = True
        if self.page_index is not None:
            need = min(
                len(req.prompt_ids) + req.max_new_tokens, self.max_seq_len
            )
            node, rec = self.kvcache.lookup_paged(
                req.prompt_ids,
                session_id=req.session_id,
                alloc=self.alloc,
                max_seq_len=self.max_seq_len,
                need_tokens=need,
                epoch=self._alloc_epoch,
                count=count,
            )
            if rec is not None:
                self._pending_restores.append(rec)
            if node is None:
                return None
            if node.depth * self.page_size >= self.max_seq_len:
                return None
            return node
        if self.prefix_store is None:
            return None
        n = len(req.prompt_ids)

        def fits(plen: int, p_bucket: int) -> bool:
            return (
                plen + self._tail_bucket(n - plen) <= self.max_seq_len
                and p_bucket <= self.max_seq_len
            )

        return self.kvcache.lookup_dense(
            req.prompt_ids, session_id=req.session_id, fits=fits,
            bucket=self._bucket, count=count,
        )

    def _decode_bucket(self, n: int) -> int:
        """Prefix-bound bucket for a decode chunk: the prefill bucket
        ladder with a 128 floor (so tiny bounds don't churn recompiles and
        executable variants stay O(log S)). Sharing the ladder means
        warmup's prefill sweep compiles every decode variant too."""
        return max(self._bucket(n), min(128, self.max_seq_len))

    def _free_slot_indices(self) -> List[int]:
        free = [i for i, s in enumerate(self._slots) if s is None]
        if self.data_groups <= 1 or len(free) <= 1:
            return free
        # Data-axis admission replication (ISSUE 13): slots partition
        # into ``data_groups`` contiguous blocks — the exact split the
        # batch-dim NamedSharding uses — and selection interleaves
        # across groups, least-occupied first. A bursty admission wave
        # then spreads its requests over every data shard's slots
        # instead of filling group 0 while groups 1..D-1 idle, so a
        # {'model':M,'data':D} engine genuinely serves D concurrent
        # decode groups.
        per = self.n_slots // self.data_groups
        groups: List[List[int]] = [[] for _ in range(self.data_groups)]
        for i in free:
            groups[min(i // per, self.data_groups - 1)].append(i)
        order = sorted(
            range(self.data_groups),
            key=lambda g: (per - len(groups[g]), g),  # occupancy, stable
        )
        out: List[int] = []
        for rank in range(per):
            for g in order:
                if rank < len(groups[g]):
                    out.append(groups[g][rank])
        return out

    def _expire_deadlines(self) -> None:
        """Force-release occupied slots whose deadline passed mid-decode
        (device thread, once per loop cycle). Mirrors _check_finished's
        release protocol: slot → None now, the stop/free device ops run
        through ``_release`` at the next admission, and the ``slot is
        None`` guard plus the admission generation stamp keep any
        still-in-flight chunk from folding into the freed slot."""
        now = time.monotonic()
        expired: List[Tuple[int, _Slot]] = []
        with self._lock:
            for i, slot in enumerate(self._slots):
                if slot is None:
                    continue
                req = slot.request
                if req.deadline is None or now < req.deadline:
                    continue
                self._slots[i] = None
                self._release.append(i)
                self._release_pages_locked(i)
                global_metrics.inc("engine.expired")
                global_metrics.inc("engine.deadline_releases")
                expired.append((i, slot))
                if not req.future.done():
                    req.future.set_exception(DeadlineExceeded(
                        f"request deadline expired after "
                        f"{len(slot.generated)} generated token(s)"
                    ))
        if expired:
            self._prep_wake.set()  # freed pages/slots — prep can select
        # Observability OUTSIDE the lock: the black-box dump snapshots
        # the step ring and may write a journal line — file IO must not
        # stall the reader thread's folds.
        for i, slot in expired:
            req = slot.request
            if req.trace_id is None:
                continue
            end = time.perf_counter()
            global_tracer.emit(
                "engine.batch_decode",
                trace_id=req.trace_id,
                parent_id=req.parent_span_id,
                start=req.submitted_at,
                end=end,
                slot=i,
                prompt_len=slot.prompt_len,
                tokens=len(slot.generated),
                status="deadline",
            )
            global_blackbox.dump(
                "deadline_expired",
                trace_id=req.trace_id,
                slot=i,
                generated_tokens=len(slot.generated),
                prompt_len=slot.prompt_len,
            )

    def _drain_pending(self) -> None:
        """Drain the thread-safe submission queue into the FIFO backlog
        (page-gated admission needs to peek at the head without losing
        submission order). Runs on the prep thread when overlapping,
        the device thread inline — exactly one drainer per mode."""
        while True:
            try:
                self._backlog.append(self._pending.get_nowait())
            except queue.Empty:
                break

    # ------------------------------------------------------------------ #
    # DAG-aware backlog scheduling (pilottai_tpu/sched/, ROADMAP item 4)
    # ------------------------------------------------------------------ #

    def _eff_priority(self, req: GenRequest, now: float) -> int:
        """Effective priority: the request's rung plus aging-floor
        promotions — one rung per ``priority_aging_s`` of backlog wait,
        so sustained critical-path traffic can delay LOW work but never
        starve it (the starvation regression test pins this). Promotion
        deltas are counted once per request (``sched.priority_aged``)."""
        p = max(0, min(int(req.priority), 3))
        if self.priority_aging_s > 0 and p < 3:
            aged = int((now - req.submitted_at) / self.priority_aging_s)
            if aged > 0:
                boosted = min(3, p + aged)
                if boosted - p > req.aged_rungs:
                    global_metrics.inc(
                        "sched.priority_aged", boosted - p - req.aged_rungs
                    )
                    req.aged_rungs = boosted - p
                p = boosted
        return p

    def _order_backlog_locked(self) -> None:
        """Priority-order the backlog in place (slot lock held;
        ``sched_policy="dag"`` only). Stable sort by effective priority
        then submission time — uniform-priority traffic therefore keeps
        EXACT FIFO order (aging is monotone in wait, so it can never
        invert two same-priority requests), and recovered re-admissions
        (earliest ``submitted_at``) stay at the head.

        Gang handling: members of one gang sort together on the gang's
        BEST effective priority (one critical sibling lifts the whole
        fan-out) and its earliest submission; a gang still missing
        siblings, or whose whole membership doesn't fit the free
        slots+pages right now, is DEFERRED behind ungoverned work until
        either both hold or its bounded wait (``gang_wait_ms``) expires
        — after which it admits partially rather than holding the line
        forever. Ordering changes only WHEN a request admits, never
        what it computes: greedy output is byte-identical under any
        ordering (tests/test_sched.py pins it)."""
        now = time.perf_counter()
        items = list(self._backlog)
        members: Dict[str, List[GenRequest]] = {}
        for r in items:
            if r.gang_id:
                members.setdefault(r.gang_id, []).append(r)
        # Prune the wait clocks of gangs that fully left the backlog.
        # _gang_counted deliberately survives (bounded, see __init__):
        # it marks gangs that already dispatched, so their stragglers
        # skip deferral below.
        for gid in list(self._gang_seen):
            if gid not in members:
                self._gang_seen.pop(gid, None)
        free_slots = sum(
            1 for i, s in enumerate(self._slots)
            if s is None and i not in self._prep_reserved
        ) - len(self._release)
        deferred: set = set()
        gang_eff: Dict[str, int] = {}
        gang_anchor: Dict[str, float] = {}
        for gid, reqs in members.items():
            seen = self._gang_seen.setdefault(gid, now)
            gang_eff[gid] = max(self._eff_priority(r, now) for r in reqs)
            gang_anchor[gid] = min(r.submitted_at for r in reqs)
            if gid in self._gang_counted:
                # The gang already dispatched: a late-arriving or
                # fault-recovered sibling admits at its own priority
                # NOW — waiting for siblings that already ran would
                # manufacture the straggler this machinery removes.
                continue
            if (now - seen) * 1e3 >= self.gang_wait_ms:
                continue  # wait bound expired: partial-admit fallback
            size = max((r.gang_size for r in reqs), default=0)
            if size > self.n_slots:
                # Unsatisfiable by construction: a gang wider than the
                # engine can never co-admit, so deferring it would be
                # pure priority inversion (lower-priority work taking
                # every freed slot for the whole wait bound). Admit at
                # priority immediately; the pop-time accounting counts
                # it partial.
                continue
            complete = size <= len(reqs)
            capacity = len(reqs) <= max(free_slots, 0)
            if capacity and self.alloc is not None:
                # Conservative whole-gang page check (ignores prefix
                # sharing — a false defer only costs the bounded wait).
                need_pages = sum(
                    self.alloc.pages_needed(min(
                        len(r.prompt_ids) + r.max_new_tokens,
                        self.max_seq_len,
                    ))
                    for r in reqs
                )
                if need_pages > self.num_pages - 1:
                    continue  # can never fit the pool: same clamp
                capacity = need_pages <= self.alloc.free_pages
            if not (complete and capacity):
                deferred.add(gid)
        # The selection loop consults this: a deferred gang at the
        # backlog head BLOCKS (like a page-gated head) instead of
        # admitting a sibling subset early — the sort below already put
        # every admissible request in front of it, so only the gang
        # itself waits. Recomputed every selection; the wait bound
        # guarantees it clears.
        self._gang_deferred = deferred
        if len(items) < 2:
            return

        def key(r: GenRequest):
            if r.gang_id:
                return (
                    1 if r.gang_id in deferred else 0,
                    -gang_eff[r.gang_id],
                    gang_anchor[r.gang_id],
                    r.submitted_at,
                )
            return (0, -self._eff_priority(r, now), r.submitted_at, 0.0)

        items.sort(key=key)
        self._backlog = deque(items)

    def _note_admission_pop(self, req: GenRequest) -> None:
        """Backlog-pop bookkeeping (slot lock held): the per-priority
        submit→admission wait histogram — priority inversion shows up
        as a crossed percentile here, not in a debugger — and one
        admit/partial outcome count per gang."""
        wait_ms = max(0.0, (time.perf_counter() - req.submitted_at) * 1e3)
        prio = _PRIO_NAMES[max(0, min(int(req.priority), 3))]
        global_metrics.observe(f"engine.backlog_wait_ms.{prio}", wait_ms)
        gid = req.gang_id
        # Gang accounting only under the policy that actually groups
        # gangs — under "fifo" the outcome counters would be
        # meaningless ("partial" = siblings hadn't arrived yet) and the
        # dispatched-gang memory would never serve its purpose.
        if (
            self.sched_policy == "dag"
            and gid and gid not in self._gang_counted
        ):
            self._gang_counted[gid] = True
            while len(self._gang_counted) > 1024:
                self._gang_counted.popitem(last=False)
            present = 1 + sum(1 for r in self._backlog if r.gang_id == gid)
            if req.gang_size and present < req.gang_size:
                global_metrics.inc("sched.gang_partial")
            else:
                global_metrics.inc("sched.gang_admits")

    # ------------------------------------------------------------------ #
    # Speculative stage pre-warm (sched/ → prep thread → KV cache tier)
    # ------------------------------------------------------------------ #

    def prewarm(
        self, prompt_ids: List[int], session_id: Optional[str] = None
    ) -> bool:
        """Stage a KV-tier lookup for a PREDICTED prompt prefix (any
        thread; advisory). The lookup runs on the prep thread
        (``_drain_prewarms``): a host-tier hit starts its restore
        exactly as a real admission's would — async H2D staged off the
        device thread, pool scatter via ``_apply_restores`` — so when
        the predicted request actually arrives its prefill finds
        device-resident KV. No slot, no decode, no output: pre-warm can
        reorder nothing and is byte-identity-neutral by construction.
        Returns False when the engine cannot pre-warm (no KV cache
        tier, warming up, or the advisory queue is full)."""
        if self.kvcache is None or self._warming or not prompt_ids:
            global_metrics.inc("sched.prewarm_skipped")
            return False
        if len(self._prewarm_queue) >= self._prewarm_queue.maxlen:
            global_metrics.inc("sched.prewarm_skipped")
            return False
        self._prewarm_queue.append((list(prompt_ids), session_id))
        self._prep_wake.set()
        if not self.overlap_admission:
            self._wake.set()
        return True

    def _drain_prewarms(self) -> None:
        """Run queued pre-warm lookups (prep thread when overlapping,
        device thread inline — the same thread that runs selection, so
        the slot-lock discipline is identical to ``_prefix_hit``)."""
        while True:
            try:
                ids, sid = self._prewarm_queue.popleft()
            except IndexError:
                return
            global_metrics.inc("sched.prewarms")
            if self.kvcache is None or self._warming:
                global_metrics.inc("sched.prewarm_skipped")
                continue
            if (
                self.page_index is None
                and len(ids) <= self.kvcache.min_len
            ):
                # A dense entry stores the prompt minus its last token,
                # so anything at or below the floor can never hit
                # (KVCacheIndex.min_len — the documented
                # engine_prefix_min_len knob).
                self._warn_min_len(len(ids), "pre-warm")
                global_metrics.inc("sched.prewarm_skipped")
                continue
            hit = False
            try:
                with self._lock:
                    if self.page_index is not None:
                        node, rec = self.kvcache.lookup_paged(
                            ids, session_id=sid, alloc=self.alloc,
                            max_seq_len=self.max_seq_len,
                            need_tokens=min(len(ids), self.max_seq_len),
                            epoch=self._alloc_epoch, count=False,
                        )
                        if rec is not None:
                            self._pending_restores.append(rec)
                        hit = node is not None or rec is not None
                    elif self.prefix_store is not None:
                        n = len(ids)

                        def fits(plen: int, p_bucket: int) -> bool:
                            return (
                                plen + self._tail_bucket(max(n - plen, 1))
                                <= self.max_seq_len
                                and p_bucket <= self.max_seq_len
                            )

                        hit = self.kvcache.lookup_dense(
                            ids, session_id=sid, fits=fits,
                            bucket=self._bucket, count=False,
                        ) is not None
            except Exception as exc:  # noqa: BLE001 — advisory path
                self._log.warning("prewarm lookup failed: %s", exc)
                continue
            if hit:
                global_metrics.inc("sched.prewarm_hits")
                # A staged restore scatters at the device thread's next
                # _apply_restores drain — wake it.
                self._wake.set()

    def _warn_min_len(self, n: int, where: str) -> None:
        """One-shot dense-store floor warning: prompts at or below
        ``min_len`` silently never cache (entries store the prompt
        minus its last token) — say so ONCE per engine instead of
        letting bench or pre-warm prompts miss forever with no
        signal."""
        if self._warned_min_len:
            return
        self._warned_min_len = True
        floor = (
            self.kvcache.min_len if self.kvcache is not None
            else (self.prefix_store.min_len
                  if self.prefix_store is not None else 0)
        )
        self._log.warning(
            "%s prompt of %d token(s) is at or below the dense "
            "prefix-store floor (min_len=%d): prompts this short are "
            "never cached or pre-warmed — lower engine_prefix_min_len "
            "(docs/SERVING.md) if this workload should cache",
            where, n, floor,
        )

    def _admit(self) -> None:
        """Stop released slots, then dispatch pending admissions. With
        overlapped admission (the default) the groups arrive PREBUILT
        from the prep thread and this thread only performs the device
        dispatches — the prefill lands on the device stream behind the
        in-flight decode chunks, with no host-side array building in
        between. Inline mode prepares on this thread (the seed path;
        byte-identical output either way). Admits until slots or
        pending run out — completions arrive in waves, and refilling
        only one group per chunk would leave slots idle."""
        # Pending host-tier restores scatter into the pool FIRST: any
        # admission this cycle may map the restored pages.
        self._apply_restores()
        with self._lock:
            released = list(self._release)
            self._release.clear()

        if released:
            # Fixed-size release vector (padded with OOB indices) so the
            # jitted release path compiles exactly once. Must precede the
            # prompt writes below when a released slot is being reused.
            # The slot's KV pages were already returned to the pool at
            # the moment it finished/expired (_release_pages_locked —
            # per-slot early release, so backfill admissions are funded
            # one pipeline cycle earlier); only the device-side stop and
            # length-free ops remain for this thread.
            rel = np.full((self.n_slots,), self.n_slots, np.int32)
            rel[: len(released)] = released[: self.n_slots]
            rel_j = jnp.asarray(rel)
            self.dstate = release_decode(self.dstate, rel_j)
            self.cache = free_slots(self.cache, rel_j)
            # The released slots are selectable the moment their device
            # stop ops are enqueued — tell the prep thread.
            self._prep_wake.set()

        if not self.overlap_admission:
            self._drain_pending()
            self._drain_prewarms()  # inline mode: same-thread parity

        # A segmented admission in flight: advance it by ONE segment and
        # yield the cycle — the caller dispatches a decode chunk next, so
        # live slots keep decoding between segments.
        if self._segmenting is not None:
            self._advance_segment()
            return

        preps: List[Any] = []
        if self.overlap_admission:
            while True:
                try:
                    item = self._prepped.get_nowait()
                except queue.Empty:
                    break
                with self._lock:
                    n = (
                        1 if isinstance(item, _SegmentStart)
                        else len(item.group)
                    )
                    self._prepped_reqs = max(0, self._prepped_reqs - n)
                preps.append(item)
            if preps:
                self._prep_wake.set()  # look-ahead slots freed up
        else:
            groups, seg, epoch = self._select_groups()
            for entry, group in groups:
                try:
                    preps.append(
                        self._prepare_prefill(group, entry, epoch=epoch)
                    )
                except Exception as exc:  # noqa: BLE001 — host-side prep
                    # Array building touches no device state: fail these
                    # requests only, the engine stays serviceable.
                    self._log.error(
                        "admission prep failed: %s", exc, exc_info=True
                    )
                    self._fail_group(group, exc)
            if seg is not None:
                preps.append(_SegmentStart(seg, epoch))
        self._dispatch_admissions(preps)

        # A segmentation picked up in THIS call starts immediately (the
        # early-return gate above owns advancing it on later cycles).
        if self._segmenting is not None:
            self._advance_segment()

    def _dispatch_admissions(self, preps: List[Any]) -> None:
        """Dispatch prepared admissions in order (device thread only),
        with the per-group failure semantics of the inline path: a
        failed dispatch fails only its group; a failure that consumed
        the donated device state rebuilds it and REQUEUES everything not
        yet dispatched (their page allocations died with the old
        allocator — prefilling against the fresh one's sentinel rows
        silently produced garbage completions, test_engine_mesh.py)."""
        # Stale preps requeue in ONE batch after the loop: per-item
        # _requeue_prepared calls would each appendleft in front of the
        # previous call's requests, reversing FIFO admission order (and
        # under page pressure FIFO is what stops head-of-line reqs from
        # starving). Stale items precede fresh ones in `preps`, and the
        # batch requeue runs after any preps[gi+1:] requeue below, so
        # the earlier-submitted stale requests land at the very head.
        stale_preps: List[Any] = []
        for gi, prep in enumerate(preps):
            if prep.epoch != self._alloc_epoch:
                stale_preps.append(prep)
                continue
            if isinstance(prep, _SegmentStart):
                if stale_preps:
                    # FIFO: the stale preps carry EARLIER-submitted
                    # requests — installing this fresh segmentation
                    # would run its multi-cycle prefill ahead of them
                    # (prep stays parked on _seg_pending meanwhile).
                    # Requeue everything in submission order instead
                    # and let selection re-form the wave.
                    self._requeue_prepared(
                        stale_preps + [prep] + preps[gi + 1:]
                    )
                    stale_preps = []
                    break
                self._segmenting = prep.seg
                self._seg_epoch = prep.epoch
                # Group formation stopped at the segmentation (FIFO
                # order), so nothing can legitimately follow it.
                self._requeue_prepared(preps[gi + 1:])
                break
            # Deadline re-check at dispatch time: a prep can wait in
            # _prepped across a whole chunked-prefill segmentation
            # (admission early-returns for its duration — seconds for an
            # 8K prompt through the tunnel), long past the selection-time
            # sweep. A group whose every member expired or was cancelled
            # meanwhile would spend a full fused prefill on 100% dead
            # work; drop it instead. Mixed groups still dispatch — the
            # live members need the prefill anyway, and the next
            # _expire_deadlines cycle reaps the rest (releasing their
            # pages mid-dispatch here would race the in-flight page
            # writes against a concurrent re-allocation).
            now = time.monotonic()
            if all(
                req.cancelled or req.future.cancelled()
                or (req.deadline is not None and now >= req.deadline)
                for _, req in prep.group
            ):
                n_expired = sum(
                    1 for _, req in prep.group
                    if req.deadline is not None and now >= req.deadline
                    and not req.future.done()
                )
                if n_expired:
                    global_metrics.inc("engine.expired", n_expired)
                self._fail_group(prep.group, DeadlineExceeded(
                    "request deadline expired before admission dispatch"
                ))
                continue
            try:
                self._dispatch_prefill(prep)
            except Exception as exc:  # noqa: BLE001 — contain to this group
                self._log.error("prefill failed: %s", exc, exc_info=True)
                # A failed prefill DISPATCH is a device fault: the group
                # re-admits (bounded strikes) instead of failing — no
                # tokens existed for it yet, so the retry is transparent.
                self._fail_group(prep.group, exc, recover=True)
                self.degrade.record_fault("prefill")
                # admit_group donates cache/dstate/sampling: a dispatch
                # that failed mid-flight may have consumed them. If so the
                # engine state is gone with it — recover in-flight work
                # and rebuild fresh state so the engine stays serviceable
                # (silently keeping deleted buffers would crash the next
                # chunk and kill every request anyway, without recovery).
                if self.cache.lengths.is_deleted():
                    self._fail_occupied_slots(exc, record_fault=False)
                    self._rebuild_device_state(reason="prefill_failure")
                    self._requeue_prepared(preps[gi + 1:])
                    break
        if stale_preps:
            self._requeue_prepared(stale_preps)

    def _fail_group(self, group: List[Tuple[int, GenRequest]],
                    exc: Exception, recover: bool = False) -> None:
        """Fail one admission group's requests and return their
        resources (either thread). With ``recover=True`` (the prefill
        DISPATCH failure path — a device fault, not a client one) the
        group's requests requeue at the backlog head instead, bounded
        by the same per-request strike budget as slot recovery: an
        admission group has no accepted tokens yet, so its replay is a
        pure re-admission."""
        now = time.monotonic()
        t_snap = time.perf_counter()
        requeue: List[GenRequest] = []
        with self._lock:
            for idx, req in group:
                self._slots[idx] = None
                self._prep_reserved.discard(idx)
                # Reclaim the group's KV pages (under the lock — the
                # reader thread releases pages too) — leaking them here
                # permanently shrinks the pool AND trips allocate()'s
                # held-pages invariant when the slot is reused.
                if self.alloc is not None:
                    self.alloc.release(idx)
                if req.future.done():
                    continue
                if not recover:
                    req.future.set_exception(exc)
                    continue
                if self._recovery_decision_locked(req, exc, now, t_snap):
                    requeue.append(req)
            for req in reversed(requeue):
                self._backlog.appendleft(req)
        if requeue:
            global_metrics.inc("engine.recovery_requeued", len(requeue))
            self._prep_wake.set()
            self._wake.set()

    def _requeue_prepared(self, items: List[Any]) -> None:
        """Return prepared-but-undispatchable admissions to the backlog
        HEAD, in order (device thread only). Their page allocations are
        dropped (release is idempotent, and a no-op on a freshly rebuilt
        allocator) and their slots unreserved; the next selection
        re-admits them against live state. Anything the prep thread had
        queued BEHIND them drains too — under the prep gate, so no
        concurrent prep round can land an item after the drain."""
        with self._prep_gate:
            drained: List[Any] = []
            while True:
                try:
                    drained.append(self._prepped.get_nowait())
                except queue.Empty:
                    break
            with self._lock:
                self._prepped_reqs = 0
                reqs: List[GenRequest] = []
                for item in list(items) + drained:
                    if isinstance(item, _SegmentStart):
                        idx, req = item.seg[0], item.seg[1]
                        self._seg_pending = False
                        pairs = [(idx, req)]
                    else:
                        pairs = item.group
                    for idx, req in pairs:
                        self._prep_reserved.discard(idx)
                        if self.alloc is not None:
                            self.alloc.release(idx)
                        reqs.append(req)
                for req in reversed(reqs):
                    self._backlog.appendleft(req)
        self._prep_wake.set()
        self._wake.set()

    def _prep_loop(self) -> None:
        """Admission-prep thread: everything host-side an admission
        needs — backlog draining, deadline/cancel sweeps at the head,
        slot selection, page allocation, prefix matching and
        staging-buffer packing — runs HERE, off the device thread's
        dispatch path. The slot-lock + allocator-under-lock discipline
        (PR 4's early-release work) is what makes this safe: selection
        and allocation serialize against the reader's fold-time releases
        exactly as they did on the device thread. Look-ahead is bounded
        (``_prep_depth`` waves) so prep can never run unboundedly ahead
        of installs."""
        while not self._stop.is_set():
            self._drain_pending()
            # Speculative pre-warms ride the prep thread too: the
            # restore staging (host memcpy + async H2D) lands exactly
            # where a real admission's would, never on the device
            # thread.
            self._drain_prewarms()
            if (
                self._segmenting is not None
                or self._seg_pending
                or self._prepped.qsize() >= self._prep_depth
                or (not self._backlog and not self._pending.qsize())
            ):
                self._prep_wake.wait(timeout=0.02)
                self._prep_wake.clear()
                continue
            made = False
            sel_failed = False
            with self._prep_gate:
                if self._stop.is_set():
                    break
                try:
                    groups, seg, epoch = self._select_groups()
                except Exception as exc:  # noqa: BLE001 — keep prep alive
                    # A dead prep thread wedges every future admission
                    # (requests queue forever, the breaker opens on the
                    # timeouts). Best effort: log loudly and keep the
                    # thread alive — later selections can still serve
                    # the rest of the backlog. The backoff wait happens
                    # OUTSIDE the gate: sleeping with it held would
                    # block the device thread's _requeue_prepared (the
                    # rebuild/segmentation recovery paths) for 100 ms a
                    # pop — a host-side stall of the very dispatch loop
                    # this pipeline exists to keep fed.
                    self._log.error(
                        "admission selection failed: %s", exc,
                        exc_info=True,
                    )
                    sel_failed = True
                    groups, seg = [], None
                for entry, group in groups:
                    try:
                        prep = self._prepare_prefill(
                            group, entry, epoch=epoch
                        )
                    except Exception as exc:  # noqa: BLE001 — prep only
                        self._log.error(
                            "admission prep failed: %s", exc, exc_info=True
                        )
                        self._fail_group(group, exc)
                        continue
                    with self._lock:
                        self._prepped_reqs += len(group)
                    self._prepped.put(prep)
                    made = True
                if seg is not None:
                    self._seg_pending = True
                    with self._lock:
                        self._prepped_reqs += 1
                    self._prepped.put(_SegmentStart(seg, epoch))
                    made = True
            if sel_failed:
                self._prep_wake.wait(timeout=0.1)
                self._prep_wake.clear()
                continue
            if made:
                self._wake.set()
            else:
                self._prep_wake.wait(timeout=0.02)
                self._prep_wake.clear()
        self._log.info("admission prep stopped")

    def _select_groups(self):
        """Form admission groups from the backlog head (prep thread when
        overlapping, device thread inline; slot lock held inside).
        Returns ``(groups, seg, epoch)``: groups as ``[(prefix_entry,
        [(slot, request), ...])]``, ``seg`` a started chunked-prefill
        admission ``[slot, request, tokens_done]`` with pages already
        allocated (or None), and the allocator epoch the allocations
        were made under. Chosen slots are reserved until install or
        failure so overlapping selections can't double-book them."""
        seg = None
        with self._lock:
            # DAG-aware ordering first (policy-gated; warmup keeps the
            # compile sweep's deterministic submission order): priority
            # + aging floor + gang grouping decide who the "head" is.
            if self.sched_policy == "dag" and not self._warming:
                self._order_backlog_locked()
            # A slot completed but not yet device-released is not yet
            # admissible: its release ops (decode stop, page free) run
            # next device cycle, and admitting into it now would let
            # that stale release wipe the new occupant. One cycle of
            # patience. Slots a previous selection reserved (prepared
            # admission not yet installed) are off the table too.
            epoch = self._alloc_epoch
            not_yet = set(self._release)
            free = [
                i for i in self._free_slot_indices()
                if i not in not_yet and i not in self._prep_reserved
            ]
            # Degrade rung 3+ (reliability/degrade.py): cap live
            # occupancy at half the slots — less work in flight per
            # fault, faster drains, smaller recovery replays.
            if self.degrade.level() >= degrade_levels.HALF_SLOTS:
                occupied = (
                    sum(s is not None for s in self._slots)
                    + len(self._prep_reserved)
                )
                cap = max(1, self.n_slots // 2)
                free = free[: max(0, cap - occupied)]
            groups: List[Tuple[Any, List[Tuple[int, GenRequest]]]] = []
            # The in-progress group lives outside the try so the unwind
            # below sees it even when the failure lands mid-formation.
            group: List[Tuple[int, GenRequest]] = []
            blocked = False
            try:
                while free and not blocked:
                    group = []
                    group_key = None
                    while (
                        free and self._backlog
                        and len(group) < self.admit_batch
                    ):
                        req = self._backlog[0]
                        if req.cancelled or req.future.cancelled():
                            self._backlog.popleft()
                            continue
                        # Expired while queued: admitting would spend a
                        # prefill on work whose caller already gave up.
                        if (
                            req.deadline is not None
                            and time.monotonic() >= req.deadline
                        ):
                            self._backlog.popleft()
                            global_metrics.inc("engine.expired")
                            if not req.future.done():
                                req.future.set_exception(DeadlineExceeded(
                                    "request deadline expired before admission"
                                ))
                            continue
                        # A deferred gang at the head waits (bounded by
                        # gang_wait_ms) for its siblings or for enough
                        # slots+pages to take the WHOLE gang — the
                        # ordering pass already moved every admissible
                        # request in front of it, so nothing else is
                        # being held up.
                        if (
                            self.sched_policy == "dag"
                            and req.gang_id
                            and req.gang_id in self._gang_deferred
                        ):
                            blocked = True
                            break
                        # Prefix-cache match keys the group: one shared
                        # cached prefix per admission dispatch.
                        key = self._prefix_hit(req)
                        # Long un-cached tail → chunked-prefill admission
                        # (own slot, one segment per cycle), never a
                        # monolithic group prefill.
                        long_req = False
                        if self.prefill_chunk:
                            chain = (
                                len(key.path_pages)
                                if self.page_index is not None
                                and key is not None else 0
                            )
                            tail_len = (
                                len(req.prompt_ids) - chain * self.page_size
                            )
                            long_req = tail_len > 2 * self.prefill_chunk
                        if group and (key is not group_key or long_req):
                            break  # next group (or segmentation) takes it
                        group_key = key
                        prefix_pages: Tuple[int, ...] = ()
                        if self.page_index is not None and key is not None:
                            prefix_pages = key.path_pages
                        if self.alloc is not None:
                            # Clamp to slot capacity: decode stops at
                            # ctx-full anyway, so the cache never holds
                            # more (an unclamped huge max_new_tokens
                            # would make can_allocate permanently false
                            # and deadlock the FIFO head).
                            need = min(
                                len(req.prompt_ids) + req.max_new_tokens,
                                self.max_seq_len,
                            )
                            if not self.alloc.can_allocate(
                                need, len(prefix_pages)
                            ):
                                # Reclaim cached prefix pages before
                                # declaring the head blocked — caching
                                # must never starve admission. The hit's
                                # own chain is protected (evicting it
                                # would free pages we are about to map).
                                short = (
                                    self.alloc.pages_needed(need)
                                    - len(prefix_pages)
                                    - self.alloc.free_pages
                                )
                                if not (
                                    self.page_index is not None
                                    and short > 0
                                    and self.page_index.evict(
                                        short, self.alloc,
                                        protect=frozenset(prefix_pages),
                                    ) > 0
                                    and self.alloc.can_allocate(
                                        need, len(prefix_pages)
                                    )
                                ):
                                    # Head-of-line waits for pages (FIFO
                                    # fairness); completions free them.
                                    blocked = True
                                    break
                        self._backlog.popleft()
                        self._note_admission_pop(req)
                        idx = free.pop(0)
                        self._prep_reserved.add(idx)
                        if self.alloc is not None:
                            try:
                                ok = self.alloc.allocate(
                                    idx, need, prefix_pages=prefix_pages
                                )
                                assert ok, "can_allocate/allocate disagree"
                            except Exception:
                                # Undo the pop + reservation for THIS
                                # request before the outer unwind (which
                                # only knows committed members) runs:
                                # its appendleft lands behind the
                                # committed requests the unwind restores
                                # in front, so FIFO order holds.
                                self._prep_reserved.discard(idx)
                                self._backlog.appendleft(req)
                                raise
                        if long_req:
                            # Pages are allocated; segments run one per
                            # device-loop cycle once the device thread
                            # installs it. No further groups this wave —
                            # admission order holds.
                            self._prep_reserved.discard(idx)
                            seg = [
                                idx, req,
                                len(prefix_pages) * self.page_size,
                            ]
                            blocked = True
                            break
                        group.append((idx, req))
                    if not group:
                        break
                    groups.append((group_key, group))
            except Exception:
                # A failure mid-selection (prefix match, eviction, the
                # allocate assert) must not leak what this call already
                # committed: without this unwind, every earlier member —
                # the in-progress group AND fully formed groups — kept
                # its _prep_reserved entry and page allocation forever
                # while its request vanished from every queue (future
                # never resolves, slot pool permanently shrinks; the
                # prep loop's keep-alive catch only logs). Roll back all
                # of them and restore backlog FIFO order before
                # re-raising.
                pairs = [p for _, g in groups for p in g] + group
                for idx, _req in pairs:
                    self._prep_reserved.discard(idx)
                    if self.alloc is not None:
                        self.alloc.release(idx)
                for _idx, r in reversed(pairs):
                    self._backlog.appendleft(r)
                raise
            # Reserved slots stay None until install, so the picks stay
            # valid after the lock drops even with selection and install
            # on different threads.
        return groups, seg, epoch

    def _end_segmentation(self) -> None:
        """Segmentation over — installed, cancelled, expired or failed:
        group formation may resume (device thread only)."""
        self._segmenting = None
        self._seg_pending = False
        self._prep_wake.set()

    def _advance_segment(self) -> None:
        """Dispatch one chunked-prefill segment (device thread only).
        Intermediate segments run ``extend_prompt_paged`` (KV writes
        only); the final segment admits through the normal prefix-paged
        path, which samples the first token and installs the slot."""
        idx, req, done = self._segmenting
        # A segmented admission's chain may include freshly restored
        # pages (its prefix hit ran the host-tier path at selection):
        # they must be pool-resident before extend_prompt_paged attends
        # over them.
        self._apply_restores()
        if self._seg_epoch != self._alloc_epoch:
            # Device state was rebuilt mid-segmentation (a concurrent
            # dispatch failure consumed the buffers): the KV written so
            # far died with the old pool and alloc.table[idx] now reads
            # the fresh allocator's sentinel rows — continuing would
            # silently produce a garbage completion. Re-admit from the
            # backlog head instead (release is a no-op on the new pool).
            with self._lock:
                if self.alloc is not None:
                    self.alloc.release(idx)
                self._backlog.appendleft(req)
            self._end_segmentation()
            self._wake.set()
            return
        expired_now = (
            req.deadline is not None and time.monotonic() >= req.deadline
        )
        if req.cancelled or req.future.cancelled() or expired_now:
            # Release BEFORE ending segmentation: _end_segmentation wakes
            # the prep thread, and a slot that is empty but still holds
            # pages trips allocate()'s held-pages invariant if selection
            # wins the race to the lock.
            if self.alloc is not None:
                with self._lock:
                    self.alloc.release(idx)
            self._end_segmentation()
            if expired_now:
                global_metrics.inc("engine.expired")
                if not req.future.done():
                    req.future.set_exception(DeadlineExceeded(
                        "request deadline expired mid-prefill"
                    ))
            return
        try:
            remaining = len(req.prompt_ids) - done
            if remaining > self.prefill_chunk:
                seg = self.prefill_chunk
                k = done // self.page_size
                kb = 1
                while kb < max(k, 1):
                    kb *= 2
                pages_arr = np.full((kb,), self.alloc.sentinel, np.int32)
                pages_arr[:k] = self.alloc.table[idx, :k]
                seg_tokens = np.zeros((1, seg), np.int32)
                seg_tokens[0] = req.prompt_ids[done: done + seg]
                t_seg = time.perf_counter()
                with global_metrics.timer("engine.prefill_latency"):
                    self.cache = extend_prompt_paged(
                        self.params, self.cfg, self.cache,
                        jnp.asarray(pages_arr), jnp.int32(done),
                        jnp.asarray(seg_tokens),
                        jnp.asarray([seg], np.int32),
                        jnp.asarray(self.alloc.table[idx][None]),
                    )
                global_metrics.inc("engine.prefill_segments")
                if not self._warming:
                    seg_dur = time.perf_counter() - t_seg
                    self._record_attributed(
                        "prefill", seg_dur, seg,
                        est=(
                            self.collective_model.prefill_seconds(seg)
                            if self.collective_model is not None else None
                        ),
                    )
                    with self._lock:
                        self._prefill_since_fold += seg_dur
                self._segmenting[2] = done + seg
                self._beat()  # segment landed: watchdog-visible progress
                self._wake.set()  # next cycle advances without the idle wait
                return
            # Final segment: the tokens already written are this slot's
            # own page chain — admit exactly like a block-prefix hit, at
            # n_rows=1 (admit_batch padding rows against an 8K chain
            # made the prefix-score tensor 8x bigger for nothing — a
            # measured compile OOM). Re-reserve the slot across the
            # handoff: segmentation ends here but the slot is not
            # installed until _dispatch_prefill, and the prep thread
            # (woken by _end_segmentation) must not select an empty slot
            # that still holds this request's pages. Install (or the
            # failure path below) clears the reservation.
            with self._lock:
                self._prep_reserved.add(idx)
            self._end_segmentation()
            k = done // self.page_size
            entry = SimpleNamespace(
                depth=k,
                path_pages=tuple(int(p) for p in self.alloc.table[idx, :k]),
                segmented=True,  # own chain, not a cache hit (metrics)
            )
            self._dispatch_prefill(
                self._prepare_prefill([(idx, req)], entry, n_rows=1)
            )
        except Exception as exc:  # noqa: BLE001 — contain to this request
            self._log.error("chunked prefill failed: %s", exc, exc_info=True)
            # Cleanup before _end_segmentation for the same reason as the
            # cancel branch: once prep wakes, the slot must either hold
            # no pages or stay reserved — never "empty with pages". A
            # segmented admission has produced no tokens yet, so a
            # device fault here re-admits from scratch (bounded strikes)
            # rather than failing the request.
            now = time.monotonic()
            with self._lock:
                self._slots[idx] = None
                self._prep_reserved.discard(idx)
                if self.alloc is not None:
                    self.alloc.release(idx)
                if not req.future.done():
                    if self._recovery_decision_locked(
                        req, exc, now, time.perf_counter()
                    ):
                        self._backlog.appendleft(req)
                        global_metrics.inc("engine.recovery_requeued")
            self._end_segmentation()
            self.degrade.record_fault("prefill")
            if self.cache.lengths.is_deleted():
                self._fail_occupied_slots(exc, record_fault=False)
                self._rebuild_device_state(reason="prefill_failure")

    def _prepare_prefill(
        self,
        group: List[Tuple[int, GenRequest]],
        entry: Optional[Any] = None,
        n_rows: Optional[int] = None,
        epoch: Optional[int] = None,
    ) -> _PreparedAdmission:
        """Build every host-side input of one admission dispatch (either
        thread). The per-row scalars pack into ONE int32 + ONE float32
        staging buffer (``decode.pack_admit_meta`` layout): the ~10 tiny
        per-field ``jnp.asarray`` uploads this replaces each paid a
        transfer-setup/dispatch floor through the tunnel. No device work
        happens here — that is the point."""
        A = n_rows if n_rows is not None else self.admit_batch
        mi, mf = pack_admit_meta(A, pad_slot=self.n_slots)
        for row, (idx, req) in enumerate(group):
            mi[AI_SLOT, row] = idx
            mi[AI_TOPK, row] = req.top_k
            mi[AI_SEED, row] = req.seed
            mi[AI_EOS, row] = req.eos_id
            mi[AI_BUDGET, row] = req.max_new_tokens - 1
            mi[AI_JSON, row] = int(req.json_mode)
            mi[AI_SCHEMA, row] = req.json_schema_id
            mf[AF_TEMP, row] = req.temperature
            mf[AF_TOPP, row] = req.top_p
        prep = _PreparedAdmission(
            kind="full", group=list(group), entry=entry,
            epoch=self._alloc_epoch if epoch is None else epoch,
            meta_i32=mi, meta_f32=mf,
            has_json=any(req.json_mode for _, req in group),
            has_schema=bool((mi[AI_SCHEMA] >= 0).any()),
        )

        if entry is not None and self.paged:
            # Paged block-granular hit (or a chunked-prefill final
            # segment reading its own chain): the shared chain's pages are
            # already mapped into each slot's block table by the
            # allocator — no panel copy exists anywhere. Prefill only
            # the tails, with prefix attention reading the shared pages.
            k = entry.depth
            plen = k * self.page_size
            kb = 1
            while kb < k:
                kb *= 2
            pages_arr = np.full((kb,), self.alloc.sentinel, np.int32)
            pages_arr[:k] = entry.path_pages
            Tt = self._tail_bucket(
                max(len(r.prompt_ids) - plen for _, r in group)
            )
            Tf = self._bucket(max(len(r.prompt_ids) for _, r in group))
            tail_tokens = np.zeros((A, Tt), np.int32)
            full_tokens = np.zeros((A, Tf), np.int32)
            for row, (idx, req) in enumerate(group):
                tail = req.prompt_ids[plen:]
                tail_tokens[row, : len(tail)] = tail
                mi[AI_LEN, row] = len(tail)
                full_tokens[row, : len(req.prompt_ids)] = req.prompt_ids
            mi[AI_PLEN] = plen
            # Block-table rows under the lock: the reader thread mutates
            # rows at early page release.
            with self._lock:
                pr = np.full(
                    (A, self.max_pages_per_slot), self.alloc.sentinel,
                    np.int32,
                )
                for row, (idx, _) in enumerate(group):
                    pr[row] = self.alloc.table[idx]
            prep.kind = "prefix_paged"
            prep.pages_arr = pages_arr
            prep.tail_tokens = tail_tokens
            prep.full_tokens = full_tokens
            prep.page_rows = pr
            prep.n_prefix_bucket = kb
        elif entry is not None:
            # Cached-prefix admission: copy the stored panels, prefill
            # only the tails (an exact repeat is a one-token tail). Tail
            # buckets get an 8-floor ladder of their own: the 64-floor
            # prompt ladder would spend ~25% of a full 8B prefill on a
            # one-token tail.
            plen = len(entry.ids)
            Tt = self._tail_bucket(
                max(len(r.prompt_ids) - plen for _, r in group)
            )
            assert plen + Tt <= self.max_seq_len  # _prefix_hit guarantees
            Tf = self._bucket(max(len(r.prompt_ids) for _, r in group))
            tail_tokens = np.zeros((A, Tt), np.int32)
            full_tokens = np.zeros((A, Tf), np.int32)
            for row, (idx, req) in enumerate(group):
                tail = req.prompt_ids[plen:]
                tail_tokens[row, : len(tail)] = tail
                mi[AI_LEN, row] = len(tail)
                full_tokens[row, : len(req.prompt_ids)] = req.prompt_ids
            mi[AI_PLEN] = plen
            prep.kind = "prefix"
            prep.tail_tokens = tail_tokens
            prep.full_tokens = full_tokens
        else:
            T = self._bucket(max(len(r.prompt_ids) for _, r in group))
            tokens = np.zeros((A, T), np.int32)
            for row, (idx, req) in enumerate(group):
                ids = req.prompt_ids
                tokens[row, : len(ids)] = ids
                mi[AI_LEN, row] = len(ids)
            prep.tokens = tokens
            if self.alloc is not None:
                with self._lock:
                    pr = np.full(
                        (A, self.max_pages_per_slot), self.alloc.sentinel,
                        np.int32,
                    )
                    for row, (idx, _) in enumerate(group):
                        pr[row] = self.alloc.table[idx]
                prep.page_rows = pr
        return prep

    def _dispatch_prefill(self, prep: _PreparedAdmission) -> None:
        """Upload the prepared staging buffers and run the fused
        admission dispatch, then install the slots (device thread only).
        This is ALL the admission work left on the dispatch path: the
        prefill is enqueued on the device stream BEHIND whatever decode
        chunks are already in flight — chunked-prefill segments and
        decode interleave with no host-side bubble between them."""
        group = prep.group
        entry = prep.entry
        # Restored page chains must be pool-resident before this
        # dispatch can read them: drain here (not only in _admit) so a
        # prep whose restore record landed between _admit's drain and
        # its own dequeue still scatters first — the drain and this
        # dispatch share the device thread, so program order holds.
        self._apply_restores()
        # Chaos point: a slow (delay=) or failed (exc=) admission prefill.
        # Raises land in _dispatch_admissions' per-group failure handling
        # — exactly the production path a device fault would take.
        global_injector.fire("engine.prefill", n_requests=len(group))
        # Bake the token tables into this dispatch only when the group
        # actually constrains: with a 128k-vocab the B x V x L automaton
        # simulation is pure waste for non-JSON traffic. Two jit variants
        # total (with/without), both cached after first use. Schema
        # tables follow the same two-variant discipline (their ids ride
        # the packed meta buffer either way).
        group_json = self.json_tables if prep.has_json else None
        group_schema = self._schema_tables() if prep.has_schema else None
        meta_i32 = jnp.asarray(prep.meta_i32)
        meta_f32 = jnp.asarray(prep.meta_f32)
        t_pf = time.perf_counter()

        if prep.kind == "prefix_paged":
            with global_metrics.timer("engine.prefill_latency"):
                (
                    self.cache, self.dstate, self.sampling, first,
                    self.history,
                ) = admit_group_prefix_paged(
                    self.params, self.cfg, self.cache, self.dstate,
                    self.sampling, jnp.asarray(prep.pages_arr),
                    jnp.asarray(prep.tail_tokens),
                    jnp.asarray(prep.full_tokens),
                    jnp.asarray(prep.page_rows), meta_i32, meta_f32,
                    n_prefix_bucket=prep.n_prefix_bucket,
                    json_tables=group_json, history=self.history,
                    schema_tables=group_schema,
                )
            if not getattr(entry, "segmented", False):
                # A chunked-prefill final reads its OWN chain — counting
                # it as a cache hit would report near-100% hit rates on
                # deployments with the prefix cache disabled.
                global_metrics.inc("engine.prefix_hits", len(group))
                # Tokens the shared chain saved this dispatch: every
                # group member skipped the chain's prefill FLOPs.
                global_metrics.inc(
                    "engine.kvcache.prefill_tokens_saved",
                    entry.depth * self.page_size * len(group),
                )
            # Blocks past the shared chain that the prompt fully covers
            # are immutable too — register them as chain extensions.
            self._maybe_register(group)
        elif prep.kind == "prefix":
            with global_metrics.timer("engine.prefill_latency"):
                (
                    self.cache, self.dstate, self.sampling, first,
                    self.history,
                ) = admit_group_prefix(
                    self.params, self.cfg, self.cache, self.dstate,
                    self.sampling, entry.ks, entry.vs,
                    jnp.asarray(prep.tail_tokens),
                    jnp.asarray(prep.full_tokens), meta_i32, meta_f32,
                    json_tables=group_json, history=self.history,
                    schema_tables=group_schema,
                )
            global_metrics.inc("engine.prefix_hits", len(group))
            global_metrics.inc(
                "engine.kvcache.prefill_tokens_saved",
                len(entry.ids) * len(group),
            )
        else:
            with global_metrics.timer("engine.prefill_latency"):
                # One fused dispatch for the whole admission (prefill +
                # cache write + sampler + first token + decode install +
                # history) — separate dispatches each paid tunnel latency.
                (
                    self.cache, self.dstate, self.sampling, first,
                    self.history,
                ) = admit_group(
                    self.params, self.cfg, self.cache, self.dstate,
                    self.sampling, jnp.asarray(prep.tokens), meta_i32,
                    meta_f32, use_flash=self.on_tpu,
                    flash_mesh=self.flash_mesh,
                    page_rows=(
                        jnp.asarray(prep.page_rows)
                        if prep.page_rows is not None else None
                    ),
                    json_tables=group_json, history=self.history,
                    schema_tables=group_schema,
                )
            if self.paged:
                self._maybe_register(group)
            else:
                self._maybe_export(group)
        # The first tokens' D2H copy starts NOW; the reader materializes
        # the in-flight copy at fold time (no fresh round trip).
        first_copy = _HostCopy((first,))
        self._last_prefill_t = time.perf_counter()
        admit_at = time.perf_counter()
        self._beat()  # prefill enqueued: watchdog-visible progress
        if not self._warming:
            # Attribution: tokens actually prefilled this dispatch (the
            # AI_LEN rows carry tail lengths on prefix paths — prefix-hit
            # pages were NOT recomputed and must not count as achieved
            # FLOPs). The enqueue wall doubles as the prefill-time
            # estimate.
            pf_dur = admit_at - t_pf
            pf_tokens = int(prep.meta_i32[AI_LEN].sum())
            self._record_attributed(
                "prefill", pf_dur, pf_tokens,
                est=(
                    self.collective_model.prefill_seconds(pf_tokens)
                    if self.collective_model is not None else None
                ),
                at=admit_at,
            )
            idle_s = 0.0
            with self._lock:
                if self._inflight == 0:
                    # Device was DRAINED when this admission arrived: the
                    # span from the last fold to here was genuine idle —
                    # the decode-dispatch gap telemetry can't see it
                    # (its marks get masked by _last_prefill_t) — and
                    # the next fold's decode interval must restart at
                    # this prefill's END. Without both, idle-then-burst
                    # traffic books the whole idle span as decode time
                    # and busy_frac reads ~1.0 on an idle engine.
                    if self._last_attr_mark is not None:
                        idle_s = max(t_pf - self._last_attr_mark, 0.0)
                    self._last_attr_mark = admit_at
                else:
                    # Decode chunks in flight: the enclosing fold-to-fold
                    # interval spans this prefill; remember the wall so
                    # the fold doesn't count it twice.
                    self._prefill_since_fold += pf_dur
            if idle_s > 0.0:
                global_attribution.record_gap(idle_s, at=t_pf)
        with self._lock:
            for idx, req in group:
                self._slots[idx] = _Slot(
                    request=req, prompt_len=len(req.prompt_ids)
                )
                self._gen[idx] += 1
                self._prep_reserved.discard(idx)
                # Fresh occupant: optimistic n-gram first (its lookups
                # are free); the per-slot EMA demotes to model drafting
                # only if this request's output proves unpredictable.
                self._slot_rate[idx] = float(max(self.speculate, 1))
                self._draft_on[idx] = False
                if req.recovery_started_at is not None:
                    # Snapshot → re-admission wall: the latency a
                    # recovered request paid for the fault (bench
                    # RECOVERY reports p50/p99).
                    global_metrics.observe(
                        "engine.recovery_ms",
                        (admit_at - req.recovery_started_at) * 1e3,
                    )
                    req.recovery_started_at = None
            self._first_reads.append(
                ([(idx, self._gen[idx]) for idx, _ in group], first_copy)
            )
            slots_active = sum(s is not None for s in self._slots)
        for _, req in group:
            # Queue wait = submit → slot granted: the flight's admitted
            # mark is THE source of request.queue_wait_s (one histogram,
            # one definition — a second batcher-side one with a slightly
            # different start point would disagree at the tails).
            if req.flight_key is not None:
                global_flight.mark(req.flight_key, "admitted", at=admit_at)
        depth = self.queue_depth()
        global_metrics.set_gauge("engine.queue_depth", float(depth))
        global_steps.record(
            "engine.admit",
            n=len(group),
            slots_active=slots_active,
            queue_depth=depth,
        )
        global_metrics.inc("engine.admitted", len(group))

    def _apply_restores(self) -> None:
        """Scatter pending host-tier page restores into the pool (device
        thread only; a donated jitted write per chain — enqueued on the
        device stream, never awaited). Runs before any admission or
        segment dispatch, so a restored chain is always pool-resident by
        the time something reads it. Stale-epoch records (their pool was
        rebuilt) are dropped inside apply_restores."""
        if self.kvcache is None:
            return
        with self._lock:
            if not self._pending_restores:
                return
            records = self._pending_restores
            self._pending_restores = []
            epoch = self._alloc_epoch
        self.cache = self.kvcache.apply_restores(self.cache, records, epoch)
        with self._lock:
            # Writes are enqueued: the unwritten-page spill guard lifts
            # (stale records too — their pages died with the old pool,
            # and holding ids hostage would suppress spills of innocent
            # same-numbered pages in the new allocator).
            self.kvcache.mark_written(records)
        self._beat()  # restore landed: watchdog-visible progress

    def _schema_tables(self):
        """Device copies of the SchemaBank tables, refreshed when the
        bank gained a schema (device thread only)."""
        bank = self.schema_bank
        if bank is None or len(bank) == 0:
            return None
        if bank.version != self._schema_seen:
            # Snapshot the version BEFORE copying: register() on the
            # request thread mutates rows first and bumps version last,
            # so reading version after the copy could mark a torn
            # mid-registration copy as current forever.
            seen = bank.version
            self._schema_dev = tuple(jnp.asarray(t) for t in bank.tables())
            self._schema_seen = seen
        return self._schema_dev

    def _maybe_register(self, group: List[Tuple[int, GenRequest]]) -> None:
        """After a paged admission (miss or hit), pin the admitted
        prompts' fully-covered pages into the radix index so future
        prompts sharing page-aligned prefixes map them directly. Only
        blocks fully inside the prompt are registered — they are
        immutable (decode writes start at ``prompt_len``); the partial
        last block keeps taking decode writes and stays private."""
        if self.page_index is None or self._warming:
            return
        P = self.page_size
        # Under the slot lock: the reader thread releases finished slots'
        # pages at fold time now, so every allocator mutation (and the
        # table reads feeding pin()) must serialize against it.
        with self._lock:
            for idx, req in group:
                nb = len(req.prompt_ids) // P
                if nb == 0:
                    continue
                pages = [int(p) for p in self.alloc.table[idx, :nb]]
                self.page_index.register(
                    req.prompt_ids[: nb * P], pages, self.alloc
                )

    def _maybe_export(self, group: List[Tuple[int, GenRequest]]) -> None:
        """After a miss admission, copy new prompts' K/V out of the slot
        cache into the prefix store (plus derived longest-common-prefix
        entries, which converge on shared preambles). Best-effort — a
        failed export never fails the requests."""
        store = self.prefix_store
        if store is None or self._warming:
            return
        seen = set()
        for idx, req in group:
            # Store the prompt MINUS its last token: match() requires a
            # proper prefix (a tail token must produce the first-token
            # logits), so this is what makes an exact repeat hit — as a
            # one-token tail. Prompts past the HBM cap store their first
            # max_len tokens (prefix K/V is suffix-independent) — the
            # long-prompt workload is the one that needs caching most.
            ids = tuple(req.prompt_ids[:-1])[: store.max_len]
            if len(ids) < store.min_len:
                # Below the entry floor: this prompt will never cache —
                # one-shot warning instead of the PR 9 NOTE's silence.
                self._warn_min_len(len(req.prompt_ids), "admitted")
                continue
            with self._lock:
                known = ids in seen or store.has(ids)
            if known:
                continue
            seen.add(ids)
            try:
                pb = self._bucket(len(ids))
                # Quantized caches export in float32: dequant→requantize
                # is lossless only when nothing rounds in between — a
                # bf16 store entry would re-quantize to slightly
                # different int8 on the hit path and break repeat
                # determinism (review finding). Costs 2x entry HBM.
                export_dtype = (
                    jnp.float32 if self.kv_quantize else self.cache_dtype
                )
                ks, vs = export_prefix(
                    self.cache, idx, p_bucket=pb, dtype=export_dtype
                )
                # Store bookkeeping under the slot lock: the admission
                # prep thread runs match() against this store.
                with self._lock:
                    store.store(ids, ks, vs, pb)
                    lcps = store.lcp_candidates(ids)
                for p in lcps:
                    pb2 = self._bucket(p)
                    with self._lock:
                        store.store(
                            ids[:p], ks[:, :, :pb2], vs[:, :, :pb2], pb2
                        )
            except Exception as exc:  # noqa: BLE001 — cache is optional
                self._log.warning("prefix export failed: %s", exc)
                return

    def _fold_first_tokens(
        self, groups, hosts: List[np.ndarray],
        poisoned: Optional[List] = None,
    ) -> List:
        """Fold prefill-sampled first tokens into their slots (lock held).
        Entries carry the admission generation, so a stale entry from a
        failed/aborted generation can never feed the slot's next occupant.
        Returns ``(on_tokens, ids)`` stream emissions for the caller to
        fire AFTER releasing the lock; poisoned slots append to
        ``poisoned`` for the caller's outside-the-lock reporting."""
        emits: List = []
        for (rows, _), host in zip(groups, hosts):
            host = np.asarray(host)
            for row, (idx, gen) in enumerate(rows):
                slot = self._slots[idx]
                if slot is None or not slot.first_pending or gen != self._gen[idx]:
                    continue
                slot.first_pending = False
                tok = int(host[row])
                # Poison containment at the fold boundary: an
                # out-of-vocab first token (the host-visible symptom of
                # NaN logits / corrupted device memory) fails THIS
                # request, not the engine.
                if not 0 <= tok < self.cfg.vocab_size:
                    entry = self._poison_slot_locked(idx, [tok])
                    if poisoned is not None:
                        poisoned.append(entry)
                    continue
                slot.generated.append(tok)
                req = slot.request
                if tok != req.eos_id and tok not in req.stop_ids:
                    # TTFT lands here: the flight's first token mark must
                    # precede _check_finished (which may resolve the
                    # future and let the handler close the flight).
                    if req.flight_key is not None:
                        global_flight.token(req.flight_key, 1)
                    if req.on_tokens is not None:
                        emits.append((req.on_tokens, [tok]))
                self._check_finished(idx)
        return emits

    def _poison_slot_locked(
        self, idx: int, bad_ids: List[int]
    ) -> Tuple[int, GenRequest]:
        """Contain a poisoned fold to ITS request (slot lock held): the
        slot releases and the future fails with PoisonedOutput; the
        engine and every other occupant keep serving. Callers run the
        dump/ladder bookkeeping outside the lock."""
        slot = self._slots[idx]
        req = slot.request
        self._slots[idx] = None
        self._gen[idx] += 1
        self._release.append(idx)
        self._release_pages_locked(idx)
        if not req.future.done():
            req.future.set_exception(PoisonedOutput(
                f"decode fold produced out-of-vocab token id(s) "
                f"{bad_ids[:4]} (vocab {self.cfg.vocab_size}, slot {idx}); "
                f"failing this request only"
            ))
        global_metrics.inc("engine.poisoned")
        return idx, req

    def _report_poisoned(
        self, poisoned: List[Tuple[int, GenRequest]]
    ) -> None:
        """Poison observability OUTSIDE the slot lock (dump = file IO)."""
        for idx, req in poisoned:
            self.degrade.record_fault("poison")
            global_steps.record(
                "engine.poison", slot=idx, trace_id=req.trace_id
            )
            global_blackbox.dump(
                "poisoned_fold", trace_id=req.trace_id, slot=idx,
            )
        if poisoned:
            self._prep_wake.set()

    def _drain_first_reads(self) -> None:
        """Reader thread ONLY: fold pending first tokens outside a chunk
        read — the completion path for max_new_tokens <= 1 requests, whose
        zero decode budget never dispatches a chunk. Running this on the
        device thread raced the reader's chunk processing (the reader would
        see first_pending still True mid-drain and silently drop the
        chunk's tokens), so the device thread requests it via a sentinel in
        the results queue instead."""
        with self._lock:
            groups = list(self._first_reads)
            self._first_reads.clear()
        if not groups:
            return
        # Each entry's copy started at admission dispatch; materializing
        # here is not a fresh device round trip.
        hosts = [copy.wait()[0] for _, copy in groups]
        poisoned: List = []
        with self._lock:
            emits = self._fold_first_tokens(groups, hosts, poisoned)
        self._report_poisoned(poisoned)
        self._fire_stream(emits)
        self._beat()

    def _check_finished(self, idx: int) -> None:
        """Apply host-side completion rules to a slot; complete + free it
        when generation is over."""
        slot = self._slots[idx]
        if slot is None:
            return
        req = slot.request
        out = slot.generated
        finished = False
        if req.cancelled or req.future.cancelled():
            finished = True
        elif out and (out[-1] == req.eos_id or out[-1] in req.stop_ids):
            finished = True
        elif len(out) >= req.max_new_tokens:
            finished = True
        elif slot.prompt_len + len(out) >= self.max_seq_len - 1:
            finished = True
        if not finished:
            return
        self._slots[idx] = None
        self._release.append(idx)
        # Per-slot early release: the pages go back to the pool NOW (the
        # reader's fold), not at the next admission wave — with the wake
        # below, a page-gated backlog head re-checks can_allocate one
        # pipeline cycle earlier than the wave boundary.
        self._release_pages_locked(idx)
        self._wake.set()
        self._prep_wake.set()
        if out and (out[-1] == req.eos_id or out[-1] in req.stop_ids):
            out = out[:-1]
        now = time.perf_counter()
        latency = now - req.submitted_at
        global_metrics.observe("engine.request_e2e_latency", latency)
        global_metrics.inc("engine.completed")
        global_metrics.inc("engine.generated_tokens", len(out))
        if req.trace_id is not None:
            # The device threads have no asyncio context; emit the
            # request's engine span directly so its trace still nests
            # server → handler → batcher (parent = the handler's
            # engine.generate span id the request carried in).
            global_tracer.emit(
                "engine.batch_decode",
                trace_id=req.trace_id,
                parent_id=req.parent_span_id,
                start=req.submitted_at,
                end=now,
                slot=idx,
                prompt_len=slot.prompt_len,
                tokens=len(out),
            )
        if not req.future.done():
            # A recovered request's result is the tokens accepted BEFORE
            # the fault plus this (re-admitted) generation — the exact
            # sequence an uninterrupted run would have produced for
            # greedy sampling, and exactly what the streaming callbacks
            # already emitted (recovered tokens were streamed pre-fault,
            # never re-emitted).
            if req.recovered_tokens:
                out = req.recovered_tokens + out
            req.future.set_result(out)
            if req.recovery_attempts:
                global_metrics.inc("engine.recovered_requests")

    def _release_pages_locked(self, idx: int) -> None:
        """Return a finished/expired/failed slot's KV pages to the pool
        immediately (slot lock held; idempotent — release() clears the
        held list). Device-side stop/free ops still run through
        ``_release`` at the next admission; reusing the pages before
        then is safe because every device op is issued by the device
        thread in program order, so a new occupant's prefill always
        lands AFTER any stale in-flight chunk's writes."""
        if self.alloc is not None:
            if self.alloc.holds(idx):
                global_metrics.inc("engine.early_page_releases")
            self.alloc.release(idx)

    def _active_any(self) -> bool:
        return any(s is not None for s in self._slots)

    def _chunk_useful(self) -> bool:
        """True when at least one occupied slot still has decode budget
        that folded tokens plus in-flight estimates don't already cover
        (lock held)."""
        # Half-a-block tolerance under speculation: the acceptance EMA
        # sits just under D (request tails emit partial blocks), so an
        # exact-boundary check would dispatch one whole wasted weight
        # pass per wave. A boundary miss costs only one fold cycle (the
        # fold corrects the ledger and wakes this loop).
        tol = self._spec_rate / 2 if self.speculate else 0.0
        for s in self._slots:
            if s is None:
                continue
            folded = max(0, len(s.generated) - 1)  # decode tokens landed
            if folded + s.est_pending < s.request.max_new_tokens - 1 - tol:
                return True
        return False

    def _pick_chunk_blocks(self) -> int:
        """Choose the next dispatch's block count (lock held).

        The fixed policy recreates the seed behavior (always
        ``chunk_size``). The adaptive policy projects each live slot's
        remaining need in blocks — remaining token budget minus what
        in-flight chunks are already expected to deliver, divided by
        the speculation-acceptance EMA, capped by the slot's deadline
        budget — and sizes the dispatch to the MEAN projected need
        rather than the straggler's (the r6 profile's 16-block chunks
        against a 12.6-block average). Slots needing more simply get
        the next pipelined chunk; slots finishing inside the chunk fold
        (and early-release) sooner. With queued work waiting, the pick
        drops to the SMALLEST need so a finishing slot's fold/release
        boundary — and therefore backfill — arrives at the earliest
        opportunity (Orca-style iteration-level scheduling). The result
        quantizes UP to the bucket ladder so compiled executables stay
        bounded at len(chunk_buckets) per prefix-bound rung."""
        if self._force_chunk is not None:  # warmup compile sweep
            return max(1, min(self._force_chunk, self.chunk_size))
        # Degrade rung 2+ (reliability/degrade.py): clamp to the
        # smallest compiled bucket — short dispatches mean a short blast
        # radius per fault and fast fold heartbeats for the watchdog.
        if self.degrade.level() >= degrade_levels.MIN_CHUNK:
            return self.chunk_buckets[0]
        if self.chunk_policy != "adaptive":
            return self.chunk_size
        rate = self._spec_rate if self.speculate else 1.0
        rate = max(rate, 0.5)
        now = time.monotonic()
        needs: List[int] = []
        for s in self._slots:
            if s is None:
                continue
            folded = max(0, len(s.generated) - 1)
            rem = (
                s.request.max_new_tokens - 1 - folded - s.est_pending
            )
            if rem <= 0:
                continue
            need = int(-(-rem // rate))
            ddl = s.request.deadline
            if ddl is not None and self._block_seconds > 0:
                # Blocks past the deadline are pure waste: the sweep
                # force-releases the slot before they fold.
                cap = int((ddl - now) / self._block_seconds)
                need = min(need, max(cap, 1))
            needs.append(max(need, 1))
        if not needs:
            return self.chunk_buckets[0]
        target = sum(needs) / len(needs)
        if self._backlog or self._pending.qsize() or self._prepped_reqs:
            target = min(target, float(min(needs)))
        for b in self.chunk_buckets:
            if b >= target:
                return b
        return self.chunk_buckets[-1]

    def _dispatch_chunk(
        self, prefix_bound: int, n_blocks: int, est: float = 0.0,
        hi: int = 0, table_np: Optional[np.ndarray] = None,
    ):
        # Chaos point: a failed decode dispatch. Raises propagate to the
        # device loop boundary → _fail_occupied_slots RECOVERS the
        # occupants (re-admission after rebuild) or, strikes exhausted,
        # fails them with this exception; queued requests are untouched.
        global_injector.fire("engine.step")
        # Chaos point: a serving-mesh device fails mid-decode. value=
        # the boot-order device index — the dispatch raises
        # ShardLossError, the device-loop boundary classifies it and
        # the rebuild re-plans onto the surviving sub-mesh. The dict
        # form {"device": i, "hang": True} freezes that shard's
        # heartbeat instead (no raise): the per-shard watchdog triage
        # is then the only detector, exactly like a chip that stops
        # answering without erroring.
        loss = global_injector.fire("mesh.shard_loss")
        if loss is not None:
            if isinstance(loss, dict) and loss.get("hang"):
                if self._mesh_ladder is not None:
                    self._mesh_ladder.freeze(int(loss.get("device", 0)))
            else:
                raise ShardLossError(
                    0 if isinstance(loss, bool) else int(loss),
                    detail="injected",
                )
        # Chaos point: a STUCK dispatch — delay= pins the device thread
        # here without raising, exactly the shape of a hung XLA call or
        # a wedged collective. Nothing downstream ever observes it; the
        # watchdog's heartbeat staleness is the only detector.
        global_injector.fire("engine.dispatch.hang")
        # Host-gap telemetry: how long the device sat with NOTHING in
        # flight between the last fold/feed and this dispatch — the
        # host-side bubble overlapped admission + non-blocking folds
        # exist to close. 0 whenever the pipeline still held work (the
        # device was fed). Host-side approximation: enqueue times stand
        # in for device occupancy, which co-locates with it at chunk
        # granularity.
        t_dispatch = time.perf_counter()
        with self._lock:
            idle = self._inflight == 0
            marks = [
                t for t in (self._last_fold_done, self._last_prefill_t)
                if t is not None
            ]
        gap_ms = (
            max(0.0, (t_dispatch - max(marks)) * 1e3)
            if idle and marks else 0.0
        )
        global_metrics.observe("engine.host_gap_ms", gap_ms)
        if gap_ms > 0.0 and not self._warming:
            # Measured device-idle bubble: the live busy-frac gauge is
            # the complement of these over its window.
            global_attribution.record_gap(gap_ms / 1e3, at=t_dispatch)
        # Block table from the caller's under-lock snapshot (the reader
        # thread mutates rows at early release); absent when dense.
        table = jnp.asarray(table_np) if table_np is not None else None
        # Paged prefix reads: the per-page Pallas kernel streams only the
        # pages a slot owns, but pays a per-grid-cell latency that
        # dominates at serving-sized bounds (profiled on v5e: ~2x block
        # time at a 2K bound vs materializing dense panels once per
        # chunk and letting XLA's dense attention read them). Use the
        # gather fallback while the transient panels fit comfortably in
        # HBM; switch to the kernel only at bounds where they would not.
        use_pallas_now = self.use_pallas
        if self.paged and self.use_pallas:
            gather_bytes = (
                2 * self.cfg.n_layers * self.n_slots * self.cfg.n_kv_heads
                * prefix_bound * self.cfg.head_dim
                * jnp.dtype(self.cfg.dtype).itemsize
            )
            use_pallas_now = gather_bytes > self._gather_budget
        # Token-mask tables ride along only while a live slot constrains
        # (see _dispatch_prefill). Lock-free read is safe: slots are INSTALLED
        # on this thread (so a constraining slot is always seen), and the
        # reader only clears them (worst case: tables ride one extra
        # chunk).
        chunk_json = (
            self.json_tables
            if any(
                s is not None and s.request.json_mode for s in self._slots
            ) else None
        )
        chunk_schema = (
            self._schema_tables()
            if any(
                s is not None and s.request.json_schema_id >= 0
                for s in self._slots
            ) else None
        )
        # Fused decode epilogue (ISSUE 14): when every OCCUPIED slot is
        # greedy and unconstrained, the chunk's sampler fuses into the
        # vocab-tiled projection+argmax (engine/decode.py). Same
        # lock-free slot read as the table gating above — slots install
        # on this thread, so a sampled/JSON occupant is always seen; the
        # reader only clears, worst case one conservative (unfused)
        # chunk. Static flag → at most one extra executable per decode
        # variant, compiled at warmup (warmup traffic is greedy).
        # NOTE: gate on the REQUESTS, not on chunk_json/chunk_schema —
        # byte tokenizers constrain through the built-in byte automaton
        # with json_tables=None, so "no tables riding" does NOT imply
        # "no constrained slot".
        fused_now = (
            self.fused_epilogue
            and all(
                s is None or (
                    s.request.temperature <= 0.0
                    and not s.request.json_mode
                    and s.request.json_schema_id < 0
                )
                for s in self._slots
            )
        )
        # Degrade rung 1+ (reliability/degrade.py): speculative MODEL
        # drafting off — n-gram drafts only. The mode vector is a traced
        # input, so an all-False vector reuses the compiled executable
        # while skipping the shallow-layer draft passes on a device that
        # is already faulting.
        draft_vec = self._draft_on
        if (
            self.draft_layers
            and self.degrade.level() >= degrade_levels.NO_DRAFT
        ):
            draft_vec = np.zeros_like(self._draft_on)
        with global_metrics.timer("engine.chunk_dispatch_latency"):
            if self.speculate:
                (
                    toks, valid, self.cache, self.dstate, self.sampling,
                    self.history,
                ) = decode_chunk_spec(
                    self.params, self.cfg, self.cache, self.dstate,
                    self.sampling, self.history, n_blocks,
                    self.speculate, prefix_bound=prefix_bound,
                    json_tables=chunk_json, schema_tables=chunk_schema,
                    table=table,
                    use_pallas=self.paged and use_pallas_now,
                    page_strip=self.page_strip,
                    kv_mesh=(
                        self.kv_mesh
                        if self.paged and use_pallas_now else None
                    ),
                    draft_layers=self.draft_layers,
                    draft_mode=(
                        jnp.asarray(draft_vec)
                        if self.draft_layers else None
                    ),
                    fused_epilogue=fused_now,
                )
            else:
                toks, valid, self.cache, self.dstate, self.sampling = (
                    decode_chunk(
                        self.params, self.cfg, self.cache, self.dstate,
                        self.sampling, n_blocks, use_pallas_now,
                        prefix_bound=prefix_bound, table=table,
                        json_tables=chunk_json, schema_tables=chunk_schema,
                        page_strip=self.page_strip,
                        kv_mesh=(
                            self.kv_mesh
                            if self.paged and use_pallas_now else None
                        ),
                        fused_epilogue=fused_now,
                    )
                )
        # Start the D2H transfer the moment the chunk is enqueued: the
        # reader folds from this already-in-flight copy one pipeline
        # cycle later (a wait on a landed transfer, not a fresh ~100 ms
        # tunnel round trip — and never a jax.device_get).
        copies = _HostCopy((toks, valid))
        with self._lock:
            self._inflight += 1
        # engine.decode_steps is counted at fold time (_process_chunk)
        # from folded validity — executed block-steps, not the
        # dispatched chunk length, which overcounted whenever early
        # exit / done slots ran fewer blocks than dispatched. The
        # dispatch stamp feeds the per-block wall-time EMA.
        return (
            copies, tuple(self._gen), est, hi, n_blocks,
            time.perf_counter(), gap_ms,
        )

    def _process_chunk(
        self, copies, gen_stamp, est, hi, n_blocks, t_dispatch, gap_ms,
    ) -> None:
        """Fold one finished chunk's tokens into slots (reader thread).
        The chunk's D2H copy started at dispatch time (``_HostCopy``);
        this wait materializes it — while chunk N+1 executes on device —
        rather than opening a fresh blocking round trip. Pending
        first-token copies (started at their admission dispatch) fold on
        the same pass."""
        with self._lock:
            groups = list(self._first_reads)
            self._first_reads.clear()
        with global_metrics.timer("engine.chunk_read_latency"):
            toks_h, valid_h = copies.wait()
            first_hosts = [copy.wait()[0] for _, copy in groups]
        # Chaos point: poison one slot's folded ids with an out-of-vocab
        # value at the fold boundary (value= the slot index, or True for
        # the first slot that emitted) — drives the containment path a
        # real NaN-logits / corrupted-HBM fold would take.
        corrupt = global_injector.fire("engine.fold.corrupt")
        if corrupt is not None and toks_h.size:
            toks_h = toks_h.copy()
            if isinstance(corrupt, bool) or not isinstance(corrupt, int):
                cols = np.flatnonzero(valid_h.any(axis=0))
                corrupt = int(cols[0]) if cols.size else 0
            toks_h[:, corrupt] = self.cfg.vocab_size + 7
        n, B = toks_h.shape
        # Poison precheck, vectorized: one pass over the fold buffer; the
        # per-slot containment below only runs when something is actually
        # out of vocab (never on the healthy hot path).
        bad_valid = ((toks_h < 0) | (toks_h >= self.cfg.vocab_size)) & valid_h
        any_bad = bool(bad_valid.any())
        # One block-validity view serves the draft EMA, the utilization
        # counters and the acceptance EMA below.
        blk_any = valid_h.reshape(
            n_blocks, self.speculate or 1, B
        ).any(axis=1)                                        # [n_blocks, B]
        if self.speculate and self.draft_layers:
            slot_blocks = blk_any.sum(axis=0)                # [B]
            slot_tokens = valid_h.sum(axis=0)
        emits: List = []
        poisoned: List = []
        with self._lock:
            # First tokens were sampled before this chunk ran — fold them
            # first so token order inside each slot is right.
            if groups:
                emits = self._fold_first_tokens(
                    groups, first_hosts, poisoned
                )
            for b in range(B):
                slot = self._slots[b]
                if slot is None or gen_stamp[b] != self._gen[b]:
                    continue
                if self.speculate and self.draft_layers and slot_blocks[b]:
                    # Per-slot acceptance EMA + hysteresis for the draft
                    # source — under the lock AND behind the generation
                    # stamp, so a late chunk from an evicted request can
                    # never demote the slot's new occupant to the paid
                    # model-draft mode (review finding). Thresholds scale
                    # with D: at small D the absolute 3.0 hand-back was
                    # unreachable and draft mode latched on forever.
                    obs_b = slot_tokens[b] / slot_blocks[b]
                    self._slot_rate[b] = (
                        0.5 * self._slot_rate[b] + 0.5 * obs_b
                    )
                    D = self.speculate
                    enter = 1.0 + 0.125 * D
                    if not self._draft_on[b] and self._slot_rate[b] < enter:
                        self._draft_on[b] = True
                    elif self._draft_on[b] and (
                        self._slot_rate[b] > enter + 0.25 * D
                    ):
                        self._draft_on[b] = False
                # This chunk's contribution leaves the in-flight ledger
                # whether or not tokens landed (same occupant only).
                slot.est_pending = max(0.0, slot.est_pending - est)
                slot.hi_pending = max(0, slot.hi_pending - hi)
                if slot.first_pending:
                    continue
                req = slot.request
                # Poison containment: validate what crosses the fold
                # boundary. Out-of-vocab ids are the host-visible symptom
                # of NaN logits or corrupted device memory; they fail
                # ONLY this slot's request — folding them would crash (or
                # corrupt) the tokenizer and detokenized stream instead.
                if any_bad and bad_valid[:, b].any():
                    bad = [int(t) for t in toks_h[bad_valid[:, b], b]]
                    poisoned.append(self._poison_slot_locked(b, bad))
                    continue
                fresh: List[int] = []
                for i in range(n):
                    if not valid_h[i, b]:
                        continue
                    tok = int(toks_h[i, b])
                    slot.generated.append(tok)
                    if tok != req.eos_id and tok not in req.stop_ids:
                        fresh.append(tok)
                        # Per-token flight mark (ITL/TPOT) — before
                        # _check_finished can resolve the future.
                        if req.flight_key is not None:
                            global_flight.token(req.flight_key, 1)
                    self._check_finished(b)
                    if self._slots[b] is None:
                        break
                if fresh and req.on_tokens is not None:
                    emits.append((req.on_tokens, fresh))
            slots_active = sum(s is not None for s in self._slots)
        self._report_poisoned(poisoned)
        self._fire_stream(emits)
        # Chunk utilization: blocks where at least one slot emitted ÷
        # blocks dispatched. The gap is exactly the straggler/tail waste
        # adaptive sizing attacks — a fixed 16-block chunk whose slots
        # all finished by block 5 scores 5/16, an adaptive 8-block pick
        # 5/8. The gauge is cumulative (counters carry the exact
        # numerator/denominator); the ring record carries this
        # dispatch's own numbers for the Perfetto counter track.
        useful_blocks = int(blk_any.any(axis=1).sum())
        accepted = int(valid_h.sum())
        global_metrics.inc("engine.blocks_dispatched", n_blocks)
        global_metrics.inc("engine.blocks_useful", useful_blocks)
        disp_total = global_metrics.get("engine.blocks_dispatched")
        if disp_total > 0:
            global_metrics.set_gauge(
                "engine.chunk_utilization",
                global_metrics.get("engine.blocks_useful") / disp_total,
            )
        # decode_steps = device block-steps that actually emitted,
        # counted HERE from folded validity rather than
        # chunk_size-per-dispatch at dispatch time — early exit and
        # done slots made the old count overstate executed work, so
        # rate derivations (and SERVING.md's acceptance formula
        # tokens ÷ (decode_steps × slots)) disagreed with reality.
        global_metrics.inc("engine.decode_steps", useful_blocks)
        global_metrics.inc("engine.chunk_folds")
        # Wall-seconds per block EMA for the sizing policy's deadline
        # budget: THIS chunk's dispatch→fold latency over its blocks.
        # (A fold-to-fold gap would absorb idle time between requests
        # on low-traffic deployments and inflate the estimate 10-100x,
        # clamping every deadline-bound dispatch to the smallest
        # bucket.) Pipeline overlap makes this a mild overestimate —
        # conservative in the right direction for a deadline cap.
        per_block = (time.perf_counter() - t_dispatch) / max(n_blocks, 1)
        if 0.0 < per_block < 5.0:
            self._block_seconds = (
                0.5 * self._block_seconds + 0.5 * per_block
                if self._block_seconds else per_block
            )
        # Engine step telemetry: one bounded ring record per folded chunk
        # — what the black-box dump replays when a request dies.
        depth = self.queue_depth()
        global_metrics.set_gauge("engine.queue_depth", float(depth))
        global_steps.record(
            "engine.chunk",
            tokens=accepted,
            chunk_blocks=n_blocks,
            blocks_useful=useful_blocks,
            utilization=round(useful_blocks / max(n_blocks, 1), 3),
            host_gap_ms=round(gap_ms, 3),
            slots_active=slots_active,
            queue_depth=depth,
            page_strip=self.page_strip,
            pipeline_depth=self.PIPELINE_DEPTH,
            **(
                {"kv_pages_free": self.alloc.free_pages,
                 "kv_pages_total": self.num_pages - 1}
                if self.alloc is not None else {}
            ),
        )
        if self.speculate:
            # Observed tokens-per-block over blocks that actually emitted
            # (done-slot and trailing no-op blocks excluded — counting
            # them drags the EMA back toward 1 and re-creates the wasted
            # weight passes the estimate exists to avoid).
            D = self.speculate
            active_blocks = int(blk_any.sum())
            if active_blocks > 0:
                obs = accepted / active_blocks
                obs = min(max(obs, 0.5), float(D))
                self._spec_rate = 0.5 * self._spec_rate + 0.5 * obs
                # Exported as a 0..1 acceptance fraction (EMA tokens
                # per block over the draft depth) — the workload
                # fingerprint reads this back to characterize how
                # speculation-friendly the traffic is (obs/profile.py).
                global_metrics.set_gauge(
                    "engine.spec_acceptance", self._spec_rate / float(D)
                )
        global_metrics.inc("engine.generated_tokens_device", accepted)
        # Host-gap bookkeeping: this chunk has left the pipeline; the
        # next dispatch measures its bubble from here.
        t_fold = time.perf_counter()
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            self._last_fold_done = t_fold
            prev_mark = self._last_attr_mark
            self._last_attr_mark = t_fold
            pf_since = self._prefill_since_fold
            self._prefill_since_fold = 0.0
        if not self._warming:
            # Decode device-time estimate: the fold-to-fold interval
            # minus the measured idle gap and any prefill enqueue walls
            # inside it (already attributed above). Pipelined chunks make
            # per-dispatch walls overlap; fold-to-fold sums to occupancy
            # instead of double-counting. Achieved FLOPs count ACCEPTED
            # tokens only (folded validity) — rejected speculative rows
            # ran the weights but did no useful work.
            if prev_mark is not None:
                dur = max(t_fold - prev_mark - gap_ms / 1e3 - pf_since, 0.0)
            else:
                dur = max(t_fold - t_dispatch, 0.0)
            self._record_attributed(
                "decode", dur, accepted,
                est=(
                    self.collective_model.decode_seconds(
                        n_blocks, self.n_slots, accepted
                    )
                    if self.collective_model is not None else None
                ),
                at=t_fold,
            )
        # Fold landed: the watchdog's definition of forward progress.
        self._beat()

    def _restore_place(self, arr):
        """Host→device upload for KV-tier restore panels, following the
        pool's 'model'-axis sharding when it has one (identity layout
        otherwise). Shapes: dense entries [L, K, rows, H]; paged restore
        chains [L, 1, rows, K, H]."""
        mesh = self._kv_place_mesh
        if mesh is None or not self.kv_heads_sharded:
            return jax.device_put(arr)
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        K = self.cfg.n_kv_heads
        if arr.ndim == 4 and arr.shape[1] == K:
            spec = P(None, "model", None, None)
        elif arr.ndim == 5 and arr.shape[3] == K:
            spec = P(None, None, None, "model", None)
        else:
            return jax.device_put(arr)
        return jax.device_put(arr, NamedSharding(mesh, spec))

    def _record_attributed(
        self,
        phase: str,
        wall_s: float,
        tokens: int,
        est: Optional[Dict[str, float]] = None,
        at: Optional[float] = None,
    ) -> None:
        """One dispatch's device-time attribution, with per-axis
        collective time carved out of the measured wall (ISSUE 13).
        ``est`` is the CollectiveModel's per-axis seconds estimate for
        this dispatch; the split never invents time — collective +
        compute records sum to exactly the measured wall, so
        ``engine.collective_frac[.axis]`` is a share of real device
        time. Off-mesh (est None/empty) this is the plain single-record
        path the gauges always had."""
        if est:
            compute_s, coll = self.collective_model.split(wall_s, est)
            global_attribution.record(
                phase, compute_s, tokens=tokens, at=at, collective=coll,
            )
        else:
            global_attribution.record(phase, wall_s, tokens=tokens, at=at)

    def _fire_stream(self, emits: List) -> None:
        """Fire streaming callbacks OUTSIDE the slot lock (reader thread).
        A callback is user code bridging into an event loop; holding the
        lock across it would let a slow consumer stall folding."""
        for cb, ids in emits:
            try:
                cb(ids)
            except Exception as exc:  # noqa: BLE001 — consumer's problem
                self._log.warning("stream callback failed: %s", exc)

    def _read_loop(self) -> None:
        """Reader thread: blockingly reads dispatched chunks and resolves
        completions, so the device thread never stalls on a transfer."""
        while True:
            try:
                item = self._results.get(timeout=0.1)
            except queue.Empty:
                if self._stop.is_set():
                    break
                continue
            try:
                if item is None:  # drain-first-tokens sentinel
                    with self._lock:
                        self._drain_queued = False
                    self._drain_first_reads()
                    self._wake.set()
                    continue
                self._process_chunk(*item)
            except Exception as exc:  # noqa: BLE001 — reader boundary
                # The chunk's tokens are lost on the host while the device
                # has already consumed their budget; swallowing would hang
                # the affected requests forever and leak their slots.
                # Recovery re-admits the occupants; the rebuild request
                # (consumed by the device thread, where rebuilds are
                # safe) resets the pool a failed transfer makes suspect.
                # Flag BEFORE the sweep: _fail_occupied_slots wakes the
                # device thread, and it must observe the rebuild request
                # before it can re-admit the recovered requests — or
                # they would prefill against the suspect pool and the
                # deferred rebuild would then swap state under live
                # occupants (silent output corruption).
                self._log.error("reader error: %s", exc, exc_info=True)
                self._rebuild_requested = "reader_error"
                self._fail_occupied_slots(exc)
                # The failed chunk left the pipeline without reaching
                # _process_chunk's bookkeeping tail. Sentinel failures
                # (first-token drains) never entered the pipeline, so
                # decrementing for them would mark a still-executing
                # chunk's window as idle and fake a host-gap sample.
                if item is not None:
                    with self._lock:
                        self._inflight = max(0, self._inflight - 1)
            self._wake.set()
        self._log.info("reader stopped")

    def _rebuild_device_state(self, reason: Optional[str] = None) -> None:
        """(Re)create cache/sampling/decode state — at construction, and
        after a failed dispatch left the previous buffers consumed or
        suspect (device thread only; failure callers must fail/recover
        the occupants first). The allocator swap and epoch bump happen
        under the slot lock, so a concurrent admission prep can never
        allocate half in the old pool and half in the new: a prep
        stamped with the old epoch requeues at dispatch time instead of
        prefilling against the fresh allocator's sentinel rows.

        ``reason`` marks a FAILURE-path rebuild (None = construction):
        those were previously visible only as log lines — now each one
        counts under ``engine.rebuilds{reason=}``, lands in the step
        ring and writes a black-box dump, so an engine quietly
        rebuilding once a minute shows up on a dashboard instead of in
        grep."""
        if reason is not None:
            # Chaos point: a rebuild that itself fails (exc=) — retried
            # next device-loop cycle via _rebuild_requested.
            global_injector.fire("engine.rebuild", reason=reason)
        if reason == "shard_loss" and self._mesh_ladder is not None:
            # Degraded-mesh rebuild (ISSUE 16): re-plan onto the
            # surviving sub-mesh and re-place the weights BEFORE the
            # pool is recreated, so place_kv_cache below lays the fresh
            # KV out on the new plan. The occupants were already swept
            # into recovery by the failure arm; their re-prefill runs
            # on the degraded mesh and greedy output stays
            # byte-identical (nothing trusts the old pool). Raises
            # MeshLadderExhausted only if the ladder emptied between
            # the failure arm's viable() check and here — the caller's
            # retry path handles it like any failed rebuild.
            self._replan_mesh()
        if self.paged:
            cache = PagedKVCache.create(
                self.cfg.n_layers, self.n_slots, self.num_pages,
                self.page_size, self.cfg.n_kv_heads, self.cfg.head_dim,
                dtype=self.cache_dtype, quantized=self.kv_quantize,
            )
            alloc = PageAllocator(
                self.num_pages, self.page_size, self.n_slots,
                self.max_pages_per_slot,
            )
        else:
            cache = KVCache.create(
                self.cfg.n_layers, self.n_slots, self.max_seq_len,
                self.cfg.n_kv_heads, self.cfg.head_dim,
                dtype=self.cache_dtype, quantized=self.kv_quantize,
            )
            alloc = None
        # Serving-mesh layout AT CREATION (parallel/sharding.py): paged
        # pool kv-heads shard over 'model', dense panels over
        # ('data'/'fsdp', 'model'). The cache is donated through every
        # dispatch, so the initial committed layout is what jit's
        # argument shardings follow — placing it here means the first
        # dispatch starts sharded instead of paying a whole-pool
        # reshard, and a failure-path rebuild restores the same layout.
        cache = place_kv_cache(
            cache, self._kv_place_mesh,
            n_kv_heads=self.cfg.n_kv_heads, n_slots=self.n_slots,
        )
        with self._lock:
            self.cache = cache
            self.alloc = alloc
            self._alloc_epoch += 1
            # A fresh pool invalidates every cached page — reset the
            # index's bookkeeping (the allocator above is new, so no
            # unpinning against the old one).
            if self.paged and getattr(self, "page_index", None) is not None:
                self.page_index.clear()
        self.sampling = SamplingState.create(self.n_slots)
        self.dstate = DecodeState.create(self.n_slots)
        # Per-slot token-id history by position (speculative drafting).
        self.history = (
            jnp.zeros((self.n_slots, self.max_seq_len), jnp.int32)
            if self.speculate else None
        )
        if reason is not None:
            global_metrics.inc("engine.rebuilds")
            global_metrics.inc(f"engine.rebuilds.{reason}")
            global_steps.record("engine.rebuild", reason=reason)
            global_blackbox.dump("engine_rebuild", rebuild_reason=reason)
            self._log.warning("device state rebuilt (reason=%s)", reason)
            # The rebuild IS forward progress — recovery re-admissions
            # must not race the watchdog's stall clock.
            self._beat()

    def _recoverable(self, req: GenRequest, now: float) -> bool:
        """May this request re-admit instead of failing? (lock held)"""
        return (
            self.recovery_max_attempts > 0
            and req.recovery_attempts < self.recovery_max_attempts
            and not req.cancelled
            and not req.future.cancelled()
            and (req.deadline is None or now < req.deadline)
        )

    def _recovery_decision_locked(
        self, req: GenRequest, exc: Exception, now: float, t_snap: float
    ) -> bool:
        """ONE requeue-or-fail policy for every failure arm (slot lock
        held). True → the request re-admits: attempts bumped, recovery
        stamp set — the CALLER appends it to the backlog so each site
        keeps its own FIFO ordering. False → the future was failed with
        ``exc`` (strike accounting included)."""
        if self._recoverable(req, now):
            req.recovery_attempts += 1
            req.recovery_started_at = t_snap
            return True
        if (
            self.recovery_max_attempts > 0
            and req.recovery_attempts >= self.recovery_max_attempts
        ):
            global_metrics.inc("engine.recovery_failed")
        req.future.set_exception(exc)
        return False

    def _fail_occupied_slots(
        self, exc: Exception, record_fault: bool = True,
        allow_recovery: bool = True,
    ) -> None:
        """Contain a device/transfer failure to the ENGINE, not its
        requests (either thread). Every occupied slot's progress —
        original prompt plus the tokens already accepted — is
        snapshotted and re-admitted at the backlog head through the
        normal admission path: the re-prefill runs over prompt+generated
        (the prefix cache absorbs most of it when the pool survived), so
        a greedy request's final output is byte-identical to an
        uninterrupted run, and streaming consumers resume at the next
        NEW token (``recovered_tokens`` are never re-emitted). Attempts
        are bounded per request (``recovery_max_attempts`` strikes →
        fail with the original exception); cancelled/expired requests
        and grammar-constrained requests that already streamed tokens
        fail immediately (the JSON automaton's state is derived from
        the position *after the prompt*, so a spliced replay prompt
        would constrain against the wrong state — restart-from-scratch
        is only transparent when nothing was emitted).

        ``allow_recovery=False`` ends the containment contract: every
        occupant fails with the original exception regardless of
        remaining strikes — the mesh ladder exhausted, so there is no
        device state left to recover ONTO (PR 8's strikes-exhausted
        semantics, reached structurally instead of by count)."""
        now = time.monotonic()
        t_snap = time.perf_counter()
        recovered: List[GenRequest] = []
        failed = 0
        with self._lock:
            for i, slot in enumerate(self._slots):
                if slot is None:
                    continue
                self._slots[i] = None
                self._gen[i] += 1
                self._release.append(i)
                self._release_pages_locked(i)
                req = slot.request
                if req.future.done():
                    continue
                replay = list(slot.generated)
                if not allow_recovery:
                    req.future.set_exception(exc)
                    failed += 1
                    continue
                json_bound = req.json_mode or req.json_schema_id >= 0
                if json_bound and replay and req.on_tokens is not None:
                    # Streamed grammar-constrained output can neither be
                    # spliced (DFA state is position-derived) nor
                    # restarted (the consumer already saw tokens).
                    req.future.set_exception(exc)
                    failed += 1
                    continue
                if not self._recovery_decision_locked(req, exc, now, t_snap):
                    failed += 1
                    continue
                if json_bound and replay:
                    # Restart the whole generation (nothing was
                    # streamed): the grammar mask re-derives cleanly
                    # from the original prompt, and greedy output is
                    # the same either way.
                    replay = []
                if replay:
                    # New list, not in-place extend: callers hold
                    # references to the original prompt (usage counting).
                    req.prompt_ids = req.prompt_ids + replay
                    req.recovered_tokens.extend(replay)
                    req.max_new_tokens -= len(replay)
                    global_metrics.inc("engine.tokens_replayed", len(replay))
                recovered.append(req)
            self._first_reads.clear()
            # Backlog HEAD in original submission order: these requests
            # were admitted earliest, so FIFO fairness keeps holding.
            for req in reversed(recovered):
                self._backlog.appendleft(req)
        if recovered or failed:
            global_metrics.inc("engine.recovery_requeued", len(recovered))
            global_steps.record(
                "engine.recovery",
                requeued=len(recovered),
                failed=failed,
                error=str(exc)[:200],
            )
            self._log.warning(
                "engine failure (%s): %d in-flight request(s) requeued "
                "for recovery, %d failed", exc, len(recovered), failed,
            )
        if record_fault:
            # record_fault=False when the caller already counted this
            # incident (the prefill-failure arms record "prefill" first)
            # — one incident must step the ladder once, not twice.
            self.degrade.record_fault("device")
        self._prep_wake.set()
        self._wake.set()

    def _run(self) -> None:
        self._log.info(
            "device loop starting (slots=%d, max_seq=%d, chunk=%d, pallas=%s)",
            self.n_slots, self.max_seq_len, self.chunk_size, self.use_pallas,
        )
        while not self._stop.is_set():
            try:
                # Self-heal after any donated dispatch (decode_chunk too,
                # not just admission) failed mid-flight and consumed the
                # state buffers — or after another thread's failure path
                # requested a rebuild; the failure arms already
                # failed/recovered the occupants on the way here.
                if (
                    self.cache.lengths.is_deleted()
                    or self._rebuild_requested is not None
                ):
                    reason = self._rebuild_requested or "state_consumed"
                    self._rebuild_requested = None
                    # A deferred rebuild can race an admission that was
                    # mid-dispatch when the requesting thread swept its
                    # occupants (slots install only after admit_group
                    # returns): anyone occupying a slot NOW must be
                    # recovered before the swap, or they would decode
                    # against the fresh allocator's sentinel rows.
                    # Idempotent when the original sweep got everyone.
                    if any(s is not None for s in self._slots):
                        self._fail_occupied_slots(
                            RuntimeError(
                                f"device state rebuilt ({reason}) with "
                                f"request in flight"
                            ),
                            record_fault=False,
                        )
                    self._rebuild_device_state(reason=reason)
                self._expire_deadlines()
                self._admit()
                with self._lock:
                    useful = self._chunk_useful()
                    if useful:
                        # Scheduling decision: this dispatch's block
                        # count, from remaining budgets + acceptance EMA
                        # (bucket-quantized; constant under "fixed").
                        n_blocks = self._pick_chunk_blocks()
                        # Upper bound on any live slot's cache length at
                        # chunk start (device lengths ≤ prompt + folded
                        # decode tokens + the in-flight chunks' hard
                        # maximum), taken BEFORE this chunk's own tokens
                        # are counted.
                        bound = max(
                            s.prompt_len + min(
                                max(0, len(s.generated) - 1)
                                + s.hi_pending,
                                s.request.max_new_tokens - 1,
                            )
                            for s in self._slots
                            if s is not None
                        )
                        est = n_blocks * (
                            self._spec_rate if self.speculate else 1.0
                        )
                        hi = n_blocks * (self.speculate or 1)
                        for s in self._slots:
                            if s is not None:
                                s.est_pending += est
                                s.hi_pending += hi
                        # Block-table snapshot under the lock: the
                        # reader mutates rows at early page release.
                        table_np = (
                            self.alloc.table.copy()
                            if self.alloc is not None else None
                        )
                if useful:
                    item = self._dispatch_chunk(
                        self._decode_bucket(bound), n_blocks, est, hi,
                        table_np,
                    )
                    while not self._stop.is_set():
                        try:
                            self._results.put(item, timeout=0.5)
                            break
                        except queue.Full:
                            continue
                else:
                    with self._lock:
                        need_drain = (
                            bool(self._first_reads) and not self._drain_queued
                        )
                        if need_drain:
                            self._drain_queued = True
                    if need_drain:
                        self._results.put(None)  # reader folds, in order
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
            except Exception as exc:  # noqa: BLE001 — device loop boundary
                self._log.error("device loop error: %s", exc, exc_info=True)
                # Shard-loss triage (ISSUE 16): an error that names a
                # failed DEVICE is a loss of that shard, not a generic
                # dispatch failure — mark it lost and rebuild onto the
                # surviving sub-mesh. When the ladder has no rung left
                # for the survivors, the containment contract ends and
                # the occupants fail with the original exception (the
                # PR 8 strikes-exhausted semantics).
                reason = "device_loop_error"
                recover = True
                ladder = self._mesh_ladder
                if isinstance(exc, MeshLadderExhausted):
                    recover = False
                elif ladder is not None:
                    dev = classify_device_error(exc)
                    if dev is not None:
                        ladder.mark_lost(dev)
                        global_metrics.inc("engine.shard_losses")
                        if ladder.viable():
                            reason = "shard_loss"
                        else:
                            recover = False
                self._fail_occupied_slots(exc, allow_recovery=recover)
                # Conservative containment: a dispatch that raised
                # mid-flight may have partially mutated device state even
                # when the donated buffers survived — rebuild fresh so
                # recovered re-admissions never decode against suspect
                # KV. (This is what makes recovered greedy output
                # byte-identical by construction: everything re-prefills
                # from the tokens, nothing trusts the old pool.)
                try:
                    self._rebuild_device_state(reason=reason)
                except MeshLadderExhausted as rexc:
                    # Raced to exhaustion after the viable() check:
                    # nothing to rebuild onto — fail anything that
                    # slipped into recovery and stop re-planning.
                    self._log.error("mesh ladder exhausted: %s", rexc)
                    self._fail_occupied_slots(
                        exc, record_fault=False, allow_recovery=False
                    )
                    self._rebuild_requested = "rebuild_retry"
                except Exception as rexc:  # noqa: BLE001 — retry next cycle
                    self._log.error(
                        "device-state rebuild failed: %s", rexc,
                        exc_info=True,
                    )
                    self._rebuild_requested = "rebuild_retry"
        self._log.info("device loop stopped")

    # ------------------------------------------------------------------ #

    def get_metrics(self) -> Dict[str, Any]:
        return {
            "slots_total": self.n_slots,
            "slots_active": sum(s is not None for s in self._slots),
            # queue_depth(), not pending+backlog: prepared-but-not-yet-
            # dispatched admissions count toward shedding, so they must
            # be visible here too or shed storms look causeless.
            "pending": self.queue_depth(),
            **(
                {"kv_pages_free": self.alloc.free_pages,
                 "kv_pages_total": self.num_pages - 1,
                 "page_strip": self.page_strip}
                if self.alloc is not None else {}
            ),
            **(
                {"prefix_entries": len(self.prefix_store),
                 "prefix_hits": global_metrics.get("engine.prefix_hits")}
                if self.prefix_store is not None else {}
            ),
            **(
                {"prefix_pages": self.page_index.pinned_pages,
                 "prefix_hits": global_metrics.get("engine.prefix_hits")}
                if self.page_index is not None else {}
            ),
            **(
                {"kvcache": {
                    "host_mb": round(
                        self.kvcache.host.bytes_held / (1024 * 1024), 2
                    ),
                    "host_entries": len(self.kvcache.host),
                    "lookups": global_metrics.get("engine.kvcache.lookups"),
                    "hits": global_metrics.get("engine.kvcache.hits"),
                    "host_hits": global_metrics.get(
                        "engine.kvcache.host_hits"
                    ),
                    "spills": global_metrics.get("engine.kvcache.spills"),
                    "restores": global_metrics.get(
                        "engine.kvcache.restores"
                    ),
                    "prefill_tokens_saved": global_metrics.get(
                        "engine.kvcache.prefill_tokens_saved"
                    ),
                }}
                if self.kvcache is not None and self.kvcache.host is not None
                else {}
            ),
            "decode_steps": global_metrics.get("engine.decode_steps"),
            # DAG-aware scheduling (pilottai_tpu/sched/): backlog
            # ordering policy + gang/pre-warm outcome counters.
            "sched": {
                "policy": self.sched_policy,
                "gang_admits": global_metrics.get("sched.gang_admits"),
                "gang_partial": global_metrics.get("sched.gang_partial"),
                "priority_aged": global_metrics.get("sched.priority_aged"),
                "prewarms": global_metrics.get("sched.prewarms"),
                "prewarm_hits": global_metrics.get("sched.prewarm_hits"),
            },
            # Weight quantization (ISSUE 14): mode, int4 scale group,
            # measured weight-stream bytes (the gauges set at boot) and
            # whether the fused greedy epilogue is enabled.
            "quant": {
                "weight_quant": self.weight_quant,
                "quant_group": self.quant_group,
                "weight_bytes": self.weight_bytes,
                "weight_bytes_per_token": self.weight_bytes_per_token,
                "fused_epilogue": self.fused_epilogue,
            },
            "overlap_admission": self.overlap_admission,
            "pipeline_depth": self.PIPELINE_DEPTH,
            "chunk_policy": self.chunk_policy,
            "chunk_buckets": list(self.chunk_buckets),
            "chunk_utilization": round(
                global_metrics.get("engine.blocks_useful")
                / max(global_metrics.get("engine.blocks_dispatched"), 1),
                4,
            ),
            "completed": global_metrics.get("engine.completed"),
            # Live attribution gauges (obs/attribution.py): rolling-
            # window MFU and the measured-idle complement.
            "mfu": round(global_metrics.get("engine.mfu"), 4),
            "device_busy_frac": round(
                global_metrics.get("engine.device_busy_frac"), 4
            ),
            "collective_frac": round(
                global_metrics.get("engine.collective_frac"), 4
            ),
            # ACTIVE mesh plan, not the boot plan: after a shard-loss
            # re-plan this reports the rung the engine is actually
            # serving on (the single-chip rung sets self.mesh = None,
            # so the ladder — which remembers the boot set — keeps the
            # section alive with shape {} / n_chips 1).
            **(
                {"mesh": {
                    "shape": (
                        {
                            str(a): int(s)
                            for a, s in self.mesh.shape.items()
                            if int(s) > 1
                        }
                        if self.mesh is not None else {}
                    ),
                    "n_chips": (
                        int(self.mesh.devices.size)
                        if self.mesh is not None else 1
                    ),
                    "kv_heads_sharded": self.kv_heads_sharded,
                    "data_groups": self.data_groups,
                    **(
                        {
                            "rung": self._mesh_ladder.rung,
                            "plan": plan_label(self._mesh_ladder.plan()),
                            "lost_devices": self._mesh_ladder.lost(),
                            "shard_losses": global_metrics.get(
                                "engine.shard_losses"
                            ),
                        }
                        if self._mesh_ladder is not None else {}
                    ),
                    "collective_frac_model": round(
                        global_metrics.get("engine.collective_frac.model"),
                        4,
                    ),
                    "collective_frac_data": round(
                        global_metrics.get("engine.collective_frac.data"),
                        4,
                    ),
                }}
                if self.mesh is not None or self._mesh_ladder is not None
                else {}
            ),
            **(
                {"max_queue_depth": self.max_queue_depth,
                 "shed": global_metrics.get("engine.shed")}
                if self.max_queue_depth is not None else {}
            ),
            "expired": global_metrics.get("engine.expired"),
            # Engine fault domain: ladder rung, failure-path rebuilds,
            # in-flight recovery accounting and fold-poison containment.
            "degrade_level": self.degrade.level(),
            "rebuilds": global_metrics.get("engine.rebuilds"),
            "poisoned": global_metrics.get("engine.poisoned"),
            "recovery": {
                "max_attempts": self.recovery_max_attempts,
                "requeued": global_metrics.get("engine.recovery_requeued"),
                "recovered": global_metrics.get("engine.recovered_requests"),
                "failed": global_metrics.get("engine.recovery_failed"),
                "tokens_replayed": global_metrics.get("engine.tokens_replayed"),
            },
            **(
                {"watchdog_stalled": self._watchdog.stalled}
                if self._watchdog is not None else {}
            ),
        }
