"""Continuous batcher: many concurrent small generations on one device loop.

The workload shape (SURVEY.md §3.4): agent steps are bursty, short,
JSON-bound generations — dozens in flight, each a few hundred tokens. The
batcher multiplexes them onto fixed-shape device computations:

* a dedicated *device thread* runs prefill/decode (never the asyncio loop —
  the reference's blocking-psutil-in-async-loop bug, SURVEY §2.12-h, is the
  cautionary tale);
* requests admit into KV-cache *slots* between decode steps (continuous
  batching: no head-of-line blocking on long generations);
* prefills compile per power-of-two length bucket; decode compiles once.

All shapes static → zero recompiles at steady state.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pilottai_tpu.engine.sampling import SamplingState, sample_tokens, update_slot
from pilottai_tpu.models.common import ModelConfig
from pilottai_tpu.models.transformer import forward_decode, forward_prefill
from pilottai_tpu.ops.kvcache import KVCache, write_prompt
from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import global_metrics


@dataclass
class GenRequest:
    prompt_ids: List[int]
    max_new_tokens: int = 256
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    eos_id: int = -1
    stop_ids: List[int] = field(default_factory=list)
    future: Future = field(default_factory=Future)
    submitted_at: float = field(default_factory=time.perf_counter)
    # Set by the caller (any thread) to abandon the request; the device loop
    # frees its slot at the next step instead of decoding dead work.
    cancelled: bool = False


@dataclass
class _Slot:
    request: GenRequest
    generated: List[int] = field(default_factory=list)
    prompt_len: int = 0
    # (cancellation lives on the request: see GenRequest.cancelled)


class ContinuousBatcher:
    """Slot-based continuous batching over a jitted prefill/decode pair."""

    def __init__(
        self,
        cfg: ModelConfig,
        params: Any,
        n_slots: int = 8,
        max_seq_len: Optional[int] = None,
        min_bucket: int = 64,
        cache_dtype=jnp.bfloat16,
    ) -> None:
        self.cfg = cfg
        self.params = params
        self.n_slots = n_slots
        self.max_seq_len = min(max_seq_len or cfg.max_seq_len, cfg.max_seq_len)
        self.min_bucket = min_bucket
        self._log = get_logger("engine.batcher")

        self.cache = KVCache.create(
            cfg.n_layers, n_slots, self.max_seq_len, cfg.n_kv_heads, cfg.head_dim,
            dtype=cache_dtype,
        )
        self.sampling = SamplingState.create(n_slots)
        self._slots: List[Optional[_Slot]] = [None] * n_slots
        self._pending: "queue.Queue[GenRequest]" = queue.Queue()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        self._insert = jax.jit(write_prompt, donate_argnums=(0,))

    # ------------------------------------------------------------------ #
    # Lifecycle
    # ------------------------------------------------------------------ #

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="pilottai-device-loop", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        # Fail any stranded requests.
        while True:
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                break
            if not req.future.done():
                req.future.set_exception(RuntimeError("engine stopped"))
        for slot in self._slots:
            if slot and not slot.request.future.done():
                slot.request.future.set_exception(RuntimeError("engine stopped"))

    def warmup(self, prompt_len: int = 64) -> None:
        """Compile the decode step and one prefill bucket up front."""
        ids = list(range(2, 2 + prompt_len))
        req = GenRequest(prompt_ids=ids, max_new_tokens=2)
        self.submit(req)
        req.future.result(timeout=600)

    # ------------------------------------------------------------------ #
    # Submission (any thread)
    # ------------------------------------------------------------------ #

    def submit(self, request: GenRequest) -> Future:
        # Leave room for at least one generated token; clamp the keep window
        # so it can never be <= 0 (a negative-zero slice would keep the whole
        # oversized prompt and crash the prefill copy).
        keep = self.max_seq_len - 1 - request.max_new_tokens
        keep = min(max(keep, 1), self.max_seq_len - 2)
        if len(request.prompt_ids) > keep:
            request.prompt_ids = request.prompt_ids[-keep:]
        self._pending.put(request)
        self._wake.set()
        return request.future

    # ------------------------------------------------------------------ #
    # Device loop (device thread only)
    # ------------------------------------------------------------------ #

    def _bucket(self, n: int) -> int:
        b = self.min_bucket
        while b < n:
            b *= 2
        return min(b, self.max_seq_len)

    def _admit(self) -> None:
        for idx in range(self.n_slots):
            if self._slots[idx] is not None:
                continue
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                return
            if req.cancelled or req.future.cancelled():
                continue
            try:
                self._prefill_into(idx, req)
            except Exception as exc:  # noqa: BLE001 - fail this request only
                self._log.error("prefill failed: %s", exc, exc_info=True)
                self._slots[idx] = None
                if not req.future.done():
                    req.future.set_exception(exc)

    def _prefill_into(self, idx: int, req: GenRequest) -> None:
        ids = req.prompt_ids
        T = self._bucket(len(ids))
        tokens = np.zeros((1, T), np.int32)
        tokens[0, : len(ids)] = ids
        positions = np.arange(T, dtype=np.int32)[None]
        with global_metrics.timer("engine.prefill_latency"):
            logits, ks, vs = forward_prefill(
                self.params, self.cfg, jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray([len(ids)], jnp.int32),
            )
        self.cache = self._insert(
            self.cache, jnp.int32(idx), ks[:, 0], vs[:, 0], jnp.int32(len(ids))
        )
        self.sampling = update_slot(
            self.sampling, idx, req.temperature, req.top_k, req.top_p, req.seed
        )
        # First generated token comes from the last prompt logit.
        first = self._sample_one(np.asarray(logits[0, len(ids) - 1]), req)
        slot = _Slot(request=req, prompt_len=len(ids))
        slot.generated.append(first)
        self._slots[idx] = slot
        global_metrics.inc("engine.admitted")
        if self._finished(slot):
            self._complete(idx)

    @staticmethod
    def _sample_one(logits: np.ndarray, req: GenRequest) -> int:
        """Host-side sampling for the first token (it comes straight out of
        prefill); must honor the same temperature/top_k/top_p contract as
        the device sampler used for all subsequent tokens."""
        if req.temperature <= 0.0:
            return int(np.argmax(logits))
        rng = np.random.default_rng(req.seed)
        scaled = logits.astype(np.float64) / max(req.temperature, 1e-6)
        if req.top_k > 0:
            kth = np.partition(scaled, -req.top_k)[-req.top_k]
            scaled = np.where(scaled >= kth, scaled, -np.inf)
        if req.top_p < 1.0:
            order = np.argsort(scaled)[::-1]
            probs_sorted = np.exp(scaled[order] - np.nanmax(scaled))
            probs_sorted /= probs_sorted.sum()
            cum = np.cumsum(probs_sorted)
            keep_sorted = (cum - probs_sorted) < req.top_p  # exclusive mass
            drop = order[~keep_sorted]
            scaled[drop] = -np.inf
        probs = np.exp(scaled - scaled.max())
        probs /= probs.sum()
        return int(rng.choice(len(probs), p=probs))

    def _finished(self, slot: _Slot) -> bool:
        req = slot.request
        if req.cancelled or req.future.cancelled():
            return True
        last = slot.generated[-1]
        if last == req.eos_id or last in req.stop_ids:
            return True
        if len(slot.generated) >= req.max_new_tokens:
            return True
        if slot.prompt_len + len(slot.generated) >= self.max_seq_len - 1:
            return True
        return False

    def _complete(self, idx: int) -> None:
        slot = self._slots[idx]
        assert slot is not None
        self._slots[idx] = None
        self.cache = self.cache._replace(lengths=self.cache.lengths.at[idx].set(0))
        req = slot.request
        out = slot.generated
        if out and (out[-1] == req.eos_id or out[-1] in req.stop_ids):
            out = out[:-1]
        latency = time.perf_counter() - req.submitted_at
        global_metrics.observe("engine.request_e2e_latency", latency)
        global_metrics.inc("engine.completed")
        global_metrics.inc("engine.generated_tokens", len(out))
        if not req.future.done():
            req.future.set_result(out)

    def _active_any(self) -> bool:
        return any(s is not None for s in self._slots)

    def _decode_step(self) -> None:
        tokens = np.zeros((self.n_slots,), np.int32)
        active = np.zeros((self.n_slots,), bool)
        for i, slot in enumerate(self._slots):
            if slot is not None:
                tokens[i] = slot.generated[-1]
                active[i] = True
        with global_metrics.timer("engine.decode_step_latency"):
            logits, self.cache = forward_decode(
                self.params, self.cfg, jnp.asarray(tokens), self.cache,
                jnp.asarray(active),
            )
            next_tokens, self.sampling = sample_tokens(logits, self.sampling)
            host_tokens = np.asarray(next_tokens)  # one small D2H per step
        global_metrics.inc("engine.decode_steps")
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            slot.generated.append(int(host_tokens[i]))
            if self._finished(slot):
                self._complete(i)

    def _run(self) -> None:
        self._log.info("device loop starting (slots=%d, max_seq=%d)",
                       self.n_slots, self.max_seq_len)
        while not self._stop.is_set():
            self._admit()
            if not self._active_any():
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            try:
                self._decode_step()
            except Exception as exc:  # noqa: BLE001 - device loop boundary
                self._log.error("decode step failed: %s", exc, exc_info=True)
                for i, slot in enumerate(self._slots):
                    if slot is not None and not slot.request.future.done():
                        slot.request.future.set_exception(exc)
                        self._slots[i] = None
        self._log.info("device loop stopped")

    # ------------------------------------------------------------------ #

    def get_metrics(self) -> Dict[str, Any]:
        return {
            "slots_total": self.n_slots,
            "slots_active": sum(s is not None for s in self._slots),
            "pending": self._pending.qsize(),
            "decode_steps": global_metrics.get("engine.decode_steps"),
            "completed": global_metrics.get("engine.completed"),
        }
