"""JSON-Schema–constrained decoding: compile a schema to a byte DFA.

Extends the generic JSON grammar masking (``engine/json_mask.py``) from
"well-formed JSON" to "THIS shape of JSON": the OpenAI
``response_format: {"type": "json_schema"}`` contract. Without ``$ref``
recursion a JSON Schema unrolls into a FINITE automaton — arrays loop
within their own states and every nested object/array has a statically
known continuation — so no pushdown stack is needed at all. The device
work per byte stays two gathers (``ALLOWED[state]`` mask,
``NEXT[state, byte]`` advance), identical in shape to the generic
tables, and runs inside the jitted decode chunk like everything else.

Output is COMPACT (no optional whitespace) and properties are emitted in
schema order; properties not listed in ``required`` may be skipped (a
byte-trie over the still-allowed keys disambiguates). Budget feasibility
uses ``MINCOST[state]`` — the shortest byte count from a state to the
accept state (reverse BFS) — masking any byte whose successor could not
finish within the remaining budget, which is strictly stronger than the
generic automaton's depth margin.

Supported subset (the agent-protocol shapes and the usual structured-
output surface): ``object`` with ``properties``/``required`` (no
``additionalProperties``), ``array`` of a supported item schema,
``string`` (free-form printable ASCII + escapes), ``number``/
``integer``, ``boolean``, ``null``, ``enum`` of scalars, ``const``, and
unions via ``type: [..]``. ``$ref``/``anyOf``/recursion raise
``UnsupportedSchema`` — callers fall back to generic JSON masking.

Conventions: state 0 is the ACCEPT state (``MINCOST == 0``; the mask
layer forces EOS there, exactly like the generic ``S_DONE``); state 1 is
the root start, so admission initializes schema slots to ``json_state=1``
with no per-schema lookup. The reference has no counterpart — it
re-prompts on malformed JSON (``pilott/pilott.py:603-639``).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

INF = np.int32(2**30)

_PRINTABLE = [b for b in range(0x20, 0x7F)]
_ESCAPES = [ord(c) for c in '"\\/bfnrt']
_DIGITS = [ord(c) for c in "0123456789"]

ACC = 0    # accept state (document complete)
START = 1  # root start state


class UnsupportedSchema(ValueError):
    """Schema uses a feature outside the compiled subset."""


class _Builder:
    """Mutable DFA builder: per-state [256] allow mask + next table."""

    def __init__(self, max_states: int = 2048) -> None:
        self.max_states = max_states
        self.allowed: List[np.ndarray] = []
        self.next: List[np.ndarray] = []
        # Edges whose target is a literal's continuation (external state):
        # trie insertion must never traverse THROUGH one — a literal that
        # is a strict prefix of another (e.g. enum [1, 12]) would attach
        # new edges to the continuation and corrupt it. Rejected instead.
        self.terminal: set = set()
        self.new_state()  # ACC = 0 (no outgoing edges)
        self.new_state()  # START = 1 (root fragment is wired to it)

    def new_state(self) -> int:
        # Enforced DURING compilation, not after: schemas come from
        # unauthenticated API requests, and a giant const/enum literal
        # must fail at the cap (~KB of tables), not after allocating a
        # state per literal byte (a 10 MB const ≈ 13 GB of tables).
        if len(self.allowed) >= self.max_states:
            raise UnsupportedSchema(
                f"schema too large (> {self.max_states} DFA states)"
            )
        self.allowed.append(np.zeros((256,), np.bool_))
        self.next.append(np.zeros((256,), np.int32))
        return len(self.allowed) - 1

    def edge(self, s: int, bytes_: Any, t: int) -> None:
        if isinstance(bytes_, (int, np.integer)):
            bytes_ = [int(bytes_)]
        for b in bytes_:
            # Retargeting an existing transition would silently replace
            # one fragment's continuation with another's (e.g. a future
            # union whose members share first bytes) — a wrong DFA that
            # still compiles. Fail loudly; re-adding the same edge is a
            # no-op.
            if self.allowed[s][b] and int(self.next[s][b]) != t:
                raise UnsupportedSchema(
                    f"conflicting DFA transitions from state {s} on byte "
                    f"{b:#x} (overlapping alternatives)"
                )
            self.allowed[s][b] = True
            self.next[s][b] = t

    def chain(self, s: int, text: str, t: int) -> None:
        """Literal byte chain from ``s`` through fresh states to ``t``.
        TRIE semantics: existing edges are followed, not overwritten, so
        several literals inserted from the same state share their common
        prefix and diverge at the first differing byte (object keys all
        start with '\"'; enum members may share arbitrary prefixes)."""
        data = text.encode("utf-8")
        for i, b in enumerate(data):
            last = i == len(data) - 1
            if self.allowed[s][b]:
                existing = int(self.next[s][b])
                if last:
                    # Duplicate identical literal is a no-op; anything
                    # else is a collision.
                    if (s, b) not in self.terminal or existing != t:
                        raise UnsupportedSchema(
                            "literal collision (duplicate serialization "
                            "with different continuations)"
                        )
                    return
                if (s, b) in self.terminal:
                    raise UnsupportedSchema(
                        f"literal {text!r} extends through another "
                        "literal's end (prefix-ambiguous literals)"
                    )
                s = existing
            else:
                nxt = t if last else self.new_state()
                self.edge(s, b, nxt)
                if last:
                    self.terminal.add((s, b))
                s = nxt

    def copy_state(self, dst: int, src: int) -> None:
        """Overlay ``src``'s edges onto ``dst`` (used by number states,
        whose end is implicit: the byte after the number belongs to the
        continuation)."""
        sel = self.allowed[src]
        self.allowed[dst] = self.allowed[dst] | sel
        self.next[dst] = np.where(sel, self.next[src], self.next[dst])


def _string_fragment(b: _Builder, start: int, cont: int) -> None:
    """'"' chars* '"' from ``start`` to ``cont`` (value string)."""
    body = b.new_state()
    esc = b.new_state()
    b.edge(start, ord('"'), body)
    plain = [c for c in _PRINTABLE if c not in (ord('"'), ord("\\"))]
    b.edge(body, plain, body)
    b.edge(body, ord("\\"), esc)
    b.edge(esc, _ESCAPES, body)
    b.edge(body, ord('"'), cont)


def _number_fragment(
    b: _Builder, start: int, cont: int, integer: bool
) -> None:
    """JSON number from ``start``; termination is implicit — integer/
    fraction/exponent states OVERLAY the continuation's edges (the byte
    after a number belongs to whatever follows; digits never collide
    with JSON structure bytes). The integer part is ``0 | [1-9][0-9]*``
    — a leading zero cannot be followed by more digits (JSON grammar;
    '01' is not valid JSON and the validates-by-construction contract
    forbids emitting it)."""
    nonzero = [d for d in _DIGITS if d != ord("0")]
    int_digits = b.new_state()   # [1-9][0-9]*
    zero = b.new_state()         # lone leading 0
    neg = b.new_state()
    b.edge(start, ord("-"), neg)
    for s in (start, neg):
        b.edge(s, ord("0"), zero)
        b.edge(s, nonzero, int_digits)
    b.edge(int_digits, _DIGITS, int_digits)
    terminal = [int_digits, zero]
    if not integer:
        frac = b.new_state()
        frac_digits = b.new_state()
        for s in (int_digits, zero):
            b.edge(s, ord("."), frac)
        b.edge(frac, _DIGITS, frac_digits)
        b.edge(frac_digits, _DIGITS, frac_digits)
        exp = b.new_state()
        exp_sign = b.new_state()
        exp_digits = b.new_state()
        for s in (int_digits, zero, frac_digits):
            b.edge(s, [ord("e"), ord("E")], exp)
        b.edge(exp, [ord("+"), ord("-")], exp_sign)
        b.edge(exp, _DIGITS, exp_digits)
        b.edge(exp_sign, _DIGITS, exp_digits)
        b.edge(exp_digits, _DIGITS, exp_digits)
        terminal += [frac_digits, exp_digits]
    for s in terminal:
        b.copy_state(s, cont)


def _literal_value(b: _Builder, start: int, value: Any, cont: int) -> None:
    """A ``const``/``enum`` member as its exact JSON serialization."""
    b.chain(start, json.dumps(value), cont)


def _compile_value(
    b: _Builder, schema: Dict[str, Any], start: int, cont: int, depth: int
) -> None:
    """Wire ``start ─(one value matching schema)→ cont``."""
    if depth > 32:
        raise UnsupportedSchema("schema nesting too deep (>32)")
    if not isinstance(schema, dict):
        raise UnsupportedSchema(f"schema must be an object, got {schema!r}")
    for key in ("$ref", "anyOf", "oneOf", "allOf", "not",
                "patternProperties", "additionalProperties"):
        if schema.get(key):
            raise UnsupportedSchema(f"unsupported schema keyword: {key}")

    if "const" in schema:
        _literal_value(b, start, schema["const"], cont)
        return
    if "enum" in schema:
        for value in schema["enum"]:
            if isinstance(value, (dict, list)):
                raise UnsupportedSchema("enum members must be scalars")
            _literal_value(b, start, value, cont)
        return

    stype = schema.get("type")
    if isinstance(stype, list):
        for t in stype:
            _compile_value(b, {**schema, "type": t}, start, cont, depth)
        return
    if stype == "string":
        _string_fragment(b, start, cont)
    elif stype in ("number", "integer"):
        _number_fragment(b, start, cont, integer=stype == "integer")
    elif stype == "boolean":
        b.chain(start, "true", cont)
        b.chain(start, "false", cont)
    elif stype == "null":
        b.chain(start, "null", cont)
    elif stype == "array":
        item = schema.get("items")
        if item is None:
            raise UnsupportedSchema("array schema needs 'items'")
        open_ = b.new_state()   # after '['
        sep = b.new_state()     # after an item
        b.edge(start, ord("["), open_)
        b.edge(open_, ord("]"), cont)
        b.edge(sep, ord("]"), cont)
        item_start = b.new_state()
        # ',' between items loops back to a fresh item.
        b.edge(sep, ord(","), item_start)
        _compile_value(b, item, item_start, sep, depth + 1)
        b.copy_state(open_, item_start)  # first item starts right after '['
    elif stype == "object":
        props = schema.get("properties") or {}
        if not isinstance(props, dict):
            raise UnsupportedSchema("'properties' must be an object")
        required = set(schema.get("required") or [])
        unknown = required - set(props)
        if unknown:
            raise UnsupportedSchema(f"required names not in properties: {unknown}")
        names = list(props)  # schema order, preserved in output
        open_ = b.new_state()
        b.edge(start, ord("{"), open_)
        _compile_object_body(b, names, props, required, open_, cont, depth)
    else:
        raise UnsupportedSchema(f"unsupported type: {stype!r}")


def _compile_object_body(
    b: _Builder,
    names: List[str],
    props: Dict[str, Any],
    required: set,
    open_: int,
    cont: int,
    depth: int,
) -> None:
    """Decision-point automaton over ordered, possibly-optional keys.

    ``decision[i]`` is the state where properties ``i..n`` may still
    appear (in order). From there a byte trie over the candidate keys
    disambiguates which property comes next; '}' is legal iff every
    remaining property is optional. ``first`` tracks whether a ','
    separator is owed (two variants per decision point)."""
    n = len(names)
    # decision[i][first?] — first=True means no property emitted yet.
    decision: Dict[Tuple[int, bool], int] = {}

    def get_decision(i: int, first: bool) -> int:
        if i == n:
            # No properties left: close (the caller wires '}'->cont).
            st = decision.get((n, first))
            if st is None:
                st = b.new_state()
                b.edge(st, ord("}"), cont)
                decision[(n, first)] = st
            return st
        key = (i, first)
        if key in decision:
            return decision[key]
        st = b.new_state()
        decision[key] = st
        # '}' legal when every remaining property is optional.
        if not any(names[j] in required for j in range(i, n)):
            b.edge(st, ord("}"), cont)
        # Candidate keys: i, plus i+1.. while the skipped ones are
        # optional. Keys are emitted as ',' (unless first) '"name":'.
        j = i
        while j < n:
            after_value = get_decision(j + 1, False)
            entry = st
            if not first:
                comma = b.next[st][ord(",")] if b.allowed[st][ord(",")] else None
                if comma is None:
                    comma = b.new_state()
                    b.edge(st, ord(","), comma)
                entry = comma
            vstart = b.new_state()
            b.chain(entry, json.dumps(names[j]) + ":", vstart)
            _compile_value(b, props[names[j]], vstart, after_value, depth + 1)
            if names[j] in required:
                break  # later keys can't appear before a required one
            j += 1
        return st

    first_state = get_decision(0, True)
    b.copy_state(open_, first_state)


class SchemaDFA:
    """Compiled schema: device-ready tables + a host-side stepper."""

    def __init__(self, allowed: np.ndarray, nxt: np.ndarray,
                 mincost: np.ndarray) -> None:
        self.allowed = allowed  # [S, 256] bool
        self.next = nxt         # [S, 256] int32
        self.mincost = mincost  # [S] int32 (bytes to ACC; INF unreachable)

    @property
    def n_states(self) -> int:
        return self.allowed.shape[0]

    # Host-side simulation (tests, validation).
    def matches(self, text: str) -> bool:
        state = START
        for byte in text.encode("utf-8"):
            if not self.allowed[state, byte]:
                return False
            state = int(self.next[state, byte])
        return state == ACC

    def step(self, state: int, byte: int) -> Optional[int]:
        if not self.allowed[state, byte]:
            return None
        return int(self.next[state, byte])


def compile_schema(schema: Dict[str, Any]) -> SchemaDFA:
    """Compile a JSON Schema (supported subset) into a byte DFA."""
    b = _Builder()
    root_type = schema.get("type")
    if root_type not in ("object", "array") and "enum" not in schema \
            and "const" not in schema:
        raise UnsupportedSchema(
            f"root schema must be an object or array, got {root_type!r}"
        )
    _compile_value(b, schema, START, ACC, 0)
    allowed = np.stack(b.allowed)
    nxt = np.stack(b.next)
    mincost = _min_costs(allowed, nxt)
    if mincost[START] >= INF:
        raise UnsupportedSchema("schema admits no finite document")
    return SchemaDFA(allowed, nxt, mincost)


def _min_costs(allowed: np.ndarray, nxt: np.ndarray) -> np.ndarray:
    """Shortest #bytes from each state to ACC (reverse BFS)."""
    S = allowed.shape[0]
    cost = np.full((S,), INF, np.int32)
    cost[ACC] = 0
    # Reverse adjacency: states with an edge into t.
    frontier = [ACC]
    # Precompute predecessor lists once.
    preds: List[List[int]] = [[] for _ in range(S)]
    for s in range(S):
        targets = np.unique(nxt[s][allowed[s]])
        for t in targets:
            preds[int(t)].append(s)
    while frontier:
        nxt_frontier: List[int] = []
        for t in frontier:
            for s in preds[t]:
                if cost[s] > cost[t] + 1:
                    cost[s] = cost[t] + 1
                    nxt_frontier.append(s)
        frontier = nxt_frontier
    return cost


class SchemaBank:
    """Fixed-capacity device bank of compiled schemas.

    Pre-sized ``(max_schemas, max_states)`` so registering a new schema
    updates rows in place and never changes the table shapes the jitted
    decode chunk was compiled against (a growing shape would recompile
    the engine's hot path on the first request of every new schema)."""

    def __init__(self, max_schemas: int = 8, max_states: int = 768) -> None:
        self.max_schemas = max_schemas
        self.max_states = max_states
        self.allowed = np.zeros((max_schemas, max_states, 256), np.bool_)
        self.next = np.zeros((max_schemas, max_states, 256), np.int32)
        self.mincost = np.full((max_schemas, max_states), INF, np.int32)
        self._ids: Dict[str, int] = {}
        # Bumped on every table mutation — device-side copies re-upload
        # when stale (the batcher checks before each dispatch).
        self.version = 0

    def register(self, schema: Dict[str, Any]) -> int:
        """Compile (or look up) a schema; returns its bank row.

        Raises ``UnsupportedSchema`` for schemas outside the subset or
        bigger than ``max_states``."""
        key = json.dumps(schema, sort_keys=True)
        if key in self._ids:
            return self._ids[key]
        dfa = compile_schema(schema)
        if dfa.n_states > self.max_states:
            raise UnsupportedSchema(
                f"schema compiles to {dfa.n_states} states "
                f"(> bank capacity {self.max_states})"
            )
        if len(self._ids) >= self.max_schemas:
            # NO eviction: an in-flight request still masks against its
            # bank row — repointing it mid-generation would silently
            # constrain against the wrong schema. Callers degrade to the
            # generic grammar instead; restart clears the bank.
            raise UnsupportedSchema(
                f"schema bank full ({self.max_schemas} distinct schemas)"
            )
        sid = len(self._ids)
        self.allowed[sid] = False
        self.next[sid] = 0
        self.mincost[sid] = INF
        self.allowed[sid, : dfa.n_states] = dfa.allowed
        self.next[sid, : dfa.n_states] = dfa.next
        self.mincost[sid, : dfa.n_states] = dfa.mincost
        self._ids[key] = sid
        self.version += 1
        return sid

    def tables(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        return self.allowed, self.next, self.mincost

    def __len__(self) -> int:
        return len(self._ids)
