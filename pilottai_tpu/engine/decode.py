"""Fused multi-step decode: N tokens per device dispatch.

Why this exists: through a remote-TPU tunnel (and even locally, at small
per-step cost) every host<->device round trip costs ~100 ms; a
one-dispatch-per-token decode loop is latency-bound long before the chip
is. ``decode_chunk`` jits a ``lax.scan`` over N decode steps — sampling,
EOS/budget tracking, and KV writes all on device — so the host touches
the device once per N tokens, and the batcher pipelines chunks so even
that touch overlaps compute (``engine/batcher.py``).

The KV-cache trick: inside the chunk the big per-layer cache panels are
**read-only** (prefix attention via the Pallas decode kernel — a custom
call that wrote carry state would force XLA to copy the panels every
layer, every step). Each step's fresh K/V goes to a tiny per-layer ring
buffer ([B, K, N, H]); in-chunk attention runs dense over the ring and
merges with the prefix pass by the standard online-softmax combine; one
batched scatter per layer lands the ring in the big cache at chunk end.

No reference counterpart: the reference's only decode loop is a remote
HTTP call (``pilott/engine/llm.py:59``). This file is the engine half of
the ≤500 ms p50 agent-step target (BASELINE.md).
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from pilottai_tpu.engine.sampling import (
    SamplingState,
    admit_sampling,
    sample_core,
    split_step_keys,
)
from pilottai_tpu.models.common import ModelConfig, rms_norm, rope_tables
from pilottai_tpu.models.qmatmul import qmatmul
from pilottai_tpu.models.quant import Q4Tensor, QTensor
from pilottai_tpu.models.transformer import (
    _attn_out,
    _embed,
    _mlp,
    _qkv,
    _unembed,
    forward_prefill,
)
from pilottai_tpu.ops.kvcache import (
    KVCache,
    dequantize_kv,
    quantize_kv,
    write_chunk_rows,
    write_prompts,
)
from pilottai_tpu.ops.paged import (
    PagedKVCache,
    gather_pages,
    install_lengths,
    write_chunk_rows_paged,
    write_prompts_paged,
)
from pilottai_tpu.ops.pallas.decode_attention import decode_attention
from pilottai_tpu.ops.pallas.paged_attention import (
    paged_decode_attention,
    paged_decode_attention_sharded,
)

NEG_INF = -2.0**30


def _paged_kernel_for(kv_mesh):
    """The paged-attention entry point for this dispatch: per-shard
    under ``shard_map`` when the pool is model-sharded (``kv_mesh`` set
    by the batcher only when ``paged_sharding_ok``), else the plain
    kernel. ONE selection point — the sharded-dispatch contract must
    not diverge between the decode / spec / model-draft sites."""
    if kv_mesh is not None:
        return partial(paged_decode_attention_sharded, kv_mesh)
    return paged_decode_attention

# ---------------------------------------------------------------------- #
# Packed admission metadata: ONE int32 + ONE float32 staging buffer per
# admission dispatch instead of ~10 per-field host→device transfers.
# Each tiny ``jnp.asarray`` pays a transfer-setup + dispatch floor
# (measured through the remote-TPU tunnel; PERF_NOTES round 8), so the
# per-row scalars ride two fixed-shape buffers and the admit functions
# unpack them FIRST thing inside the jit, where row slicing is free
# (the slices fuse into their consumers — values are bit-identical to
# the old per-field arguments).
# ---------------------------------------------------------------------- #

ADMIT_I32_ROWS = 9
(
    AI_SLOT,     # target slot (OOB = padding row)
    AI_TOPK,     # top-k (0 = disabled)
    AI_SEED,     # PRNG seed
    AI_EOS,      # eos token id (-1 = none)
    AI_BUDGET,   # max_new_tokens - 1
    AI_JSON,     # 1 = grammar-constrained JSON decoding
    AI_LEN,      # true prompt length (full prefill) / tail length (prefix)
    AI_SCHEMA,   # SchemaBank row (-1 = generic grammar)
    AI_PLEN,     # prefix length, broadcast (prefix admissions; else 0)
) = range(ADMIT_I32_ROWS)
ADMIT_F32_ROWS = 2
AF_TEMP, AF_TOPP = range(ADMIT_F32_ROWS)


def pack_admit_meta(
    A: int,
    slots=(),
    temps=(),
    topks=(),
    topps=(),
    seeds=(),
    eos=(),
    jsonm=(),
    budgets=(),
    lens=(),
    schema_ids=(),
    prefix_len: int = 0,
    pad_slot: int = 0,
):
    """Host-side builder for the packed admission staging buffers.

    Returns ``(meta_i32 [ADMIT_I32_ROWS, A], meta_f32 [ADMIT_F32_ROWS,
    A])`` as NUMPY arrays — the caller performs the single
    ``jnp.asarray`` per buffer (that is the point). Unspecified rows
    keep the padding-row defaults (slot = ``pad_slot`` i.e. OOB,
    temp 0, top_p 1, eos/schema −1, everything else 0)."""
    import numpy as _np

    mi = _np.zeros((ADMIT_I32_ROWS, A), _np.int32)
    mf = _np.zeros((ADMIT_F32_ROWS, A), _np.float32)
    mi[AI_SLOT] = pad_slot
    mi[AI_EOS] = -1
    mi[AI_SCHEMA] = -1
    mi[AI_PLEN] = int(prefix_len)
    mf[AF_TOPP] = 1.0
    for row_idx, values in (
        (AI_SLOT, slots), (AI_TOPK, topks), (AI_SEED, seeds),
        (AI_EOS, eos), (AI_BUDGET, budgets), (AI_JSON, jsonm),
        (AI_LEN, lens), (AI_SCHEMA, schema_ids),
    ):
        for col, v in enumerate(values):
            mi[row_idx, col] = int(v)
    for row_idx, values in ((AF_TEMP, temps), (AF_TOPP, topps)):
        for col, v in enumerate(values):
            mf[row_idx, col] = float(v)
    return mi, mf


def _unpack_admit_meta(meta_i32: jax.Array, meta_f32: jax.Array,
                       schema_tables) -> Tuple[jax.Array, ...]:
    """Split the packed staging buffers back into per-field rows
    (traced). ``schema_ids`` surfaces only when schema tables ride the
    dispatch, preserving the two-variant compile discipline the
    per-field signature had (a schema-free deployment never traces the
    schema path)."""
    return (
        meta_i32[AI_SLOT],
        meta_f32[AF_TEMP],
        meta_i32[AI_TOPK],
        meta_f32[AF_TOPP],
        meta_i32[AI_SEED],
        meta_i32[AI_EOS],
        meta_i32[AI_JSON].astype(bool),
        meta_i32[AI_BUDGET],
        meta_i32[AI_LEN],
        meta_i32[AI_SCHEMA] if schema_tables is not None else None,
    )


def _dequant_pair(k, v, scales, dtype):
    """Return full-precision (k, v) panels: identity for unquantized
    caches, fused broadcast-dequant for int8 ones (``scales`` is the
    matching (k_scale, v_scale) pair)."""
    if scales is None:
        return k, v
    return dequantize_kv(k, scales[0], dtype), dequantize_kv(v, scales[1], dtype)


def _bounded_panels(cache, l: int, op):
    """Layer ``l``'s prefix K/V as ``(k, v, scales)``: ``op`` bounds the
    read (a dense ``slice_in_dim`` or a paged ``gather_pages`` — both
    accept the [.., P, H] panels AND the [.., P] scale pools). int8
    caches return the RAW int8 panels plus ``(k_scale, v_scale)``; the
    attention applies scales AFTER its dot products
    (``q·(k·s) == s·(q·k)``, exactly), so per-block panel HBM reads stay
    int8-sized instead of a materialized full-precision copy. The ONE
    place the panel/scale pairing lives — decode_chunk,
    decode_chunk_spec and the paged prefix admission all read through
    it."""
    k_, v_ = cache.layers[l]
    sc = None if cache.scales is None else (
        op(cache.scales[l][0]), op(cache.scales[l][1])
    )
    return op(k_), op(v_), sc


def _layer_tail(cfg: ModelConfig, lp, x: jax.Array, attn: jax.Array) -> jax.Array:
    """Everything after a layer's attention weights: projection,
    optional post-norms, residual, MLP, residual. ONE definition shared
    by the plain chunk, the speculative chunk, the shallow-layer draft
    and the tail prefill — the draft's documented invariant ('the draft
    computes exactly the target's shallow prefix') depends on these
    staying in lockstep (review finding)."""
    out = _attn_out(cfg, lp["attn"], attn)
    if cfg.post_norms:
        out = rms_norm(out, lp["ln1_post"]["scale"], cfg.rms_eps, cfg.rms_offset)
    x = x + out
    h = rms_norm(x, lp["ln2"]["scale"], cfg.rms_eps, cfg.rms_offset)
    out, _ = _mlp(cfg, lp, h)
    if cfg.post_norms:
        out = rms_norm(out, lp["ln2_post"]["scale"], cfg.rms_eps, cfg.rms_offset)
    return x + out


# --------------------------------------------------------------------- #
# Fused greedy epilogue (ISSUE 14): logits projection + sampling as one
# vocab-tiled reduction for the common all-greedy, non-JSON dispatch.
# --------------------------------------------------------------------- #

# Vocab tile for the fused epilogue: big enough that the projection
# matmul stays MXU-shaped, small enough that a [B, tile] fp32 logits
# block lives in registers/VMEM instead of round-tripping HBM.
EPILOGUE_VOCAB_TILE = 8192


def _head_tile(params, off: int, end: int):
    """Columns [off, end) of the unembedding head, preserving the
    weight's quantized representation (the tile's HBM read stays
    int8/int4-sized). The head is ``lm_head`` when untied — possibly a
    ``QTensor`` (int4 mode falls the head back to int8) — else the
    transposed tied embedding."""
    if "lm_head" in params:
        head = params["lm_head"]
        if isinstance(head, QTensor):
            return QTensor(
                q=jax.lax.slice_in_dim(head.q, off, end, axis=-1),
                s=jax.lax.slice_in_dim(head.s, off, end, axis=-1),
            )
        if isinstance(head, Q4Tensor):
            return Q4Tensor(
                q=jax.lax.slice_in_dim(head.q, off, end, axis=-1),
                s=jax.lax.slice_in_dim(head.s, off, end, axis=-1),
                in_dim=head.in_dim, group=head.group,
            )
        return jax.lax.slice_in_dim(head, off, end, axis=-1)
    return jax.lax.slice_in_dim(params["embed"], off, end, axis=0).T


def fused_greedy_epilogue(
    cfg: ModelConfig, params, h: jax.Array,
    tile: int = EPILOGUE_VOCAB_TILE,
) -> jax.Array:
    """Greedy sampling fused into the logits projection: final-normed
    hidden states ``h`` [B, T, E] → argmax token ids [B, T] int32,
    byte-identical to ``argmax(_unembed(cfg, params, h), -1)``.

    The projection runs tile-by-tile over the vocab with a running
    (max, argmax) carry, so the [B, T, V] fp32 logits buffer — 16 MB+
    per step at a 128K vocab, written and immediately re-read by the
    sampler — never materializes in HBM, and the separate sampler
    small-ops (two full-vocab sorts for the top-k/top-p masks that
    greedy slots never use) disappear entirely. Per-element dot
    products are unchanged (tiling splits the *output* axis, never the
    contraction), softcap applies per tile (same monotonic values), and
    ties resolve to the lowest index exactly like ``jnp.argmax``: the
    in-tile argmax picks the first max and the cross-tile carry only
    replaces on a strictly greater max."""
    B, T, E = h.shape
    V = cfg.vocab_size
    x = h.reshape(B * T, E)
    best = jnp.full((B * T,), -jnp.inf, jnp.float32)
    idx = jnp.zeros((B * T,), jnp.int32)
    for off in range(0, V, tile):
        end = min(off + tile, V)
        logits_t = qmatmul(
            x, _head_tile(params, off, end),
            preferred_element_type=jnp.float32,
        )
        if cfg.logit_softcap > 0.0:
            logits_t = (
                jnp.tanh(logits_t / cfg.logit_softcap) * cfg.logit_softcap
            )
        m = jnp.max(logits_t, axis=-1)
        a = jnp.argmax(logits_t, axis=-1).astype(jnp.int32)
        better = m > best
        idx = jnp.where(better, off + a, idx)
        best = jnp.where(better, m, best)
    return idx.reshape(B, T)


def _advance_keys(sampling: SamplingState) -> SamplingState:
    """PRNG parity with ``sample_core`` for the fused epilogue: the
    SAME key split per step (``sampling.split_step_keys``), keys
    carried, step keys discarded (greedy slots never consume them) —
    the sampling-state trajectory stays bit-identical to the unfused
    path by sharing the scheme, not by copying it."""
    _, carry_keys = split_step_keys(sampling.key)
    return sampling._replace(key=carry_keys)


class DecodeState(NamedTuple):
    """Per-slot generation state living on device across chunks."""

    tokens: jax.Array  # [B] int32 — next input token (last sampled)
    done: jax.Array    # [B] bool — finished or empty slot
    budget: jax.Array  # [B] int32 — generations still allowed

    @classmethod
    def create(cls, n_slots: int) -> "DecodeState":
        return cls(
            tokens=jnp.zeros((n_slots,), jnp.int32),
            done=jnp.ones((n_slots,), bool),
            budget=jnp.zeros((n_slots,), jnp.int32),
        )


@partial(jax.jit, donate_argnames=("state",))
def admit_decode(
    state: DecodeState,
    slots: jax.Array,         # [A] int32; OOB rows dropped
    first_tokens: jax.Array,  # [A] int32 — sampled from the prefill logits
    budgets: jax.Array,       # [A] int32 — max_new_tokens - 1 (first token
                              # already produced); <= 0 admits as done
    live: jax.Array,          # [A] bool — False rows are padding
) -> DecodeState:
    slots = jnp.where(live, slots, state.tokens.shape[0])
    return DecodeState(
        tokens=state.tokens.at[slots].set(first_tokens, mode="drop"),
        done=state.done.at[slots].set(budgets <= 0, mode="drop"),
        budget=state.budget.at[slots].set(jnp.maximum(budgets, 0), mode="drop"),
    )


@partial(jax.jit, donate_argnames=("state",))
def release_decode(state: DecodeState, slots: jax.Array) -> DecodeState:
    """Host-side completion/cancel: stop decoding these slots."""
    return DecodeState(
        tokens=state.tokens,
        done=state.done.at[slots].set(True, mode="drop"),
        budget=state.budget.at[slots].set(0, mode="drop"),
    )


def _prefix_stats_dense(
    qg: jax.Array,       # [B, K, G, H]
    layer_k: jax.Array,  # [B, K, S, H] (compute dtype, or int8 w/ scales)
    layer_v: jax.Array,
    last: jax.Array,     # [B] max valid key index (may be -1: empty)
    qpos: jax.Array,     # [B] query absolute position
    scale: float,
    softcap: float,
    window: int,
    kv_scales=None,      # (k_scale [B,K,S], v_scale) for int8 panels
):
    """XLA fallback for the Pallas prefix kernel (CPU tests / tiny models).
    Same (acc, m, l) contract. int8 panels stream raw through the dots;
    the per-position scales fold in after (before softcap), which is
    algebraically exact and keeps HBM reads int8-sized."""
    B, K, G, H = qg.shape
    S = layer_k.shape[2]
    if kv_scales is not None:
        layer_k = layer_k.astype(qg.dtype)
    s = jnp.einsum(
        "bkgh,bksh->bkgs", qg, layer_k, preferred_element_type=jnp.float32
    ) * scale
    if kv_scales is not None:
        s = s * kv_scales[0][:, :, None, :]
    if softcap > 0.0:
        s = jnp.tanh(s / softcap) * softcap
    col = jnp.arange(S)[None, None, None, :]
    mask = col <= last[:, None, None, None]
    if window > 0:
        mask &= (qpos[:, None, None, None] - col) < window
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)                                   # [B, K, G]
    p = jnp.where(
        m[..., None] > NEG_INF / 2, jnp.exp(s - m[..., None]), 0.0
    )
    l = jnp.sum(p, axis=-1)
    if kv_scales is not None:
        p = p * kv_scales[1][:, :, None, :]
        layer_v = layer_v.astype(qg.dtype)
        acc = jnp.einsum(
            "bkgs,bksh->bkgh", p.astype(qg.dtype), layer_v,
            preferred_element_type=jnp.float32,
        )
    else:
        acc = jnp.einsum(
            "bkgs,bksh->bkgh", p.astype(layer_v.dtype), layer_v,
            preferred_element_type=jnp.float32,
        )
    return acc.reshape(B, K * G, H), m.reshape(B, K * G), l.reshape(B, K * G)


def _ring_stats(
    qg: jax.Array,      # [B, K, G, H]
    ring_k: jax.Array,  # [B, K, N, H]
    ring_v: jax.Array,
    step: jax.Array,    # scalar — current chunk step i (rows 0..i valid)
    scale: float,
    softcap: float,
    window: int,
):
    """In-chunk attention over the ring buffer. Row j holds the token at
    chunk-relative offset j; for an active slot offset == step, so the
    causal mask is j <= step and the window check (step - j) < window."""
    B, K, G, H = qg.shape
    N = ring_k.shape[2]
    s = jnp.einsum(
        "bkgh,bknh->bkgn", qg, ring_k, preferred_element_type=jnp.float32
    ) * scale
    if softcap > 0.0:
        s = jnp.tanh(s / softcap) * softcap
    j = jnp.arange(N)[None, None, None, :]
    mask = j <= step
    if window > 0:
        mask &= (step - j) < window
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])  # row 0 always valid -> never all-masked
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum(
        "bkgn,bknh->bkgh", p.astype(ring_v.dtype), ring_v,
        preferred_element_type=jnp.float32,
    )
    return acc.reshape(B, K * G, H), m.reshape(B, K * G), l.reshape(B, K * G)


def _combine_stats(acc_a, m_a, l_a, acc_b, m_b, l_b):
    """Merge two online-softmax partials over disjoint key sets and
    normalize (final-merge form of ``_merge_stats``)."""
    acc, _, l = _merge_stats(acc_a, m_a, l_a, acc_b, m_b, l_b)
    return acc / jnp.maximum(l, 1e-30)[..., None]


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "n_steps", "use_pallas", "prefix_bound", "page_strip",
        "kv_mesh", "fused_epilogue",
    ),
    donate_argnames=("cache", "dstate", "sampling"),
)
def decode_chunk(
    params,
    cfg: ModelConfig,
    cache: KVCache,
    dstate: DecodeState,
    sampling: SamplingState,
    n_steps: int,
    use_pallas: bool = True,
    prefix_bound: Optional[int] = None,
    table: Optional[jax.Array] = None,  # [B, max_pages] — paged cache only
    json_tables: Optional[Tuple[jax.Array, jax.Array]] = None,
    schema_tables: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None,
    # ^ SchemaBank (ALLOWED, NEXT, MINCOST) — schema-constrained slots
    # ^ (token_bytes [Vt, L], token_len [Vt]) — subword JSON grammar mask
    page_strip: int = 1,  # static — pages per paged-kernel grid cell
                          # (autotuned by the batcher at warmup)
    kv_mesh: Any = None,  # static — serving mesh: the paged Pallas path
                          # runs per-shard under shard_map (pool kv-heads
                          # over 'model', slots over 'data'); None = the
                          # single-chip dispatch
    fused_epilogue: bool = False,  # static — all slots greedy + non-JSON
                          # (the batcher checks at dispatch): sampling
                          # fuses into a vocab-tiled projection and the
                          # [B, V] logits never materialize
) -> Tuple[jax.Array, jax.Array, KVCache, DecodeState, SamplingState]:
    """Run ``n_steps`` decode steps for every slot in one dispatch.

    Returns ``(tokens [n, B], valid [n, B], cache, dstate, sampling)``;
    ``valid[i, b]`` marks tokens actually generated (slot active entering
    step i). Slots flip ``done`` on device at EOS / budget / context-full,
    so a finished slot stops writing cache and burning samples mid-chunk.

    ``prefix_bound`` (static) caps how much of each cache panel the prefix
    attention reads: the caller promises every *live* slot's length is
    ≤ bound, so keys past it can only belong to freed slots (whose output
    is discarded). Decode is HBM-bound and the cache read is roughly half
    the traffic at S=512 — reading ``[., ., bound, .]`` instead of the
    full ``[., ., S, .]`` panels makes short-context serving pay for the
    context it *has*, not the capacity it reserved. The host buckets the
    bound to powers of two so compile variants stay O(log S).
    """
    B = dstate.tokens.shape[0]
    paged = isinstance(cache, PagedKVCache)
    kv_scales = None  # scale pools for the Pallas paged kernel only
    if paged:
        assert table is not None, "paged decode needs the block table"
        P = cache.page_size
        S = table.shape[1] * P               # per-slot capacity
        Sb = S if prefix_bound is None else max(1, min(prefix_bound, S))
        n_blocks = -(-Sb // P)
        if use_pallas:
            prefix_panels = tuple(
                (k_, v_, None) for (k_, v_) in cache.layers
            )                                # pools; kernel reads via table
            kv_scales = cache.scales         # int8 pools dequant in-kernel
        else:
            # XLA fallback: materialize bounded panels ONCE per chunk
            # (pool contents are frozen during the scan — decode K/V
            # goes to the ring until chunk end), then run the same
            # dense prefix attention as the unpaged path; int8 panels
            # gather raw with their scales (applied post-dot).
            prefix_panels = tuple(
                _bounded_panels(
                    cache, l, lambda a: gather_pages(a, table, n_blocks),
                )
                for l in range(cfg.n_layers)
            )
    else:
        S = cache.max_len
        Sb = S if prefix_bound is None else max(1, min(prefix_bound, S))
        # Bounded read-only views for the prefix attention (writes at
        # chunk end still land in the full panels); int8 panels slice
        # raw with their scales — applied after the dots, so per-step
        # HBM reads stay int8-sized.
        prefix_panels = tuple(
            _bounded_panels(
                cache, l, lambda a: jax.lax.slice_in_dim(a, 0, Sb, axis=2),
            )
            for l in range(cfg.n_layers)
        )
    start = cache.lengths                    # [B] frozen during the chunk
    windows = cfg.window_sizes()
    qscale = cfg.query_scale if cfg.query_scale is not None else cfg.head_dim**-0.5
    G = cfg.n_heads // cfg.n_kv_heads
    batch_shape = (B, cfg.n_kv_heads, n_steps, cfg.head_dim)
    # Rings hold fresh in-chunk K/V in compute precision even when the
    # resident cache is int8 (they are quantized at the chunk-end write).
    cache_dtype = (
        cfg.dtype if cache.scales is not None else cache.layers[0][0].dtype
    )
    rings = tuple(
        (jnp.zeros(batch_shape, cache_dtype), jnp.zeros(batch_shape, cache_dtype))
        for _ in range(cfg.n_layers)
    )
    prefix_last = start - 1                  # max valid prefix key index

    def step(carry):
        i, tokens, done, budget, offset, sampling, rings, out_t, out_v = carry
        active = ~done
        pos = start + offset                 # current token's position
        x = _embed(cfg, params, tokens[:, None])          # [B, 1, E]
        sin, cos = rope_tables(pos[:, None], cfg.head_dim, cfg.rope_theta)

        new_rings = []
        for l in range(cfg.n_layers):
            lp = jax.tree.map(lambda a: a[l], params["layers"])
            window = int(windows[l])
            layer_k, layer_v, layer_sc = prefix_panels[l]
            rk, rv = rings[l]
            p = lp["attn"]

            h = rms_norm(x, lp["ln1"]["scale"], cfg.rms_eps, cfg.rms_offset)
            q, k, v = _qkv(cfg, p, h, sin, cos)  # [B, 1, heads, H]

            rk = jax.lax.dynamic_update_slice(
                rk, k[:, 0][:, :, None].astype(rk.dtype), (0, 0, i, 0)
            )
            rv = jax.lax.dynamic_update_slice(
                rv, v[:, 0][:, :, None].astype(rv.dtype), (0, 0, i, 0)
            )

            qf = q[:, 0]                                  # [B, N, H]
            if paged and use_pallas:
                # One fused kernel invocation per layer: the page strip
                # streams the prefix AND the final grid cell folds the
                # chunk ring in (the separate per-layer ring dispatch +
                # combine this path used to pay per step is gone) — the
                # plain-decode stats contract allows it because the
                # ring's validity is the shared scalar `i`. On a serving
                # mesh the kernel runs per-shard (kv-heads over 'model',
                # slots over 'data'); the cross-shard merge is the
                # output projection's all-reduce, not an attention-side
                # collective (heads are independent).
                kernel = _paged_kernel_for(kv_mesh)
                acc_p, _, l_p = kernel(
                    qf, layer_k, layer_v, table, prefix_last,
                    q_positions=pos, n_blocks=n_blocks, n_strip=page_strip,
                    scale=qscale, softcap=cfg.attn_softcap, window=window,
                    k_scales=None if kv_scales is None else kv_scales[l][0],
                    v_scales=None if kv_scales is None else kv_scales[l][1],
                    ring_k=rk, ring_v=rv, ring_step=i,
                )
                attn = acc_p / jnp.maximum(l_p, 1e-30)[..., None]
            else:
                if use_pallas and not paged:
                    acc_p, m_p, l_p = decode_attention(
                        qf, layer_k, layer_v, prefix_last, q_positions=pos,
                        scale=qscale, softcap=cfg.attn_softcap, window=window,
                        return_stats=True,
                    )
                else:
                    acc_p, m_p, l_p = _prefix_stats_dense(
                        qf.reshape(B, cfg.n_kv_heads, G, cfg.head_dim),
                        layer_k, layer_v, prefix_last, pos,
                        qscale, cfg.attn_softcap, window,
                        kv_scales=layer_sc,
                    )
                acc_c, m_c, l_c = _ring_stats(
                    qf.reshape(B, cfg.n_kv_heads, G, cfg.head_dim),
                    rk, rv, i, qscale, cfg.attn_softcap, window,
                )
                attn = _combine_stats(acc_p, m_p, l_p, acc_c, m_c, l_c)

            x = _layer_tail(
                cfg, lp, x,
                attn.astype(x.dtype).reshape(B, 1, cfg.n_heads, cfg.head_dim),
            )
            new_rings.append((rk, rv))

        h = rms_norm(x, params["final_norm"]["scale"], cfg.rms_eps, cfg.rms_offset)
        if fused_epilogue:
            # All-greedy non-JSON dispatch: argmax fused into the
            # vocab-tiled projection (byte-identical to the unfused
            # sampler for these slots — the JSON mask is the identity
            # when no slot enables it, and greedy never reads the
            # step key; the key split still advances for state parity).
            sampled = fused_greedy_epilogue(cfg, params, h)[:, 0]
            sampling = _advance_keys(sampling)
        else:
            logits = _unembed(cfg, params, h)[:, 0]       # [B, V] fp32
            sampled, sampling = sample_core(
                logits, sampling, json_remaining=budget,
                json_token_tables=json_tables,
                json_schema_tables=schema_tables,
            )
        new_budget = budget - active.astype(jnp.int32)
        hit_eos = (sampling.eos_id >= 0) & (sampled == sampling.eos_id)
        ctx_full = (pos + 1) >= (S - 1)
        new_done = done | (active & (hit_eos | (new_budget <= 0) | ctx_full))
        new_tokens = jnp.where(active, sampled, tokens)
        new_offset = offset + active.astype(jnp.int32)
        out_t = jax.lax.dynamic_update_slice(out_t, sampled[None], (i, 0))
        out_v = jax.lax.dynamic_update_slice(out_v, active[None], (i, 0))
        return (
            i + 1, new_tokens, new_done, new_budget, new_offset, sampling,
            tuple(new_rings), out_t, out_v,
        )

    offset0 = jnp.zeros((B,), jnp.int32)
    carry0 = (
        jnp.int32(0), dstate.tokens, dstate.done, dstate.budget, offset0,
        sampling, rings,
        jnp.zeros((n_steps, B), jnp.int32), jnp.zeros((n_steps, B), bool),
    )
    # while_loop with all-done early exit (see decode_chunk_spec): each
    # step streams the full weight set, so steps past the last active
    # slot are pure waste — the dispatch now pays only for steps used.
    (
        _, tokens, done, budget, offset, sampling, rings, out_toks, out_valid,
    ) = jax.lax.while_loop(
        lambda c: (c[0] < n_steps) & ~jnp.all(c[2]),
        step,
        carry0,
    )

    if paged:
        cache = write_chunk_rows_paged(
            cache, table, [r[0] for r in rings], [r[1] for r in rings],
            start, offset,
        )
    else:
        cache = write_chunk_rows(
            cache, [r[0] for r in rings], [r[1] for r in rings], start, offset
        )
    dstate = DecodeState(tokens=tokens, done=done, budget=budget)
    return out_toks, out_valid, cache, dstate, sampling


# --------------------------------------------------------------------- #
# Speculative decode: n-gram (prompt-lookup) self-drafting
# --------------------------------------------------------------------- #
#
# One weight pass per token caps llama3-8b at ~605 ms per 48-token step on
# one v5e (8 GB int8 / 634 GB/s HBM) — VERDICT r2 Weak #2. Decode is
# memory-bound on the weight stream, so verifying a D-token block per pass
# streams the same bytes but can emit up to D tokens: the MXU cost of D
# query rows is noise next to the weight read. Drafts come from the
# sequence's own history (2-gram match → copy the continuation), the
# training-free scheme that excels exactly on agent workloads: JSON keys,
# tool names, and prompt spans repeat constantly. Acceptance only ever
# compares the model's OWN (masked) greedy output to the draft, so a bad
# draft costs speed, never correctness.
#
# Scope: greedy (temperature==0) slots speculate; sampled slots emit one
# exact-semantics token per block. Sampled streams are ALSO
# bit-identical to the non-speculative engine: a block advances the
# PRNG exactly once (row 0's sample_core) and emits exactly one sampled
# token, so the key sequence at emission points matches the plain
# chunk's step-per-token advance (pinned by
# tests/test_speculative.py::test_spec_sampled_slots_bit_identical).


def _ngram_drafts(
    history: jax.Array,  # [B, S] token ids by absolute position
    pos: jax.Array,      # [B] current token's position
    cur: jax.Array,      # [B] current token
    n_drafts: int,
) -> jax.Array:
    """Propose ``n_drafts`` continuation tokens per slot by matching the
    latest (prev2, prev, cur) 3-gram earlier in the slot's own history —
    backing off to the latest 2-gram — and copying what followed it. The
    3-gram tier disambiguates repeated contexts (a byte pair like ``",
    "`` recurs with many continuations inside JSON; three bytes usually
    pin the right one), which is where the 2-gram's acceptance plateaued.
    No match → zeros (harmless: acceptance compares against the model's
    output, so junk drafts just miss — draft quality affects speed,
    never content)."""
    B, S = history.shape
    idx = jnp.arange(S)[None, :]
    bidx = jnp.arange(B)[:, None]
    prev = jnp.take_along_axis(
        history, jnp.maximum(pos - 1, 0)[:, None], axis=1
    )                                                     # [B, 1]
    prev2 = jnp.take_along_axis(
        history, jnp.maximum(pos - 2, 0)[:, None], axis=1
    )
    prev_col = jnp.concatenate(
        [jnp.full((B, 1), -1, history.dtype), history[:, :-1]], axis=1
    )
    prev2_col = jnp.concatenate(
        [jnp.full((B, 2), -1, history.dtype), history[:, :-2]], axis=1
    )
    match = (history == cur[:, None]) & (prev_col == prev)
    # Only occurrences whose whole n-draft continuation is already
    # written (j + n_drafts <= pos): matching the frontier proposes
    # zeros from unwritten positions and never accepts — measured on
    # v5e as acceptance ~0 even on a constant output stream.
    match &= (idx <= pos[:, None] - n_drafts) & (idx >= 1)
    match3 = match & (prev2_col == prev2) & (idx >= 2) & (pos[:, None] >= 2)
    found = match.any(axis=1)
    found3 = match3.any(axis=1)
    j2 = jnp.argmax(jnp.where(match, idx, -1), axis=1)    # latest match
    j3 = jnp.argmax(jnp.where(match3, idx, -1), axis=1)
    j = jnp.where(found3, j3, j2)
    dpos = j[:, None] + 1 + jnp.arange(n_drafts)[None, :]
    drafts = history[bidx, jnp.minimum(dpos, S - 1)]
    return jnp.where(found[:, None], drafts, 0)


def _model_drafts(
    params,
    cfg: ModelConfig,
    draft_layers: int,
    n_draft: int,
    cur: jax.Array,      # [B] current token
    pos: jax.Array,      # [B] its absolute position
    prefix_panels,       # per-layer bounded panels (or pools when paged)
    rings,               # per-layer (rk, rv) chunk rings [B, K, R, H]
    start: jax.Array,    # [B] slot length at chunk start
    offset: jax.Array,   # [B] valid ring rows
    last: jax.Array,     # [B] max valid prefix key index
    paged_kernel,        # None, or dict(table=, n_blocks=, kv_scales=)
    windows,
    qscale: float,
) -> jax.Array:
    """Self-speculative drafting: run the target model's own FIRST
    ``draft_layers`` layers (plus final norm + unembed — weights shared,
    zero extra HBM) autoregressively for ``n_draft`` steps. This is the
    draft-model path for traffic the n-gram can't predict (novel prose,
    first-time prompts): a shallow prefix of the network agrees with the
    full forward far more often than a history lookup does, at
    ``draft_layers / n_layers`` of a weight pass per draft token
    (LayerSkip-style early-exit drafting; see PAPERS.md).

    The draft attends exactly what the verify pass will: bounded prefix
    panels + the chunk ring + its own in-block buffer — so the layers it
    DOES run compute the same K/V the target would for those tokens.
    Draft quality only affects speed, never output: acceptance still
    compares the target's masked greedy rows against these proposals."""
    B = cur.shape[0]
    K = cfg.n_kv_heads
    G = cfg.n_heads // cfg.n_kv_heads
    H = cfg.head_dim
    cache_dtype = rings[0][0].dtype
    bufs = tuple(
        (jnp.zeros((B, K, n_draft, H), cache_dtype),
         jnp.zeros((B, K, n_draft, H), cache_dtype))
        for _ in range(draft_layers)
    )

    def dstep(carry, j):
        tok, bufs = carry
        qpos = pos + j                       # input token's position
        x = _embed(cfg, params, tok[:, None])
        sin, cos = rope_tables(qpos[:, None], cfg.head_dim, cfg.rope_theta)
        new_bufs = []
        for l in range(draft_layers):
            lp = jax.tree.map(lambda a: a[l], params["layers"])
            window = int(windows[l])
            rk, rv = rings[l]
            bk, bv = bufs[l]
            h = rms_norm(x, lp["ln1"]["scale"], cfg.rms_eps, cfg.rms_offset)
            q, k, v = _qkv(cfg, lp["attn"], h, sin, cos)
            # Write THIS token's K/V before attending (count j+1): the
            # verify pass's in-block mask (e <= d) includes self, and the
            # draft must compute exactly the target's shallow prefix or
            # acceptance silently degrades (review finding).
            bk = jax.lax.dynamic_update_slice(
                bk, k[:, 0][:, :, None].astype(bk.dtype), (0, 0, j, 0)
            )
            bv = jax.lax.dynamic_update_slice(
                bv, v[:, 0][:, :, None].astype(bv.dtype), (0, 0, j, 0)
            )
            qf = q[:, 0]                                   # [B, N, H]
            qg = qf.reshape(B, K, G, H)
            if paged_kernel is not None:
                sc = paged_kernel["kv_scales"]
                kernel = _paged_kernel_for(paged_kernel.get("kv_mesh"))
                acc_p, m_p, l_p = kernel(
                    qf, prefix_panels[l][0], prefix_panels[l][1],
                    paged_kernel["table"], last, q_positions=qpos,
                    n_blocks=paged_kernel["n_blocks"], scale=qscale,
                    softcap=cfg.attn_softcap, window=window,
                    n_strip=paged_kernel["n_strip"],
                    k_scales=None if sc is None else sc[l][0],
                    v_scales=None if sc is None else sc[l][1],
                )
                acc_p = acc_p.reshape(B, K, G, H)
                m_p = m_p.reshape(B, K, G)
                l_p = l_p.reshape(B, K, G)
            else:
                acc_p, m_p, l_p = _prefix_stats_dense(
                    qg, prefix_panels[l][0], prefix_panels[l][1],
                    last, qpos, qscale, cfg.attn_softcap, window,
                    kv_scales=prefix_panels[l][2],
                )
                acc_p = acc_p.reshape(B, K, G, H)
                m_p = m_p.reshape(B, K, G)
                l_p = l_p.reshape(B, K, G)
            acc_r, m_r, l_r = _ragged_stats(
                qg, rk, rv, offset, start, qpos,
                qscale, cfg.attn_softcap, window,
            )
            acc_b, m_b, l_b = _ragged_stats(
                qg, bk, bv, jnp.full((B,), j + 1, jnp.int32), pos, qpos,
                qscale, cfg.attn_softcap, window,
            )
            acc, m, lsum = _merge_stats(acc_p, m_p, l_p, acc_r, m_r, l_r)
            acc, _, lsum = _merge_stats(acc, m, lsum, acc_b, m_b, l_b)
            attn = acc / jnp.maximum(lsum, 1e-30)[..., None]
            x = _layer_tail(
                cfg, lp, x,
                attn.astype(x.dtype).reshape(B, 1, cfg.n_heads, H),
            )
            new_bufs.append((bk, bv))
        h = rms_norm(
            x, params["final_norm"]["scale"], cfg.rms_eps, cfg.rms_offset
        )
        nxt = jnp.argmax(_unembed(cfg, params, h)[:, 0], axis=-1).astype(
            jnp.int32
        )
        return (nxt, tuple(new_bufs)), nxt

    (_, _), drafts = jax.lax.scan(
        dstep, (cur, bufs), jnp.arange(n_draft)
    )
    return drafts.T                                        # [B, n_draft]


def _merge_stats(acc_a, m_a, l_a, acc_b, m_b, l_b):
    """Unnormalized online-softmax merge over disjoint key sets (the
    normalizing division happens once, after the last merge)."""
    m = jnp.maximum(m_a, m_b)
    wa = jnp.where(m_a > NEG_INF / 2, jnp.exp(m_a - m), 0.0)
    wb = jnp.where(m_b > NEG_INF / 2, jnp.exp(m_b - m), 0.0)
    return acc_a * wa[..., None] + acc_b * wb[..., None], m, l_a * wa + l_b * wb


def _ragged_stats(
    qg: jax.Array,     # [B, K, G, H] single-position queries
    ks: jax.Array,     # [B, K, N, H] — row r valid iff r < count[b]
    vs: jax.Array,
    count: jax.Array,  # [B] valid rows
    pos0: jax.Array,   # [B] absolute position of row 0 (sliding window)
    qpos: jax.Array,   # [B] query positions
    scale: float,
    softcap: float,
    window: int,
):
    """Online-softmax partials over a per-slot ragged key buffer — the
    generic form of ``_ring_stats`` (whose validity is a shared scalar).
    Used by the shallow-layer draft for both the chunk ring and its own
    in-block buffer."""
    B, K, G, H = qg.shape
    N = ks.shape[2]
    s = jnp.einsum(
        "bkgh,bknh->bkgn", qg, ks, preferred_element_type=jnp.float32
    ) * scale
    if softcap > 0.0:
        s = jnp.tanh(s / softcap) * softcap
    r = jnp.arange(N)[None, None, None, :]
    mask = r < count[:, None, None, None]
    if window > 0:
        kpos = pos0[:, None, None, None] + r
        mask &= (qpos[:, None, None, None] - kpos) < window
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.where(m[..., None] > NEG_INF / 2, jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum(
        "bkgn,bknh->bkgh", p.astype(vs.dtype), vs,
        preferred_element_type=jnp.float32,
    )
    return acc, m, l


def _spec_block_attn(
    qg: jax.Array,       # [B, K, G, D, H] block queries
    layer_k: jax.Array,  # [B, K, Sb, H] bounded prefix panels (None when
    layer_v: jax.Array,  # prefix_stats is given)
    ring_k: jax.Array,   # [B, K, R, H] chunk ring (row r = position start+r)
    ring_v: jax.Array,
    blk_k: jax.Array,    # [B, K, D, H] the block's own keys
    blk_v: jax.Array,
    last: jax.Array,     # [B] max valid prefix key index (may be -1)
    start: jax.Array,    # [B] slot length at chunk start
    offset: jax.Array,   # [B] valid ring rows
    qpos: jax.Array,     # [B, D] absolute query positions
    scale: float,
    softcap: float,
    window: int,
    prefix_stats: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None,
    # ^ precomputed (acc_p [B,K,G,D,H], m_p [B,K,G,D], l_p) — the Pallas
    # paged kernel's output; skips the dense prefix pass.
    kv_scales=None,      # (k_scale [B,K,S], v_scale) for int8 panels
) -> jax.Array:
    """Three-source attention for a speculative block: bounded prefix
    panels + in-chunk ring (per-slot valid count) + the block itself
    (causal). Dense XLA on purpose: decode attention is HBM-bound and
    dense beat the Pallas prefix kernel at serving context sizes
    (measured on v5e, round 2). The paged-pool path supplies its prefix
    partials via ``prefix_stats`` instead (its pages never materialize
    as dense panels)."""
    B, K, G, D, H = qg.shape

    def softcapped(s):
        return jnp.tanh(s / softcap) * softcap if softcap > 0.0 else s

    if prefix_stats is not None:
        acc_p, m_p, l_p = prefix_stats
    else:
        # Prefix: every block query sees the whole valid prefix. int8
        # panels stream raw; scales fold in after the dots (exact).
        lk = layer_k.astype(qg.dtype) if kv_scales is not None else layer_k
        s = jnp.einsum(
            "bkgdh,bksh->bkgds", qg, lk,
            preferred_element_type=jnp.float32,
        ) * scale
        if kv_scales is not None:
            s = s * kv_scales[0][:, :, None, None, :]
        s = softcapped(s)
        col = jnp.arange(layer_k.shape[2])[None, None, None, None, :]
        mask = col <= last[:, None, None, None, None]
        if window > 0:
            mask &= (qpos[:, None, None, :, None] - col) < window
        s = jnp.where(mask, s, NEG_INF)
        m_p = jnp.max(s, axis=-1)
        p = jnp.where(
            m_p[..., None] > NEG_INF / 2, jnp.exp(s - m_p[..., None]), 0.0
        )
        l_p = jnp.sum(p, axis=-1)
        if kv_scales is not None:
            p = p * kv_scales[1][:, :, None, None, :]
            lv = layer_v.astype(qg.dtype)
        else:
            lv = layer_v
        acc_p = jnp.einsum(
            "bkgds,bksh->bkgdh", p.astype(qg.dtype if kv_scales is not None
                                          else layer_v.dtype), lv,
            preferred_element_type=jnp.float32,
        )

    # Ring: rows < offset are live; row r sits at position start + r.
    R = ring_k.shape[2]
    s = softcapped(jnp.einsum(
        "bkgdh,bkrh->bkgdr", qg, ring_k,
        preferred_element_type=jnp.float32,
    ) * scale)
    r = jnp.arange(R)[None, None, None, None, :]
    rpos = start[:, None, None, None, None] + r
    mask = r < offset[:, None, None, None, None]
    if window > 0:
        mask &= (qpos[:, None, None, :, None] - rpos) < window
    s = jnp.where(mask, s, NEG_INF)
    m_r = jnp.max(s, axis=-1)
    p = jnp.where(m_r[..., None] > NEG_INF / 2, jnp.exp(s - m_r[..., None]), 0.0)
    l_r = jnp.sum(p, axis=-1)
    acc_r = jnp.einsum(
        "bkgdr,bkrh->bkgdh", p.astype(ring_v.dtype), ring_v,
        preferred_element_type=jnp.float32,
    )

    # Block itself: causal within the D candidates (e <= d); query d is
    # always its own key, so this source is never empty.
    s = softcapped(jnp.einsum(
        "bkgdh,bkeh->bkgde", qg, blk_k,
        preferred_element_type=jnp.float32,
    ) * scale)
    e = jnp.arange(D)[None, None, None, None, :]
    d = jnp.arange(D)[None, None, None, :, None]
    mask = e <= d
    if window > 0:
        mask &= (d - e) < window
    s = jnp.where(mask, s, NEG_INF)
    m_b = jnp.max(s, axis=-1)
    p = jnp.exp(s - m_b[..., None])
    l_b = jnp.sum(p, axis=-1)
    acc_b = jnp.einsum(
        "bkgde,bkeh->bkgdh", p.astype(blk_v.dtype), blk_v,
        preferred_element_type=jnp.float32,
    )

    acc, m, l = _merge_stats(acc_p, m_p, l_p, acc_r, m_r, l_r)
    acc, _, l = _merge_stats(acc, m, l, acc_b, m_b, l_b)
    attn = acc / jnp.maximum(l, 1e-30)[..., None]         # [B, K, G, D, H]
    return attn.transpose(0, 3, 1, 2, 4).reshape(B, D, K * G * H)


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "n_steps", "draft_len", "prefix_bound", "use_pallas",
        "draft_layers", "page_strip", "kv_mesh", "fused_epilogue",
    ),
    donate_argnames=("cache", "dstate", "sampling", "history"),
)
def decode_chunk_spec(
    params,
    cfg: ModelConfig,
    cache: KVCache,
    dstate: DecodeState,
    sampling: SamplingState,
    history: jax.Array,      # [B, S] token ids by position
    n_steps: int,
    draft_len: int,          # D >= 2: block width (1 current + D-1 drafts)
    prefix_bound: Optional[int] = None,
    json_tables: Optional[Tuple[jax.Array, jax.Array]] = None,
    schema_tables: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None,
    table: Optional[jax.Array] = None,  # [B, max_pages] — paged cache only
    use_pallas: bool = False,           # paged prefix reads via the Pallas
                                        # kernel (TPU); else gather fallback
    draft_layers: int = 0,   # >0: shallow-layer self-drafting available
    draft_mode: Optional[jax.Array] = None,  # [B] bool — slots whose
                                        # drafts come from the model
                                        # instead of the n-gram lookup
    page_strip: int = 1,     # static — pages per paged-kernel grid cell
    kv_mesh: Any = None,     # static — serving mesh for the per-shard
                             # paged kernel (see decode_chunk)
    fused_epilogue: bool = False,  # static — all slots greedy + non-JSON:
                             # row 0's sampler AND the verify rows fuse
                             # into one vocab-tiled argmax (see
                             # decode_chunk)
) -> Tuple[jax.Array, jax.Array, KVCache, DecodeState, SamplingState, jax.Array]:
    """Speculative fused chunk: ``n_steps`` verify-blocks of ``draft_len``
    tokens per dispatch. Same contract as ``decode_chunk`` except the
    token stream comes back as ``[n_steps * draft_len, B]`` (block-major,
    draft-minor) and the per-slot emit count varies 1..D per block.

    Greedy slots emit ``accepted + 1`` tokens per weight pass —
    bit-identical to the non-speculative chunk's output. Sampled slots
    emit exactly one sampled token per block, ALSO bit-identical: one
    PRNG advance per block == one advance per emitted token, matching
    the plain chunk's key sequence at every emission position.

    Works on BOTH caches: dense panels are read through bounded slices;
    paged pools through the block table — the extended Pallas paged
    kernel streams each block's D queries against the slot's pages
    (``q_blocks``), or the XLA fallback materializes bounded dense
    panels once per chunk (pool contents are frozen during the scan)."""
    from pilottai_tpu.engine.sampling import _advance_json, fused_verify_rows

    B = dstate.tokens.shape[0]
    D = draft_len
    assert D >= 2, "draft_len < 2 is plain decode_chunk"
    paged = isinstance(cache, PagedKVCache)
    kv_scales = None
    if paged:
        assert table is not None, "paged decode needs the block table"
        P = cache.page_size
        S = table.shape[1] * P
        Sb = S if prefix_bound is None else max(1, min(prefix_bound, S))
        n_blocks = -(-Sb // P)
        if use_pallas:
            prefix_panels = tuple(
                (k_, v_, None) for (k_, v_) in cache.layers
            )                                # pools; kernel reads via table
            kv_scales = cache.scales
        else:
            prefix_panels = tuple(
                _bounded_panels(
                    cache, l, lambda a: gather_pages(a, table, n_blocks),
                )
                for l in range(cfg.n_layers)
            )
    else:
        S = cache.max_len
        Sb = S if prefix_bound is None else max(1, min(prefix_bound, S))
        prefix_panels = tuple(
            _bounded_panels(
                cache, l, lambda a: jax.lax.slice_in_dim(a, 0, Sb, axis=2),
            )
            for l in range(cfg.n_layers)
        )
    start = cache.lengths
    windows = cfg.window_sizes()
    qscale = cfg.query_scale if cfg.query_scale is not None else cfg.head_dim**-0.5
    G = cfg.n_heads // cfg.n_kv_heads
    R = n_steps * D
    # Rings stay in compute precision; the chunk-end write quantizes.
    cache_dtype = (
        cfg.dtype if cache.scales is not None else cache.layers[0][0].dtype
    )
    ring_shape = (B, cfg.n_kv_heads, R, cfg.head_dim)
    rings = tuple(
        (jnp.zeros(ring_shape, cache_dtype), jnp.zeros(ring_shape, cache_dtype))
        for _ in range(cfg.n_layers)
    )
    prefix_last = start - 1
    bidx = jnp.arange(B)

    def step(carry):
        (
            i, tokens, done, budget, offset, sampling, history, rings,
            out_toks, out_valid,
        ) = carry
        active = ~done
        pos = start + offset
        drafts = _ngram_drafts(history, pos, tokens, D - 1)
        if draft_layers > 0:
            # Adaptive drafting: slots whose n-gram acceptance EMA
            # collapsed (host-side hysteresis, engine/batcher.py) draft
            # through the model's own first layers instead. lax.cond
            # skips the shallow forward entirely while every slot is
            # still n-gram-happy.
            pk_info = (
                {"table": table, "n_blocks": n_blocks,
                 "kv_scales": kv_scales, "n_strip": page_strip,
                 "kv_mesh": kv_mesh}
                if (paged and use_pallas) else None
            )
            mode = (
                draft_mode if draft_mode is not None
                else jnp.zeros((B,), bool)
            )
            mdrafts = jax.lax.cond(
                jnp.any(mode),
                lambda: _model_drafts(
                    params, cfg, draft_layers, D - 1, tokens, pos,
                    prefix_panels, rings, start, offset, prefix_last,
                    pk_info, windows, qscale,
                ),
                lambda: drafts,
            )
            drafts = jnp.where(mode[:, None], mdrafts, drafts)
        blk = jnp.concatenate([tokens[:, None], drafts], axis=1)  # [B, D]
        pvec = pos[:, None] + jnp.arange(D)[None, :]
        x = _embed(cfg, params, blk)                              # [B, D, E]
        sin, cos = rope_tables(pvec, cfg.head_dim, cfg.rope_theta)

        new_rings = []
        for l in range(cfg.n_layers):
            lp = jax.tree.map(lambda a: a[l], params["layers"])
            window = int(windows[l])
            layer_k, layer_v, layer_sc = prefix_panels[l]
            rk, rv = rings[l]
            p = lp["attn"]

            h = rms_norm(x, lp["ln1"]["scale"], cfg.rms_eps, cfg.rms_offset)
            q, k, v = _qkv(cfg, p, h, sin, cos)  # [B, D, heads, H]
            blk_k = k.transpose(0, 2, 1, 3).astype(cache_dtype)  # [B, K, D, H]
            blk_v = v.transpose(0, 2, 1, 3).astype(cache_dtype)
            qg = q.transpose(0, 2, 1, 3).reshape(
                B, cfg.n_kv_heads, G, D, cfg.head_dim
            )
            if paged and use_pallas:
                # Pallas paged prefix read with D query rows per slot
                # (q_blocks): the kernel offsets row d's position by d
                # for the sliding-window mask; causality vs the prefix
                # is free (every prefix key precedes the block).
                kernel = _paged_kernel_for(kv_mesh)
                acc_p, m_p, l_p = kernel(
                    qg.reshape(B, cfg.n_kv_heads * G * D, cfg.head_dim),
                    layer_k, layer_v, table, prefix_last,
                    q_positions=pos, n_blocks=n_blocks, q_blocks=D,
                    n_strip=page_strip,
                    scale=qscale, softcap=cfg.attn_softcap, window=window,
                    k_scales=None if kv_scales is None else kv_scales[l][0],
                    v_scales=None if kv_scales is None else kv_scales[l][1],
                )
                pstats = (
                    acc_p.reshape(B, cfg.n_kv_heads, G, D, cfg.head_dim),
                    m_p.reshape(B, cfg.n_kv_heads, G, D),
                    l_p.reshape(B, cfg.n_kv_heads, G, D),
                )
                attn = _spec_block_attn(
                    qg, None, None, rk, rv, blk_k, blk_v,
                    prefix_last, start, offset, pvec,
                    qscale, cfg.attn_softcap, window,
                    prefix_stats=pstats,
                )
            else:
                attn = _spec_block_attn(
                    qg, layer_k, layer_v, rk, rv, blk_k, blk_v,
                    prefix_last, start, offset, pvec,
                    qscale, cfg.attn_softcap, window,
                    kv_scales=layer_sc,
                )
            x = _layer_tail(
                cfg, lp, x,
                attn.astype(x.dtype).reshape(B, D, cfg.n_heads, cfg.head_dim),
            )
            new_rings.append((blk_k, blk_v))

        h = rms_norm(x, params["final_norm"]["scale"], cfg.rms_eps, cfg.rms_offset)

        # ---- verify ---------------------------------------------------
        if fused_epilogue:
            # All-greedy non-JSON dispatch: row 0's sampler AND every
            # verify row reduce to argmax (the grammar mask is the
            # identity with no JSON slot), so all D rows fuse into one
            # vocab-tiled projection+argmax and the [B, D, V] fp32
            # logits never land in HBM. One key split preserves the
            # plain sampler's one-advance-per-block PRNG trajectory.
            emitted = fused_greedy_epilogue(cfg, params, h)    # [B, D]
            sampling = _advance_keys(sampling)
        else:
            logits = _unembed(cfg, params, h)             # [B, D, V] fp32
            # Row 0 runs the full sampler (mask + greedy/sample + key +
            # json advance) — identical per-token semantics to the
            # plain chunk.
            pre_row0 = sampling
            tok0, sampling = sample_core(
                logits[:, 0], sampling, json_remaining=budget,
                json_token_tables=json_tables,
                json_schema_tables=schema_tables,
            )
            # Rows 1..D-1: masked greedy with coords advanced along the
            # DRAFT path (rows only matter while drafts keep being
            # accepted, and then draft == emitted, so the draft-path
            # coords are the right ones). One fused mask+argmax across
            # all verify rows — the per-row dispatch loop was the
            # sampler small-op floor (sampling.fused_verify_rows;
            # byte-identical per row).
            verify = fused_verify_rows(
                logits[:, 1:], blk[:, 1:], pre_row0, budget,
                token_tables=json_tables, schema_tables=schema_tables,
            )
            emitted = jnp.concatenate([tok0[:, None], verify], axis=1)

        # Leading-match acceptance (greedy slots only).
        match = emitted[:, : D - 1] == blk[:, 1:]         # [B, D-1]
        lead = jnp.cumprod(match.astype(jnp.int32), axis=1)
        acc = jnp.sum(lead, axis=1)                       # [B] 0..D-1
        greedy_slot = sampling.temperature <= 0.0
        cand = jnp.where(greedy_slot, acc + 1, 1)         # tokens offered

        # Truncate at EOS / budget / context-full, terminal included.
        jj = jnp.arange(D)[None, :]
        eos_hit = (sampling.eos_id[:, None] >= 0) & (
            emitted == sampling.eos_id[:, None]
        )
        ctx_full = (pvec + 1) >= (S - 1)
        term = eos_hit | ctx_full | (budget[:, None] - (jj + 1) <= 0)
        no_term_before = jnp.cumprod(
            jnp.concatenate(
                [jnp.ones((B, 1), jnp.int32), 1 - term[:, :-1].astype(jnp.int32)],
                axis=1,
            ),
            axis=1,
        ).astype(bool)
        emit_mask = (jj < cand[:, None]) & no_term_before & active[:, None]
        n_emit = jnp.sum(emit_mask.astype(jnp.int32), axis=1)  # [B] 0..D

        terminated = jnp.any(term & emit_mask, axis=1)
        new_done = done | (active & terminated)
        new_budget = budget - n_emit
        new_offset = offset + n_emit
        # Next current token: the last emitted (bonus or terminal; unused
        # when done).
        last_idx = jnp.maximum(n_emit - 1, 0)
        new_tokens = jnp.where(
            active, emitted[bidx, last_idx], tokens
        )

        # Json coords: row 0 already advanced inside sample_core; advance
        # by the remaining emitted tokens. Skipped under the fused
        # epilogue — with no JSON-enabled slot every advance is the
        # identity (``_advance_json`` gates on ``json_enabled``), so the
        # sampling-state trajectory is unchanged by construction.
        if not fused_epilogue:
            for j in range(1, D):
                stepped = _advance_json(
                    sampling, emitted[:, j], json_tables, schema_tables
                )
                take = emit_mask[:, j]
                sampling = sampling._replace(
                    json_state=jnp.where(take, stepped.json_state, sampling.json_state),
                    json_stack=jnp.where(take, stepped.json_stack, sampling.json_stack),
                    json_depth=jnp.where(take, stepped.json_depth, sampling.json_depth),
                )

        # History: emitted token j lives at position pos + 1 + j.
        hpos = jnp.where(emit_mask, pos[:, None] + 1 + jj, S)
        history = history.at[bidx[:, None], hpos].set(emitted, mode="drop")

        # Ring: block token k is in-sequence iff k < n_emit (cur plus the
        # accepted, non-terminal drafts — terminal/bonus tokens get their
        # K/V next block, exactly like the plain chunk).
        rpos = jnp.where(jj < n_emit[:, None], offset[:, None] + jj, R)
        out_rings = []
        for (rk, rv), (bk, bv) in zip(rings, new_rings):
            rk = rk.at[bidx[:, None], :, rpos].set(
                bk.transpose(0, 2, 1, 3), mode="drop"
            )
            rv = rv.at[bidx[:, None], :, rpos].set(
                bv.transpose(0, 2, 1, 3), mode="drop"
            )
            out_rings.append((rk, rv))

        out_toks = jax.lax.dynamic_update_slice(
            out_toks, emitted[None], (i, 0, 0)
        )
        out_valid = jax.lax.dynamic_update_slice(
            out_valid, emit_mask[None], (i, 0, 0)
        )
        return (
            i + 1, new_tokens, new_done, new_budget, new_offset, sampling,
            history, tuple(out_rings), out_toks, out_valid,
        )

    offset0 = jnp.zeros((B,), jnp.int32)
    carry0 = (
        jnp.int32(0), dstate.tokens, dstate.done, dstate.budget, offset0,
        sampling, history, rings,
        jnp.zeros((n_steps, B, D), jnp.int32),
        jnp.zeros((n_steps, B, D), bool),
    )
    # while_loop, not scan: a verify-block costs one full weight pass
    # (the whole point of speculation is that decode is weight-stream
    # bound), so when every slot is done/budget-exhausted mid-chunk the
    # remaining blocks are pure waste — measured on v5e as the dominant
    # overhead above the bandwidth floor at wave tails. Early exit makes
    # a generous chunk_size free: the dispatch pays for the blocks the
    # slowest slot actually needed.
    (
        _, tokens, done, budget, offset, sampling, history, rings,
        out_toks, out_valid,
    ) = jax.lax.while_loop(
        lambda c: (c[0] < n_steps) & ~jnp.all(c[2]),
        step,
        carry0,
    )

    # [n, B, D] -> [n*D, B] block-major so the host fold sees the plain
    # chunk's [rows, B] contract.
    out_toks = out_toks.transpose(0, 2, 1).reshape(n_steps * D, B)
    out_valid = out_valid.transpose(0, 2, 1).reshape(n_steps * D, B)

    if paged:
        cache = write_chunk_rows_paged(
            cache, table, [r[0] for r in rings], [r[1] for r in rings],
            start, offset,
        )
    else:
        cache = write_chunk_rows(
            cache, [r[0] for r in rings], [r[1] for r in rings], start, offset
        )
    dstate = DecodeState(tokens=tokens, done=done, budget=budget)
    return out_toks, out_valid, cache, dstate, sampling, history


# --------------------------------------------------------------------- #
# Prefix-cached admission (engine/prefix_cache.py)
# --------------------------------------------------------------------- #


def _tail_prefix_attn(
    qg: jax.Array,          # [A, K, G, T, H] tail queries
    pk: jax.Array,          # [K, P, H] shared cached-prefix keys
    pv: jax.Array,
    blk_k: jax.Array,       # [A, K, T, H] tail's own keys
    blk_v: jax.Array,
    prefix_len: jax.Array,  # scalar int32 — true prefix length (<= P)
    valid: jax.Array,       # [A] true tail lengths
    scale: float,
    softcap: float,
    window: int,
) -> jax.Array:
    """Tail-prefill attention: every tail query attends the whole cached
    prefix plus the tail causally. The prefix panels carry no batch dim —
    one cached prompt serves the whole admission group."""
    A, K, G, T, H = qg.shape

    def softcapped(s):
        return jnp.tanh(s / softcap) * softcap if softcap > 0.0 else s

    qpos = prefix_len + jnp.arange(T)                       # tail positions

    def prefix_stats(pkw, pvw, col):
        """Flash partials of the tail queries against one span of prefix
        keys (``col`` are the span's absolute columns)."""
        s = softcapped(jnp.einsum(
            "akgth,kph->akgtp", qg, pkw, preferred_element_type=jnp.float32,
        ) * scale)
        mask = col[None, None, None, None, :] < prefix_len
        if window > 0:
            mask = mask & (
                (qpos[None, None, None, :, None] - col[None, None, None, None, :])
                < window
            )
        s = jnp.where(mask, s, NEG_INF)
        m_w = jnp.max(s, axis=-1)
        p = jnp.where(
            m_w[..., None] > NEG_INF / 2, jnp.exp(s - m_w[..., None]), 0.0
        )
        l_w = jnp.sum(p, axis=-1)
        acc_w = jnp.einsum(
            "akgtp,kph->akgth", p.astype(pvw.dtype), pvw,
            preferred_element_type=jnp.float32,
        )
        return acc_w, m_w, l_w

    P = pk.shape[1]
    # The scores tensor is A·K·G·T·P·4 bytes; one shot at a long prefix
    # (8K chain × 1K tail × 8 rows = 8 GB) OOMs — window the prefix with
    # online-softmax merging (flash over the chain, coarse-grained)
    # whenever the full scores would be big.
    W = 2048
    one_shot_bytes = 4 * A * K * G * T * P
    if P > W and P % W == 0 and one_shot_bytes > (1 << 30):
        nw = P // W
        pk_w = pk.reshape(K, nw, W, H).transpose(1, 0, 2, 3)
        pv_w = pv.reshape(K, nw, W, H).transpose(1, 0, 2, 3)
        cols = (
            jnp.arange(nw)[:, None] * W + jnp.arange(W)[None, :]
        ).astype(jnp.int32)

        def wstep(carry, xs):
            acc, m, l = carry
            acc_w, m_w, l_w = prefix_stats(*xs)
            return _merge_stats(acc, m, l, acc_w, m_w, l_w), None

        init = (
            jnp.zeros((A, K, G, T, H), jnp.float32),
            jnp.full((A, K, G, T), NEG_INF, jnp.float32),
            jnp.zeros((A, K, G, T), jnp.float32),
        )
        (acc_p, m_p, l_p), _ = jax.lax.scan(
            wstep, init, (pk_w, pv_w, cols)
        )
    else:
        acc_p, m_p, l_p = prefix_stats(pk, pv, jnp.arange(P))

    s = softcapped(jnp.einsum(
        "akgth,akeh->akgte", qg, blk_k, preferred_element_type=jnp.float32,
    ) * scale)
    e = jnp.arange(T)[None, None, None, None, :]
    t = jnp.arange(T)[None, None, None, :, None]
    mask = (e <= t) & (e < valid[:, None, None, None, None])
    if window > 0:
        mask = mask & ((t - e) < window)
    s = jnp.where(mask, s, NEG_INF)
    m_b = jnp.max(s, axis=-1)
    p = jnp.exp(s - m_b[..., None])  # e == t always valid → never empty
    l_b = jnp.sum(p, axis=-1)
    acc_b = jnp.einsum(
        "akgte,akeh->akgth", p.astype(blk_v.dtype), blk_v,
        preferred_element_type=jnp.float32,
    )

    acc, _, l = _merge_stats(acc_p, m_p, l_p, acc_b, m_b, l_b)
    attn = acc / jnp.maximum(l, 1e-30)[..., None]
    return attn.transpose(0, 3, 1, 2, 4).reshape(A, T, K * G * H)


def _tail_prefill_core(
    params,
    cfg: ModelConfig,
    prefix_ks: jax.Array,   # [L, K, P, H] cached prompt-prefix keys
    prefix_vs: jax.Array,
    prefix_len: jax.Array,  # scalar int32 — true prefix length (<= P)
    tail_tokens: jax.Array,  # [A, Tt] right-padded prompt tails
    tail_lens: jax.Array,    # [A] true tail lengths (0 = padding row)
    cache_dtype,
):
    """Shared tail-prefill forward for both prefix-cached admission
    paths (dense panel copy and paged page sharing): tail tokens attend
    the cached prefix plus themselves causally. Returns
    ``(logits [A, Tt, V], ks [L, A, K, Tt, H], vs)``."""
    A, Tt = tail_tokens.shape
    positions = prefix_len + jnp.broadcast_to(
        jnp.arange(Tt, dtype=jnp.int32)[None], (A, Tt)
    )
    x = _embed(cfg, params, tail_tokens)
    sin, cos = rope_tables(positions, cfg.head_dim, cfg.rope_theta)
    windows = jnp.asarray(cfg.window_sizes())
    qscale = cfg.query_scale if cfg.query_scale is not None else cfg.head_dim**-0.5
    G = cfg.n_heads // cfg.n_kv_heads

    def layer_fn(carry, scanned):
        x = carry
        lp, window, pk, pv = scanned
        h = rms_norm(x, lp["ln1"]["scale"], cfg.rms_eps, cfg.rms_offset)
        q, k, v = _qkv(cfg, lp["attn"], h, sin, cos)
        qg = q.transpose(0, 2, 1, 3).reshape(
            A, cfg.n_kv_heads, G, Tt, cfg.head_dim
        )
        blk_k = k.transpose(0, 2, 1, 3).astype(cache_dtype)
        blk_v = v.transpose(0, 2, 1, 3).astype(cache_dtype)
        # Per-layer window under lax.cond: ``window`` is a traced scan
        # element, and only one attention variant runs per layer (the
        # jnp.where form computed BOTH every layer — advisor r3).
        if cfg.sliding_window > 0:
            attn = jax.lax.cond(
                window > 0,
                lambda: _tail_prefix_attn(
                    qg, pk, pv, blk_k, blk_v, prefix_len, tail_lens,
                    qscale, cfg.attn_softcap, int(cfg.sliding_window),
                ),
                lambda: _tail_prefix_attn(
                    qg, pk, pv, blk_k, blk_v, prefix_len, tail_lens,
                    qscale, cfg.attn_softcap, 0,
                ),
            )
        else:
            attn = _tail_prefix_attn(
                qg, pk, pv, blk_k, blk_v, prefix_len, tail_lens,
                qscale, cfg.attn_softcap, 0,
            )
        x = _layer_tail(
            cfg, lp, x,
            attn.astype(x.dtype).reshape(A, Tt, cfg.n_heads, cfg.head_dim),
        )
        return x, (blk_k, blk_v)

    x, (ks, vs) = jax.lax.scan(
        layer_fn, x, (params["layers"], windows, prefix_ks, prefix_vs)
    )
    x = rms_norm(x, params["final_norm"]["scale"], cfg.rms_eps, cfg.rms_offset)
    logits = _unembed(cfg, params, x)                    # [A, Tt, V] fp32
    return logits, ks, vs


def _tail_prefill_lazy(
    params,
    cfg: ModelConfig,
    gather_layer,            # l -> (pk [K, Pb, H], pv) in compute dtype
    prefix_len: jax.Array,
    tail_tokens: jax.Array,  # [A, Tt]
    tail_lens: jax.Array,    # [A]
    cache_dtype,
):
    """``_tail_prefill_core`` with PER-LAYER prefix gathering (python
    loop, no scan): stacking all L layers' dequantized chain panels
    up front costs ``2·L·K·Pb·H·2`` bytes — 17+ GB for an 8B model at an
    8K prefix, a measured OOM next to the weights. Here each layer
    gathers its own panels transiently (~0.5 GB at 8K) and XLA reuses
    the buffer across layers. Used by the paged admission paths whenever
    the stacked gather would exceed the gather budget."""
    A, Tt = tail_tokens.shape
    positions = prefix_len + jnp.broadcast_to(
        jnp.arange(Tt, dtype=jnp.int32)[None], (A, Tt)
    )
    x = _embed(cfg, params, tail_tokens)
    sin, cos = rope_tables(positions, cfg.head_dim, cfg.rope_theta)
    windows = cfg.window_sizes()
    qscale = cfg.query_scale if cfg.query_scale is not None else cfg.head_dim**-0.5
    G = cfg.n_heads // cfg.n_kv_heads

    ks_l, vs_l = [], []
    for l in range(cfg.n_layers):
        lp = jax.tree.map(lambda a: a[l], params["layers"])
        window = int(windows[l])
        pk, pv = gather_layer(l)
        h = rms_norm(x, lp["ln1"]["scale"], cfg.rms_eps, cfg.rms_offset)
        q, k, v = _qkv(cfg, lp["attn"], h, sin, cos)
        qg = q.transpose(0, 2, 1, 3).reshape(
            A, cfg.n_kv_heads, G, Tt, cfg.head_dim
        )
        blk_k = k.transpose(0, 2, 1, 3).astype(cache_dtype)
        blk_v = v.transpose(0, 2, 1, 3).astype(cache_dtype)
        attn = _tail_prefix_attn(
            qg, pk, pv, blk_k, blk_v, prefix_len, tail_lens,
            qscale, cfg.attn_softcap, window,
        )
        x = _layer_tail(
            cfg, lp, x,
            attn.astype(x.dtype).reshape(A, Tt, cfg.n_heads, cfg.head_dim),
        )
        ks_l.append(blk_k)
        vs_l.append(blk_v)
    x = rms_norm(x, params["final_norm"]["scale"], cfg.rms_eps, cfg.rms_offset)
    logits = _unembed(cfg, params, x)
    return logits, jnp.stack(ks_l), jnp.stack(vs_l)


@partial(
    jax.jit,
    static_argnames=("cfg",),
    donate_argnames=("cache", "dstate", "sampling", "history"),
)
def admit_group_prefix(
    params,
    cfg: ModelConfig,
    cache: KVCache,
    dstate: "DecodeState",
    sampling: SamplingState,
    prefix_ks: jax.Array,   # [L, K, P, H] cached prompt-prefix keys
    prefix_vs: jax.Array,
    tail_tokens: jax.Array,  # [A, Tt] right-padded prompt tails
    full_tokens: jax.Array,  # [A, Tf] full prompts (history install)
    meta_i32: jax.Array,     # [ADMIT_I32_ROWS, A] — AI_LEN = tail lens,
                             # AI_PLEN = true prefix length (broadcast)
    meta_f32: jax.Array,     # [ADMIT_F32_ROWS, A]
    json_tables: Optional[Tuple[jax.Array, jax.Array]] = None,
    schema_tables: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None,
    history: Optional[jax.Array] = None,
):
    """Admission with a cached prefix: copy the prefix K/V into each
    slot, prefill ONLY the tail with prefix-aware attention, sample the
    first token — one fused dispatch, like ``admit_group``. An exact
    repeat admits with a one-token tail: the 2048-position 8B prefill
    (~33 TFLOP, the dominant share of the agent-step wave measured on
    v5e) collapses to a single position."""
    A, Tt = tail_tokens.shape
    (
        slots, temps, topks, topps, seeds, eos, jsonm, budgets, tail_lens,
        schema_ids,
    ) = _unpack_admit_meta(meta_i32, meta_f32, schema_tables)
    prefix_len = meta_i32[AI_PLEN, 0]
    quantized = cache.scales is not None
    cache_dtype = cfg.dtype if quantized else cache.layers[0][0].dtype
    logits, ks, vs = _tail_prefill_core(
        params, cfg, prefix_ks, prefix_vs, prefix_len,
        tail_tokens, tail_lens, cache_dtype,
    )

    # Cache install: prefix panels (shared) + tail (per slot). Padding
    # rows route to row 0's slot and are overwritten by its later write
    # (write_prompts' reversed-dus trick). Quantized caches re-quantize
    # the store entries on the way in — lossless ONLY because the store
    # exports in float32 (a bf16 round would shift the recomputed scale
    # and break hit-path determinism), so quantize from the raw entry,
    # never from a cache_dtype cast.
    live = tail_lens > 0
    safe_slots = jnp.where(live, slots, slots[0])
    plen_start = jnp.clip(prefix_len, 0, cache.max_len - 1)
    new_layers = []
    new_scales = [] if quantized else None
    for l, (k_panel, v_panel) in enumerate(cache.layers):
        pk = prefix_ks[l][None]                         # [1, K, P, H]
        pv = prefix_vs[l][None]
        tk, tv = ks[l], vs[l]                           # [A, K, Tt, H]
        if quantized:
            pk, pk_s = quantize_kv(pk)
            pv, pv_s = quantize_kv(pv)
            tk, tk_s = quantize_kv(tk)
            tv, tv_s = quantize_kv(tv)
            ks_panel, vs_panel = cache.scales[l]
            for a in reversed(range(A)):
                sstart = (safe_slots[a], 0, 0)
                ks_panel = jax.lax.dynamic_update_slice(ks_panel, pk_s, sstart)
                vs_panel = jax.lax.dynamic_update_slice(vs_panel, pv_s, sstart)
                tstart = (safe_slots[a], 0, plen_start)
                ks_panel = jax.lax.dynamic_update_slice(
                    ks_panel, tk_s[a][None], tstart
                )
                vs_panel = jax.lax.dynamic_update_slice(
                    vs_panel, tv_s[a][None], tstart
                )
            new_scales.append((ks_panel, vs_panel))
        else:
            pk = pk.astype(cache_dtype)
            pv = pv.astype(cache_dtype)
            tk = tk.astype(cache_dtype)
            tv = tv.astype(cache_dtype)
        for a in reversed(range(A)):
            start = (safe_slots[a], 0, 0, 0)
            k_panel = jax.lax.dynamic_update_slice(k_panel, pk, start)
            v_panel = jax.lax.dynamic_update_slice(v_panel, pv, start)
            # Scan outputs are already K-major: ks[l][a] is [K, Tt, H].
            tstart = (safe_slots[a], 0, plen_start, 0)
            k_panel = jax.lax.dynamic_update_slice(
                k_panel, tk[a][None], tstart
            )
            v_panel = jax.lax.dynamic_update_slice(
                v_panel, tv[a][None], tstart
            )
        new_layers.append((k_panel, v_panel))
    new_lengths = cache.lengths
    full_lens = jnp.where(live, prefix_len + tail_lens, 0)
    for a in reversed(range(A)):
        new_lengths = jax.lax.dynamic_update_slice(
            new_lengths, full_lens[a][None], (safe_slots[a],)
        )
    cache = cache._replace(
        layers=tuple(new_layers), lengths=new_lengths,
        scales=tuple(new_scales) if new_scales is not None else None,
    )

    sampling = admit_sampling(
        sampling, slots, temps, topks, topps, seeds, eos, jsonm,
        schema_ids=schema_ids,
    )
    first, sampling = sample_prefill_tokens(
        logits, tail_lens, slots, sampling, remaining=budgets + 1,
        json_tables=json_tables, schema_tables=schema_tables,
    )
    dstate = admit_decode(dstate, slots, first, budgets, live)
    if history is not None:
        history = install_history(
            history, slots, full_tokens, full_lens, first
        )
    return cache, dstate, sampling, first, history


@partial(
    jax.jit,
    static_argnames=("cfg", "n_prefix_bucket"),
    donate_argnames=("cache", "dstate", "sampling", "history"),
)
def admit_group_prefix_paged(
    params,
    cfg: ModelConfig,
    cache: PagedKVCache,
    dstate: "DecodeState",
    sampling: SamplingState,
    prefix_pages: jax.Array,  # [n_prefix_bucket] int32 — shared chain pages
                              # in order, sentinel-padded past the true count
    tail_tokens: jax.Array,   # [A, Tt] right-padded prompt tails
    full_tokens: jax.Array,   # [A, Tf] full prompts (history install)
    page_rows: jax.Array,     # [A, max_pages] full block tables (shared
                              # prefix pages at the head, private after)
    meta_i32: jax.Array,      # [ADMIT_I32_ROWS, A] — AI_LEN = tail lens,
                              # AI_PLEN = true prefix length (page-aligned:
                              # chain pages are always full)
    meta_f32: jax.Array,      # [ADMIT_F32_ROWS, A]
    n_prefix_bucket: int = 1,
    json_tables: Optional[Tuple[jax.Array, jax.Array]] = None,
    schema_tables: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None,
    history: Optional[jax.Array] = None,
):
    """Block-granular prefix-cached admission on the paged pool
    (``engine/page_prefix.py``). Unlike the dense variant, the prefix is
    **not copied anywhere**: the shared pages are already mapped into
    each slot's block table by the host allocator — this dispatch only
    gathers them read-only for the tail's prefix attention, prefills the
    tail, and scatters the tail K/V into the slots' private pages (the
    shared pages are immutable: decode writes start at ``prompt_len``,
    past every fully-covered block)."""
    (
        slots, temps, topks, topps, seeds, eos, jsonm, budgets, tail_lens,
        schema_ids,
    ) = _unpack_admit_meta(meta_i32, meta_f32, schema_tables)
    prefix_len = meta_i32[AI_PLEN, 0]
    P = cache.page_size
    K = cache.n_kv_heads
    H = cache.head_dim
    Pb = n_prefix_bucket * P
    # The shared chain is read as prefix panels (sentinel-padded pages
    # gather scratch garbage — masked by ``col < prefix_len`` in the
    # tail attention). int8 pools dequantize on the way out; the pages
    # themselves stay quantized and untouched. Large chains gather per
    # layer instead of stacking (see _chain_tail_prefill).
    cache_dtype = (
        cfg.dtype if cache.scales is not None else cache.layers[0][0].dtype
    )
    logits, ks, vs = _chain_tail_prefill(
        params, cfg, cache, prefix_pages, prefix_len, tail_tokens,
        tail_lens, cache_dtype,
    )

    # Tail install: position t of the tail lives at absolute position
    # prefix_len + t — write through the slot's own table with that
    # offset (prefix_len is page-aligned, so only private blocks past
    # the shared chain are ever touched).
    ks_w = ks.transpose(0, 1, 3, 2, 4)  # [L, A, Tt, K, H]
    vs_w = vs.transpose(0, 1, 3, 2, 4)
    cache = write_prompts_paged(
        cache, page_rows, ks_w, vs_w, tail_lens, pos_offset=prefix_len
    )
    live = tail_lens > 0
    cache = install_lengths(
        cache, slots, jnp.where(live, prefix_len + tail_lens, 0)
    )

    sampling = admit_sampling(
        sampling, slots, temps, topks, topps, seeds, eos, jsonm,
        schema_ids=schema_ids,
    )
    first, sampling = sample_prefill_tokens(
        logits, tail_lens, slots, sampling, remaining=budgets + 1,
        json_tables=json_tables, schema_tables=schema_tables,
    )
    dstate = admit_decode(dstate, slots, first, budgets, live)
    if history is not None:
        history = install_history(
            history, slots, full_tokens,
            jnp.where(live, prefix_len + tail_lens, 0), first,
        )
    return cache, dstate, sampling, first, history


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def extend_prompt_paged(
    params,
    cfg: ModelConfig,
    cache: PagedKVCache,
    prefix_pages: jax.Array,  # [n_prefix_bucket] int32 — pages already
                              # written for this slot, sentinel-padded
    prefix_len: jax.Array,    # scalar int32 — page-aligned tokens written
    seg_tokens: jax.Array,    # [1, Ts] right-padded prompt segment
    seg_lens: jax.Array,      # [1] true segment length
    page_rows: jax.Array,     # [1, max_pages] the slot's block table
):
    """One chunked-prefill segment of a long prompt (VERDICT r5 #6):
    prefill ``seg_tokens`` attending to the KV already written for this
    slot, scatter its K/V into the slot's private pages — and nothing
    else. No sampling, no decode install, no length install: the slot
    stays decode-inactive until the FINAL segment admits through
    ``admit_group_prefix_paged``. The batcher dispatches one segment per
    device-loop cycle, so live slots' decode chunks interleave instead
    of stalling behind a monolithic multi-thousand-token prefill."""
    cache_dtype = (
        cfg.dtype if cache.scales is not None else cache.layers[0][0].dtype
    )
    _logits, ks, vs = _chain_tail_prefill(
        params, cfg, cache, prefix_pages, prefix_len, seg_tokens, seg_lens,
        cache_dtype,
    )
    ks_w = ks.transpose(0, 1, 3, 2, 4)  # [L, 1, Ts, K, H]
    vs_w = vs.transpose(0, 1, 3, 2, 4)
    return write_prompts_paged(
        cache, page_rows, ks_w, vs_w, seg_lens, pos_offset=prefix_len
    )


def _chain_tail_prefill(
    params, cfg, cache, prefix_pages, prefix_len, tail_tokens, tail_lens,
    cache_dtype,
):
    """Tail prefill against a page chain, choosing the gather strategy by
    HBM cost: small chains stack all layers' dequantized panels up front
    (one scanned forward — the fast, proven path); chains whose stacked
    panels would exceed the gather budget (PILOTTAI_GATHER_BUDGET, the
    same knob the decode chunk uses) gather per layer instead
    (``_tail_prefill_lazy``) — an 8K chain on an 8B model is 17+ GB
    stacked, a measured OOM."""
    import os as _os

    P = cache.page_size
    K = cache.n_kv_heads
    Pb = prefix_pages.shape[0] * P

    def _chain_gather(a):
        return a[:, prefix_pages].reshape((K, Pb) + a.shape[3:])

    def gather_layer(l):
        k_, v_, sc = _bounded_panels(cache, l, _chain_gather)
        return _dequant_pair(k_, v_, sc, cfg.dtype)

    budget = int(_os.environ.get("PILOTTAI_GATHER_BUDGET", 5 * 1024**3))
    stacked_bytes = (
        2 * cfg.n_layers * K * Pb * cache.head_dim
        * jnp.dtype(cfg.dtype).itemsize
    )
    if stacked_bytes > budget:
        return _tail_prefill_lazy(
            params, cfg, gather_layer, prefix_len, tail_tokens, tail_lens,
            cache_dtype,
        )
    panels = [gather_layer(l) for l in range(cfg.n_layers)]
    pks = jnp.stack([p[0] for p in panels])
    pvs = jnp.stack([p[1] for p in panels])
    return _tail_prefill_core(
        params, cfg, pks, pvs, prefix_len, tail_tokens, tail_lens,
        cache_dtype,
    )


@partial(jax.jit, static_argnames=("p_bucket", "dtype"))
def export_prefix(cache: KVCache, slot, p_bucket: int, dtype=None):
    """Read one slot's first ``p_bucket`` cache rows out as stacked
    [L, K, p_bucket, H] arrays (the prefix-store entry payload). Runs
    right after the admission dispatch, before any decode chunk touches
    the slot, so the rows hold exactly the prompt's K/V. int8 caches
    export DEQUANTIZED panels: admit_group_prefix re-quantizes on
    install, which round-trips losslessly (same scales recomputed)."""
    def grab(panel):
        K, _, H = panel.shape[1:]
        return jax.lax.dynamic_slice(
            panel, (slot, 0, 0, 0), (1, K, p_bucket, H)
        )[0]

    def grab_scale(panel):
        K = panel.shape[1]
        return jax.lax.dynamic_slice(
            panel, (slot, 0, 0), (1, K, p_bucket)
        )[0]

    dt = dtype if dtype is not None else jnp.float32
    ks_l, vs_l = [], []
    for l, (k, v) in enumerate(cache.layers):
        gk, gv = grab(k), grab(v)
        if cache.scales is not None:
            gk = dequantize_kv(gk, grab_scale(cache.scales[l][0]), dt)
            gv = dequantize_kv(gv, grab_scale(cache.scales[l][1]), dt)
        ks_l.append(gk)
        vs_l.append(gv)
    return jnp.stack(ks_l), jnp.stack(vs_l)


def install_history(
    history: jax.Array,   # [B, S]
    slots: jax.Array,     # [A] (OOB rows dropped)
    tokens: jax.Array,    # [A, T] right-padded prompts
    lens: jax.Array,      # [A] true lengths
    first: jax.Array,     # [A] prefill-sampled first tokens
) -> jax.Array:
    """Admission-side history install: prompt ids at positions [0, len)
    and the first generated token at position len. Plain function — runs
    inside admit_group's single fused dispatch."""
    B, S = history.shape
    A, T = tokens.shape
    live = lens > 0
    rows = jnp.where(live, slots, B)
    col = jnp.arange(T)[None, :]
    # Wipe the row, then lay down the prompt and the first token.
    history = history.at[rows].set(0, mode="drop")
    wcol = jnp.where(col < lens[:, None], col, S)
    history = history.at[rows[:, None], wcol].set(tokens, mode="drop")
    history = history.at[
        rows, jnp.minimum(lens, S - 1)
    ].set(first, mode="drop")
    return history


@partial(
    jax.jit,
    static_argnames=("cfg", "use_flash", "flash_mesh"),
    donate_argnames=("cache", "dstate", "sampling", "history"),
)
def admit_group(
    params,
    cfg: ModelConfig,
    cache: KVCache,
    dstate: "DecodeState",
    sampling: SamplingState,
    tokens: jax.Array,     # [A, T] right-padded prompt ids
    meta_i32: jax.Array,   # [ADMIT_I32_ROWS, A] packed int metadata
    meta_f32: jax.Array,   # [ADMIT_F32_ROWS, A] packed float metadata
    use_flash: bool = True,
    flash_mesh: Any = None,
    page_rows: Optional[jax.Array] = None,  # [A, max_pages] — paged cache
    json_tables: Optional[Tuple[jax.Array, jax.Array]] = None,
    schema_tables: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None,
    history: Optional[jax.Array] = None,    # [B, S] — speculative decode
):
    """The whole admission path — prefill forward, batched cache write,
    sampler install, on-device first-token sample, decode-state install —
    as ONE device dispatch. Through a remote-TPU tunnel each dispatch
    costs tens of ms of host latency; five per admission group was a
    measurable slice of the p50 budget (VERDICT.md next-step 2). The
    per-row scalars arrive packed in two staging buffers (one H2D
    transfer each — ``pack_admit_meta``); positions are derived on
    device, so a full-prefill admission moves exactly three host arrays.

    Returns (cache, dstate, sampling, first_tokens [A])."""
    A, T = tokens.shape
    (
        slots, temps, topks, topps, seeds, eos, jsonm, budgets, lens,
        schema_ids,
    ) = _unpack_admit_meta(meta_i32, meta_f32, schema_tables)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (A, T))
    logits, ks, vs = forward_prefill(
        params, cfg, tokens, positions, lens,
        use_flash=use_flash, flash_mesh=flash_mesh,
    )
    if isinstance(cache, PagedKVCache):
        assert page_rows is not None, "paged admission needs page rows"
        cache = write_prompts_paged(cache, page_rows, ks, vs, lens)
        cache = install_lengths(cache, slots, lens)
    else:
        cache = write_prompts(cache, slots, ks, vs, lens)
    sampling = admit_sampling(
        sampling, slots, temps, topks, topps, seeds, eos, jsonm,
        schema_ids=schema_ids,
    )
    first, sampling = sample_prefill_tokens(
        logits, lens, slots, sampling, remaining=budgets + 1,
        json_tables=json_tables, schema_tables=schema_tables,
    )
    dstate = admit_decode(dstate, slots, first, budgets, lens > 0)
    if history is not None:
        history = install_history(history, slots, tokens, lens, first)
    return cache, dstate, sampling, first, history


@partial(jax.jit, donate_argnames=("sampling",))
def sample_prefill_tokens(
    logits: jax.Array,    # [A, T, V] fp32 — prefill logits
    valid: jax.Array,     # [A] prompt lengths (last logit at valid-1)
    slots: jax.Array,     # [A] slot each prompt was admitted into
    sampling: SamplingState,
    remaining: Optional[jax.Array] = None,  # [A] total generation budget
    json_tables: Optional[Tuple[jax.Array, jax.Array]] = None,
    schema_tables: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None,
) -> Tuple[jax.Array, SamplingState]:
    """Sample each admitted prompt's first generated token on device,
    using (and advancing) the slot's sampling params — host-side sampling
    duplication was VERDICT.md Weak #9."""
    A = logits.shape[0]
    last = jnp.take_along_axis(
        logits, jnp.maximum(valid - 1, 0)[:, None, None], axis=1
    )[:, 0]                                              # [A, V]
    sub = jax.tree.map(lambda a: a[slots], sampling)
    tokens, sub = sample_core(
        last, sub, json_remaining=remaining, json_token_tables=json_tables,
        json_schema_tables=schema_tables,
    )
    del A
    # Write back everything the sampler advanced: the PRNG keys and the
    # JSON automaton coords (the first token is the automaton's first
    # transition).
    return tokens, sampling._replace(
        key=sampling.key.at[slots].set(sub.key, mode="drop"),
        json_state=sampling.json_state.at[slots].set(sub.json_state, mode="drop"),
        json_stack=sampling.json_stack.at[slots].set(sub.json_stack, mode="drop"),
        json_depth=sampling.json_depth.at[slots].set(sub.json_depth, mode="drop"),
    )
