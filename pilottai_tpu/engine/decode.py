"""Fused multi-step decode: N tokens per device dispatch.

Why this exists: through a remote-TPU tunnel (and even locally, at small
per-step cost) every host<->device round trip costs ~100 ms; a
one-dispatch-per-token decode loop is latency-bound long before the chip
is. ``decode_chunk`` jits a ``lax.scan`` over N decode steps — sampling,
EOS/budget tracking, and KV writes all on device — so the host touches
the device once per N tokens, and the batcher pipelines chunks so even
that touch overlaps compute (``engine/batcher.py``).

The KV-cache trick: inside the chunk the big per-layer cache panels are
**read-only** (prefix attention via the Pallas decode kernel — a custom
call that wrote carry state would force XLA to copy the panels every
layer, every step). Each step's fresh K/V goes to a tiny per-layer ring
buffer ([B, K, N, H]); in-chunk attention runs dense over the ring and
merges with the prefix pass by the standard online-softmax combine; one
batched scatter per layer lands the ring in the big cache at chunk end.

No reference counterpart: the reference's only decode loop is a remote
HTTP call (``pilott/engine/llm.py:59``). This file is the engine half of
the ≤500 ms p50 agent-step target (BASELINE.md).
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from pilottai_tpu.engine.sampling import SamplingState, admit_sampling, sample_core
from pilottai_tpu.models.common import ModelConfig, rms_norm, rope_tables
from pilottai_tpu.models.transformer import (
    _attn_out,
    _embed,
    _mlp,
    _qkv,
    _unembed,
    forward_prefill,
)
from pilottai_tpu.ops.kvcache import KVCache, write_chunk_rows, write_prompts
from pilottai_tpu.ops.paged import (
    PagedKVCache,
    gather_pages,
    install_lengths,
    write_chunk_rows_paged,
    write_prompts_paged,
)
from pilottai_tpu.ops.pallas.decode_attention import decode_attention
from pilottai_tpu.ops.pallas.paged_attention import paged_decode_attention

NEG_INF = -2.0**30


class DecodeState(NamedTuple):
    """Per-slot generation state living on device across chunks."""

    tokens: jax.Array  # [B] int32 — next input token (last sampled)
    done: jax.Array    # [B] bool — finished or empty slot
    budget: jax.Array  # [B] int32 — generations still allowed

    @classmethod
    def create(cls, n_slots: int) -> "DecodeState":
        return cls(
            tokens=jnp.zeros((n_slots,), jnp.int32),
            done=jnp.ones((n_slots,), bool),
            budget=jnp.zeros((n_slots,), jnp.int32),
        )


@partial(jax.jit, donate_argnames=("state",))
def admit_decode(
    state: DecodeState,
    slots: jax.Array,         # [A] int32; OOB rows dropped
    first_tokens: jax.Array,  # [A] int32 — sampled from the prefill logits
    budgets: jax.Array,       # [A] int32 — max_new_tokens - 1 (first token
                              # already produced); <= 0 admits as done
    live: jax.Array,          # [A] bool — False rows are padding
) -> DecodeState:
    slots = jnp.where(live, slots, state.tokens.shape[0])
    return DecodeState(
        tokens=state.tokens.at[slots].set(first_tokens, mode="drop"),
        done=state.done.at[slots].set(budgets <= 0, mode="drop"),
        budget=state.budget.at[slots].set(jnp.maximum(budgets, 0), mode="drop"),
    )


@partial(jax.jit, donate_argnames=("state",))
def release_decode(state: DecodeState, slots: jax.Array) -> DecodeState:
    """Host-side completion/cancel: stop decoding these slots."""
    return DecodeState(
        tokens=state.tokens,
        done=state.done.at[slots].set(True, mode="drop"),
        budget=state.budget.at[slots].set(0, mode="drop"),
    )


def _prefix_stats_dense(
    qg: jax.Array,       # [B, K, G, H]
    layer_k: jax.Array,  # [B, K, S, H]
    layer_v: jax.Array,
    last: jax.Array,     # [B] max valid key index (may be -1: empty)
    qpos: jax.Array,     # [B] query absolute position
    scale: float,
    softcap: float,
    window: int,
):
    """XLA fallback for the Pallas prefix kernel (CPU tests / tiny models).
    Same (acc, m, l) contract."""
    B, K, G, H = qg.shape
    S = layer_k.shape[2]
    s = jnp.einsum(
        "bkgh,bksh->bkgs", qg, layer_k, preferred_element_type=jnp.float32
    ) * scale
    if softcap > 0.0:
        s = jnp.tanh(s / softcap) * softcap
    col = jnp.arange(S)[None, None, None, :]
    mask = col <= last[:, None, None, None]
    if window > 0:
        mask &= (qpos[:, None, None, None] - col) < window
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)                                   # [B, K, G]
    p = jnp.where(
        m[..., None] > NEG_INF / 2, jnp.exp(s - m[..., None]), 0.0
    )
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum(
        "bkgs,bksh->bkgh", p.astype(layer_v.dtype), layer_v,
        preferred_element_type=jnp.float32,
    )
    return acc.reshape(B, K * G, H), m.reshape(B, K * G), l.reshape(B, K * G)


def _ring_stats(
    qg: jax.Array,      # [B, K, G, H]
    ring_k: jax.Array,  # [B, K, N, H]
    ring_v: jax.Array,
    step: jax.Array,    # scalar — current chunk step i (rows 0..i valid)
    scale: float,
    softcap: float,
    window: int,
):
    """In-chunk attention over the ring buffer. Row j holds the token at
    chunk-relative offset j; for an active slot offset == step, so the
    causal mask is j <= step and the window check (step - j) < window."""
    B, K, G, H = qg.shape
    N = ring_k.shape[2]
    s = jnp.einsum(
        "bkgh,bknh->bkgn", qg, ring_k, preferred_element_type=jnp.float32
    ) * scale
    if softcap > 0.0:
        s = jnp.tanh(s / softcap) * softcap
    j = jnp.arange(N)[None, None, None, :]
    mask = j <= step
    if window > 0:
        mask &= (step - j) < window
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])  # row 0 always valid -> never all-masked
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum(
        "bkgn,bknh->bkgh", p.astype(ring_v.dtype), ring_v,
        preferred_element_type=jnp.float32,
    )
    return acc.reshape(B, K * G, H), m.reshape(B, K * G), l.reshape(B, K * G)


def _combine_stats(acc_a, m_a, l_a, acc_b, m_b, l_b):
    """Merge two online-softmax partials over disjoint key sets."""
    m = jnp.maximum(m_a, m_b)
    wa = jnp.where(m_a > NEG_INF / 2, jnp.exp(m_a - m), 0.0)
    wb = jnp.where(m_b > NEG_INF / 2, jnp.exp(m_b - m), 0.0)
    l = l_a * wa + l_b * wb
    acc = acc_a * wa[..., None] + acc_b * wb[..., None]
    return acc / jnp.maximum(l, 1e-30)[..., None]


@partial(
    jax.jit,
    static_argnames=("cfg", "n_steps", "use_pallas", "prefix_bound"),
    donate_argnames=("cache", "dstate", "sampling"),
)
def decode_chunk(
    params,
    cfg: ModelConfig,
    cache: KVCache,
    dstate: DecodeState,
    sampling: SamplingState,
    n_steps: int,
    use_pallas: bool = True,
    prefix_bound: Optional[int] = None,
    table: Optional[jax.Array] = None,  # [B, max_pages] — paged cache only
    json_tables: Optional[Tuple[jax.Array, jax.Array]] = None,
    # ^ (token_bytes [Vt, L], token_len [Vt]) — subword JSON grammar mask
) -> Tuple[jax.Array, jax.Array, KVCache, DecodeState, SamplingState]:
    """Run ``n_steps`` decode steps for every slot in one dispatch.

    Returns ``(tokens [n, B], valid [n, B], cache, dstate, sampling)``;
    ``valid[i, b]`` marks tokens actually generated (slot active entering
    step i). Slots flip ``done`` on device at EOS / budget / context-full,
    so a finished slot stops writing cache and burning samples mid-chunk.

    ``prefix_bound`` (static) caps how much of each cache panel the prefix
    attention reads: the caller promises every *live* slot's length is
    ≤ bound, so keys past it can only belong to freed slots (whose output
    is discarded). Decode is HBM-bound and the cache read is roughly half
    the traffic at S=512 — reading ``[., ., bound, .]`` instead of the
    full ``[., ., S, .]`` panels makes short-context serving pay for the
    context it *has*, not the capacity it reserved. The host buckets the
    bound to powers of two so compile variants stay O(log S).
    """
    B = dstate.tokens.shape[0]
    paged = isinstance(cache, PagedKVCache)
    if paged:
        assert table is not None, "paged decode needs the block table"
        P = cache.page_size
        S = table.shape[1] * P               # per-slot capacity
        Sb = S if prefix_bound is None else max(1, min(prefix_bound, S))
        n_blocks = -(-Sb // P)
        if use_pallas:
            prefix_panels = cache.layers     # pools; kernel reads via table
        else:
            # XLA fallback: materialize bounded dense panels ONCE per
            # chunk (pool contents are frozen during the scan — decode
            # K/V goes to the ring until chunk end), then run the same
            # dense prefix attention as the unpaged path.
            prefix_panels = tuple(
                (
                    gather_pages(k_, table, n_blocks),
                    gather_pages(v_, table, n_blocks),
                )
                for (k_, v_) in cache.layers
            )
    else:
        S = cache.max_len
        Sb = S if prefix_bound is None else max(1, min(prefix_bound, S))
        # Bounded read-only views for the prefix attention (writes at chunk
        # end still land in the full panels).
        prefix_panels = tuple(
            (
                jax.lax.slice_in_dim(k_, 0, Sb, axis=2),
                jax.lax.slice_in_dim(v_, 0, Sb, axis=2),
            )
            for (k_, v_) in cache.layers
        )
    start = cache.lengths                    # [B] frozen during the chunk
    windows = cfg.window_sizes()
    qscale = cfg.query_scale if cfg.query_scale is not None else cfg.head_dim**-0.5
    G = cfg.n_heads // cfg.n_kv_heads
    batch_shape = (B, cfg.n_kv_heads, n_steps, cfg.head_dim)
    cache_dtype = cache.layers[0][0].dtype
    rings = tuple(
        (jnp.zeros(batch_shape, cache_dtype), jnp.zeros(batch_shape, cache_dtype))
        for _ in range(cfg.n_layers)
    )
    prefix_last = start - 1                  # max valid prefix key index

    def step(carry, i):
        tokens, done, budget, offset, sampling, rings = carry
        active = ~done
        pos = start + offset                 # current token's position
        x = _embed(cfg, params, tokens[:, None])          # [B, 1, E]
        sin, cos = rope_tables(pos[:, None], cfg.head_dim, cfg.rope_theta)

        new_rings = []
        for l in range(cfg.n_layers):
            lp = jax.tree.map(lambda a: a[l], params["layers"])
            window = int(windows[l])
            layer_k, layer_v = prefix_panels[l]
            rk, rv = rings[l]
            p = lp["attn"]

            h = rms_norm(x, lp["ln1"]["scale"], cfg.rms_eps, cfg.rms_offset)
            q, k, v = _qkv(cfg, p, h, sin, cos)  # [B, 1, heads, H]

            rk = jax.lax.dynamic_update_slice(
                rk, k[:, 0][:, :, None].astype(rk.dtype), (0, 0, i, 0)
            )
            rv = jax.lax.dynamic_update_slice(
                rv, v[:, 0][:, :, None].astype(rv.dtype), (0, 0, i, 0)
            )

            qf = q[:, 0]                                  # [B, N, H]
            if paged and use_pallas:
                acc_p, m_p, l_p = paged_decode_attention(
                    qf, layer_k, layer_v, table, prefix_last,
                    q_positions=pos, n_blocks=n_blocks,
                    scale=qscale, softcap=cfg.attn_softcap, window=window,
                )
            elif use_pallas and not paged:
                acc_p, m_p, l_p = decode_attention(
                    qf, layer_k, layer_v, prefix_last, q_positions=pos,
                    scale=qscale, softcap=cfg.attn_softcap, window=window,
                    return_stats=True,
                )
            else:
                acc_p, m_p, l_p = _prefix_stats_dense(
                    qf.reshape(B, cfg.n_kv_heads, G, cfg.head_dim),
                    layer_k, layer_v, prefix_last, pos,
                    qscale, cfg.attn_softcap, window,
                )
            acc_c, m_c, l_c = _ring_stats(
                qf.reshape(B, cfg.n_kv_heads, G, cfg.head_dim),
                rk, rv, i, qscale, cfg.attn_softcap, window,
            )
            attn = _combine_stats(acc_p, m_p, l_p, acc_c, m_c, l_c)

            out = _attn_out(cfg, p, attn.astype(x.dtype)[:, None])
            if cfg.post_norms:
                out = rms_norm(
                    out, lp["ln1_post"]["scale"], cfg.rms_eps, cfg.rms_offset
                )
            x_res = x + out
            h = rms_norm(x_res, lp["ln2"]["scale"], cfg.rms_eps, cfg.rms_offset)
            out, _ = _mlp(cfg, lp, h)
            if cfg.post_norms:
                out = rms_norm(
                    out, lp["ln2_post"]["scale"], cfg.rms_eps, cfg.rms_offset
                )
            x = x_res + out
            new_rings.append((rk, rv))

        h = rms_norm(x, params["final_norm"]["scale"], cfg.rms_eps, cfg.rms_offset)
        logits = _unembed(cfg, params, h)[:, 0]           # [B, V] fp32

        sampled, sampling = sample_core(
            logits, sampling, json_remaining=budget,
            json_token_tables=json_tables,
        )
        new_budget = budget - active.astype(jnp.int32)
        hit_eos = (sampling.eos_id >= 0) & (sampled == sampling.eos_id)
        ctx_full = (pos + 1) >= (S - 1)
        new_done = done | (active & (hit_eos | (new_budget <= 0) | ctx_full))
        new_tokens = jnp.where(active, sampled, tokens)
        new_offset = offset + active.astype(jnp.int32)
        carry = (
            new_tokens, new_done, new_budget, new_offset, sampling,
            tuple(new_rings),
        )
        return carry, (sampled, active)

    offset0 = jnp.zeros((B,), jnp.int32)
    carry0 = (
        dstate.tokens, dstate.done, dstate.budget, offset0, sampling, rings
    )
    (tokens, done, budget, offset, sampling, rings), (out_toks, out_valid) = (
        jax.lax.scan(step, carry0, jnp.arange(n_steps))
    )

    if paged:
        cache = write_chunk_rows_paged(
            cache, table, [r[0] for r in rings], [r[1] for r in rings],
            start, offset,
        )
    else:
        cache = write_chunk_rows(
            cache, [r[0] for r in rings], [r[1] for r in rings], start, offset
        )
    dstate = DecodeState(tokens=tokens, done=done, budget=budget)
    return out_toks, out_valid, cache, dstate, sampling


@partial(
    jax.jit,
    static_argnames=("cfg", "use_flash", "flash_mesh"),
    donate_argnames=("cache", "dstate", "sampling"),
)
def admit_group(
    params,
    cfg: ModelConfig,
    cache: KVCache,
    dstate: "DecodeState",
    sampling: SamplingState,
    tokens: jax.Array,     # [A, T] right-padded prompt ids
    positions: jax.Array,  # [A, T]
    lens: jax.Array,       # [A] true prompt lengths (0 = padding row)
    slots: jax.Array,      # [A] target slots (OOB = padding row)
    temps: jax.Array,      # [A]
    topks: jax.Array,      # [A]
    topps: jax.Array,      # [A]
    seeds: jax.Array,      # [A]
    eos: jax.Array,        # [A]
    jsonm: jax.Array,      # [A] bool
    budgets: jax.Array,    # [A] max_new_tokens - 1
    use_flash: bool = True,
    flash_mesh: Any = None,
    page_rows: Optional[jax.Array] = None,  # [A, max_pages] — paged cache
    json_tables: Optional[Tuple[jax.Array, jax.Array]] = None,
):
    """The whole admission path — prefill forward, batched cache write,
    sampler install, on-device first-token sample, decode-state install —
    as ONE device dispatch. Through a remote-TPU tunnel each dispatch
    costs tens of ms of host latency; five per admission group was a
    measurable slice of the p50 budget (VERDICT.md next-step 2).

    Returns (cache, dstate, sampling, first_tokens [A])."""
    logits, ks, vs = forward_prefill(
        params, cfg, tokens, positions, lens,
        use_flash=use_flash, flash_mesh=flash_mesh,
    )
    if isinstance(cache, PagedKVCache):
        assert page_rows is not None, "paged admission needs page rows"
        cache = write_prompts_paged(cache, page_rows, ks, vs, lens)
        cache = install_lengths(cache, slots, lens)
    else:
        cache = write_prompts(cache, slots, ks, vs, lens)
    sampling = admit_sampling(
        sampling, slots, temps, topks, topps, seeds, eos, jsonm
    )
    first, sampling = sample_prefill_tokens(
        logits, lens, slots, sampling, remaining=budgets + 1,
        json_tables=json_tables,
    )
    dstate = admit_decode(dstate, slots, first, budgets, lens > 0)
    return cache, dstate, sampling, first


@partial(jax.jit, donate_argnames=("sampling",))
def sample_prefill_tokens(
    logits: jax.Array,    # [A, T, V] fp32 — prefill logits
    valid: jax.Array,     # [A] prompt lengths (last logit at valid-1)
    slots: jax.Array,     # [A] slot each prompt was admitted into
    sampling: SamplingState,
    remaining: Optional[jax.Array] = None,  # [A] total generation budget
    json_tables: Optional[Tuple[jax.Array, jax.Array]] = None,
) -> Tuple[jax.Array, SamplingState]:
    """Sample each admitted prompt's first generated token on device,
    using (and advancing) the slot's sampling params — host-side sampling
    duplication was VERDICT.md Weak #9."""
    A = logits.shape[0]
    last = jnp.take_along_axis(
        logits, jnp.maximum(valid - 1, 0)[:, None, None], axis=1
    )[:, 0]                                              # [A, V]
    sub = jax.tree.map(lambda a: a[slots], sampling)
    tokens, sub = sample_core(
        last, sub, json_remaining=remaining, json_token_tables=json_tables
    )
    del A
    # Write back everything the sampler advanced: the PRNG keys and the
    # JSON automaton coords (the first token is the automaton's first
    # transition).
    return tokens, sampling._replace(
        key=sampling.key.at[slots].set(sub.key, mode="drop"),
        json_state=sampling.json_state.at[slots].set(sub.json_state, mode="drop"),
        json_stack=sampling.json_stack.at[slots].set(sub.json_stack, mode="drop"),
        json_depth=sampling.json_depth.at[slots].set(sub.json_depth, mode="drop"),
    )
