"""Compressed radix tree over token-id sequences.

The common index structure of the global KV cache tier (ISSUE 10 /
ROADMAP item 2): both the dense prefix store (``engine/prefix_cache.py``)
and the host-RAM cold tier (``kvcache/host_tier.py``) key cached K/V by
token-id prefixes, and both previously (or would otherwise) pay linear
scans over every entry per lookup — O(capacity x len) ``match``/``has``
in the dense store, measured as the admission-prep hot spot once
capacities grow past a handful of entries. A path-compressed radix tree
makes every lookup O(len(ids)):

* edges carry token *runs* (not single tokens), so a 1K-token preamble
  entry is a two-node path, not a 1K-node chain;
* ``longest_payload_prefix`` walks the query once and returns the
  deepest stored entry that prefixes it — the hit primitive;
* ``lcp_candidates`` reads the divergence points off the walked path —
  the derived-entry primitive the dense store's shared-preamble
  self-organization uses — without comparing against any entry directly;
* payload nodes are additionally indexed by exact key for O(1)-ish
  ``has``/``get``/``remove`` (tuple hashing is O(len), the same bound).

The paged ``PagePrefixIndex`` keeps its own block-granular radix (its
nodes ARE refcounted pages); this tree serves token-granular keys.
Host-side bookkeeping only — no jax imports, safe everywhere.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple


def _common_len(a: Tuple[int, ...], b: Sequence[int]) -> int:
    n = min(len(a), len(b))
    i = 0
    while i < n and a[i] == b[i]:
        i += 1
    return i


class RadixNode:
    """One tree node: the token run on its incoming edge, its children
    (keyed by each child edge's first token) and, when a key ends here,
    the stored payload."""

    __slots__ = ("label", "parent", "children", "payload", "key_len")

    def __init__(
        self,
        label: Tuple[int, ...],
        parent: Optional["RadixNode"],
        key_len: int,
    ) -> None:
        self.label = label
        self.parent = parent
        self.children: Dict[int, "RadixNode"] = {}
        self.payload: Any = None
        self.key_len = key_len  # tokens root -> here (inclusive of label)


class RadixTree:
    """Path-compressed token radix tree with per-key payloads."""

    def __init__(self) -> None:
        self._root = RadixNode((), None, 0)
        self._by_key: Dict[Tuple[int, ...], RadixNode] = {}

    def __len__(self) -> int:
        return len(self._by_key)

    def __contains__(self, ids: Sequence[int]) -> bool:
        return tuple(ids) in self._by_key

    def has(self, ids: Sequence[int]) -> bool:
        return tuple(ids) in self._by_key

    def get(self, ids: Sequence[int]) -> Any:
        node = self._by_key.get(tuple(ids))
        return node.payload if node is not None else None

    def keys(self) -> Iterator[Tuple[int, ...]]:
        return iter(self._by_key)

    def items(self) -> Iterator[Tuple[Tuple[int, ...], Any]]:
        for key, node in self._by_key.items():
            yield key, node.payload

    # ------------------------------------------------------------------ #

    def insert(self, ids: Sequence[int], payload: Any) -> RadixNode:
        """Store ``payload`` under exact key ``ids`` (replaces any
        existing payload). O(len(ids))."""
        key = tuple(ids)
        node = self._root
        i = 0
        while i < len(key):
            child = node.children.get(key[i])
            if child is None:
                leaf = RadixNode(key[i:], node, len(key))
                node.children[key[i]] = leaf
                node = leaf
                i = len(key)
                break
            m = _common_len(child.label, key[i:])
            if m < len(child.label):
                # Split the edge at the divergence point.
                child = self._split(child, m)
            node = child
            i += m
        if node is self._root:
            raise ValueError("empty key")
        self._by_key[key] = node
        node.payload = payload
        return node

    def _split(self, child: RadixNode, at: int) -> RadixNode:
        """Split ``child``'s edge after ``at`` label tokens; returns the
        new upper (pass-through) node."""
        parent = child.parent
        upper = RadixNode(
            child.label[:at], parent, child.key_len - len(child.label) + at
        )
        parent.children[child.label[0]] = upper
        child.label = child.label[at:]
        child.parent = upper
        upper.children[child.label[0]] = child
        return upper

    def remove(self, ids: Sequence[int]) -> Any:
        """Drop the key (returns its payload, or None when absent) and
        prune/merge pass-through structure so the tree never accretes
        dead interior nodes."""
        key = tuple(ids)
        node = self._by_key.pop(key, None)
        if node is None:
            return None
        payload, node.payload = node.payload, None
        # Prune payload-less leaves upward, then merge a single-child
        # pass-through survivor into its child.
        while (
            node is not self._root
            and node.payload is None
            and not node.children
        ):
            parent = node.parent
            del parent.children[node.label[0]]
            node = parent
        if (
            node is not self._root
            and node.payload is None
            and len(node.children) == 1
        ):
            (only,) = node.children.values()
            only.label = node.label + only.label
            only.parent = node.parent
            node.parent.children[only.label[0]] = only
        return payload

    # ------------------------------------------------------------------ #

    def longest_payload_prefix(
        self, ids: Sequence[int], proper: bool = True
    ) -> Optional[RadixNode]:
        """Deepest payload node whose key prefixes ``ids`` — with
        ``proper`` (the admission contract: a tail token must remain to
        produce first-token logits) the key must be strictly shorter
        than ``ids``. One O(len) walk."""
        limit = len(ids) - 1 if proper else len(ids)
        best: Optional[RadixNode] = None
        node = self._root
        i = 0
        while i < len(ids):
            child = node.children.get(ids[i])
            if child is None:
                break
            m = _common_len(child.label, ids[i:])
            if m < len(child.label):
                break
            i += m
            node = child
            if node.payload is not None and node.key_len <= limit:
                best = node
        return best

    def payload_prefixes(
        self, ids: Sequence[int], proper: bool = False
    ) -> List[RadixNode]:
        """EVERY payload node whose key prefixes ``ids``, shallowest
        first (so ``[-1]`` is ``longest_payload_prefix``'s answer). The
        cell router's affinity lookup needs the whole chain — the
        deepest entry may belong to a dead replica, and a dead owner's
        entry must not shadow a live owner's shallower one. One O(len)
        walk."""
        limit = len(ids) - 1 if proper else len(ids)
        out: List[RadixNode] = []
        node = self._root
        i = 0
        while i < len(ids):
            child = node.children.get(ids[i])
            if child is None:
                break
            m = _common_len(child.label, ids[i:])
            if m < len(child.label):
                break
            i += m
            node = child
            if node.payload is not None and node.key_len <= limit:
                out.append(node)
        return out

    def deepest_common(
        self, ids: Sequence[int]
    ) -> Tuple[Optional[RadixNode], int]:
        """``(payload_node, lcp)``: the longest common prefix between
        ``ids`` and ANY stored key, plus a payload node whose key starts
        with that prefix (the entry a partial restore can slice).
        Causal-attention K/V is suffix-independent per position, so the
        first ``lcp`` rows of that entry reconstruct ``ids[:lcp]``
        exactly — the cold-tier primitive that serves multi-turn
        transcripts whose stored turn diverges only past the shared
        history. One O(len) walk (+ a descent to the nearest payload)."""
        node = self._root
        i = 0
        while i < len(ids):
            child = node.children.get(ids[i])
            if child is None:
                break
            m = _common_len(child.label, ids[i:])
            i += m
            node = child
            if m < len(child.label):
                break
        if node is self._root:
            return None, 0
        best = node
        while best.payload is None:
            # Interior pass-through nodes always have children (pruned
            # otherwise), and every subtree holds a payload.
            best = next(iter(best.children.values()))
        return best, min(i, len(ids))

    def lcp_candidates(
        self, ids: Sequence[int], min_len: int = 1
    ) -> List[int]:
        """Distinct longest-common-prefix lengths between ``ids`` and
        stored keys that are worth deriving as their own entries:
        >= ``min_len``, strictly shorter than the keys they were read
        off, and not already stored. Sorted longest-first (store order —
        derived entries self-organize toward shared preambles). Read off
        the walked path's divergence points: every key in a sibling
        subtree shares exactly the path prefix; a mid-edge divergence
        shares the path plus the matched run."""
        out = set()
        node = self._root
        i = 0
        n = len(ids)
        while True:
            for tok, _child in node.children.items():
                if i < n and tok == ids[i]:
                    continue
                # Keys below this sibling edge extend past depth i (the
                # edge is non-empty), so their LCP with ids is exactly i.
                if i >= min_len:
                    out.add(i)
            if i >= n:
                break
            child = node.children.get(ids[i])
            if child is None:
                break
            m = _common_len(child.label, ids[i:])
            if m < len(child.label):
                # Diverged inside the edge: every key below shares i + m.
                if i + m >= min_len:
                    out.add(i + m)
                break
            i += m
            node = child
        return [
            p for p in sorted(out, reverse=True)
            if not self.has(tuple(ids[:p]))
        ]


__all__ = ["RadixTree", "RadixNode"]
