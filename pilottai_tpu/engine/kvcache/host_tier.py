"""Host-RAM cold tier for evicted KV (the spill side of ISSUE 10).

Device-resident prefix caches are capacity-bound: the dense store holds
a handful of panel entries, the paged radix a quarter of the page pool —
and eviction previously threw the K/V away, so a multi-turn agent
session whose entry aged out re-prefilled its ENTIRE history on the next
turn. This tier catches evictions instead: the evicted panels/pages copy
to host RAM via an async D2H started at eviction time (the ``_HostCopy``
discipline of PERF_NOTES r8 — ``copy_to_host_async`` at spill,
materialize lazily at restore; no thread ever blocks on a fresh device
round trip), and a later session resume or preamble hit restores from
host memory instead of recomputing the prefill FLOPs.

Eviction within the tier is **cost-aware** (``policy="cost"``): the
score is recency x reconstruction-cost density — prefill FLOPs saved per
byte held, which for token-keyed entries reduces to
``true_tokens / padded_rows`` (the model constants cancel within one
engine) — so a tightly packed preamble outlives an equally old but
mostly-padding entry. ``policy="lru"`` is plain recency.

**Sessions** pin lineages: ``note_session`` records each session's
latest prompt prefix, and entries lying on a live session's lineage are
evicted only when nothing unpinned remains (the tier never wedges).
The session table itself is a bounded LRU so unbounded client-minted
session ids cannot leak host memory.

Entries are keyed by token-id prefix in a ``RadixTree`` (O(len) match)
plus an exact-key dict. Everything here is host-side bookkeeping plus
async-copy handles — rebuild-proof by construction: an engine-state
rebuild swaps device pools and clears the device-resident indexes, but
this tier's numpy payloads and keys survive untouched.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pilottai_tpu.engine.kvcache.integrity import (
    corrupt_arrays,
    entry_header,
    kv_checksum,
)
from pilottai_tpu.engine.kvcache.policy import (
    eviction_score,
    validate_policy,
)
from pilottai_tpu.engine.kvcache.radix import RadixTree
from pilottai_tpu.reliability.inject import global_injector
from pilottai_tpu.utils.metrics import global_metrics


class SpillCopy:
    """Handle for device->host reads STARTED at spill time
    (``copy_to_host_async``) and materialized only when a restore (or a
    test) asks — by then the transfer has long landed, so ``wait`` is a
    host-side materialize, not a fresh blocking round trip. Mirrors
    ``engine/batcher.py:_HostCopy``; the AST tripwire
    (tests/test_no_blocking_hotpath.py) sanctions exactly this shape.

    With ``integrity=True`` (the host-tier entries — NOT the batcher's
    fold-path token reads, which alias this class as ``_HostCopy``), a
    CRC-32 **digest** seals at first materialization — the earliest
    moment the bytes are host-resident — and ``verify()`` recomputes it
    at every restore, so anything that rots the host copy between spill
    and restore (the ``kvcache.spill.corrupt`` chaos point simulates
    exactly this window) is detected instead of restored as silent
    wrong KV. The chaos point is gated on the same flag: corrupting a
    fold read would poison the TOKEN stream, which is the
    ``engine.fold.corrupt`` point's job, not this one's."""

    __slots__ = ("_arrays", "_host", "_digest", "_integrity")

    def __init__(self, arrays, integrity: bool = False) -> None:
        self._arrays = tuple(arrays)
        self._host: Optional[List[np.ndarray]] = None
        self._digest: Optional[int] = None
        self._integrity = bool(integrity)
        for a in self._arrays:
            try:
                a.copy_to_host_async()
            except AttributeError:  # plain numpy in tests
                pass

    def wait(self) -> List[np.ndarray]:
        if self._host is None:
            self._host = [np.asarray(a) for a in self._arrays]
            self._arrays = ()  # drop device refs once materialized
            if self._integrity:
                self._digest = kv_checksum(self._host)
                # Chaos point: bytes rot in host RAM AFTER the digest
                # sealed — the exact window verify() exists to catch.
                if global_injector.fire("kvcache.spill.corrupt") is not None:
                    self._host = [
                        np.array(h, copy=True) for h in self._host
                    ]
                    corrupt_arrays(self._host)
        return self._host

    def digest(self) -> int:
        """The sealed CRC-32 (materializes on first call; forces the
        integrity frame on for copies created without one)."""
        if self._digest is None:
            self._integrity = True
            self.wait()
            if self._digest is None:  # already materialized unsealed
                self._digest = kv_checksum(self._host)
        return self._digest  # type: ignore[return-value]

    def verify(self) -> bool:
        """Recompute the CRC over the current host bytes against the
        sealed digest. Cheap next to the H2D upload it gates."""
        host = self.wait()
        if self._digest is None:  # unframed copy: nothing to verify
            return True
        return kv_checksum(host) == self._digest


def _nbytes(arrays) -> int:
    total = 0
    for a in arrays:
        size = 1
        for d in a.shape:
            size *= int(d)
        total += size * np.dtype(a.dtype).itemsize
    return total


class HostEntry:
    """One spilled prefix: the token key, the (lazy) host payload and
    the eviction-score bookkeeping."""

    __slots__ = ("key", "copy", "nbytes", "tokens", "rows", "meta",
                 "kind", "stamp", "header")

    def __init__(self, key, copy, nbytes, tokens, rows, meta, kind,
                 header=None):
        self.key = key          # Tuple[int, ...] — the covered prefix
        self.copy = copy        # SpillCopy (or pre-materialized arrays)
        self.nbytes = nbytes
        self.tokens = tokens    # true tokens the entry reconstructs
        self.rows = rows        # padded rows held (>= tokens)
        self.meta = meta        # dense: p_bucket; paged: block index
        self.kind = kind        # "dense" | "page"
        self.stamp = 0
        # Layout/quant/version frame (kvcache/integrity.py), sealed at
        # put time from the device arrays' metadata; restore verifies
        # the materialized bytes still match it.
        self.header = header


class HostTier:
    """Bounded host-RAM store of spilled KV prefixes."""

    def __init__(
        self,
        budget_bytes: int,
        policy: str = "cost",
        max_sessions: int = 256,
    ) -> None:
        self.budget_bytes = max(0, int(budget_bytes))
        self.policy = validate_policy(policy, "kvcache")
        self._tree = RadixTree()
        self._bytes = 0
        self._clock = 0
        # Eviction notification (fired OUTSIDE the lock, after a budget
        # eviction fully dropped an entry from this tier): the serving
        # cell's routing table decays its affinity entry for the prefix
        # — once the KV is gone from both tiers, routing by it is pure
        # superstition. Callback receives the evicted key.
        self.on_evict = None
        # session id -> latest prompt prefix (lineage tip). Bounded LRU:
        # client-minted ids must not grow host state unboundedly.
        self._sessions: "OrderedDict[str, Tuple[int, ...]]" = OrderedDict()
        self.max_sessions = max_sessions
        # One lock: the tier is fed from the device thread (dense export
        # eviction), the prep thread (admission-pressure page eviction,
        # restores) and tests.
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._tree)

    @property
    def bytes_held(self) -> int:
        return self._bytes

    # ------------------------------------------------------------------ #
    # Spill (put)
    # ------------------------------------------------------------------ #

    def put(
        self,
        key: Sequence[int],
        arrays,
        *,
        tokens: int,
        rows: Optional[int] = None,
        meta: Any = None,
        kind: str = "dense",
        count: bool = True,
    ) -> bool:
        """Accept an evicted entry's device arrays: start the async D2H
        now (off the hot path — nothing waits on it here), account the
        bytes, and evict colder host entries past the budget. Returns
        False (and starts nothing) when the entry alone exceeds the
        whole budget. ``count=False`` skips the spill counters (a
        cross-replica migration import is a transfer, not a spill)."""
        nbytes = _nbytes(arrays)
        if self.budget_bytes <= 0 or nbytes > self.budget_bytes:
            return False
        key = tuple(key)
        copy = SpillCopy(arrays, integrity=True)
        with self._lock:
            old = self._tree.get(key)
            if old is not None:
                # Same prefix re-spilled (identical content by
                # construction — prefix K/V is deterministic): keep the
                # fresh copy, swap the accounting.
                self._bytes -= old.nbytes
            entry = HostEntry(
                key, copy, nbytes, tokens,
                rows if rows is not None else tokens, meta, kind,
                header=entry_header(arrays, kind),
            )
            self._clock += 1
            entry.stamp = self._clock
            self._tree.insert(key, entry)
            self._bytes += nbytes
            evicted = self._evict_over_budget_locked()
            self._gauges_locked()
        self._fire_evictions(evicted)
        if count:
            global_metrics.inc("engine.kvcache.spills")
            global_metrics.inc("engine.kvcache.spill_bytes", nbytes)
        return True

    # ------------------------------------------------------------------ #
    # Lookup / restore (take)
    # ------------------------------------------------------------------ #

    def match(self, ids: Sequence[int]) -> Optional[HostEntry]:
        """Longest host entry that is a PROPER prefix of ``ids``
        (dense-tier hit primitive). Touches the entry."""
        with self._lock:
            node = self._tree.longest_payload_prefix(ids, proper=True)
            if node is None:
                return None
            entry = node.payload
            self._clock += 1
            entry.stamp = self._clock
            return entry

    def match_lcp(
        self, ids: Sequence[int]
    ) -> Tuple[Optional[HostEntry], int]:
        """``(entry, lcp)``: the entry sharing the LONGEST common prefix
        with ``ids`` — not necessarily a whole-entry prefix. Prefix K/V
        is suffix-independent, so the restore path slices the entry's
        first ``lcp`` rows: exactly how a stored previous turn serves
        the next turn of the same transcript, whose prompts share the
        whole history but diverge at the new user message. ``lcp`` is
        capped to a PROPER prefix of ``ids``."""
        with self._lock:
            node, lcp = self._tree.deepest_common(ids)
            if node is None:
                return None, 0
            entry = node.payload
            self._clock += 1
            entry.stamp = self._clock
            return entry, min(lcp, len(ids) - 1, len(entry.key))

    def extension_blocks(
        self, ids: Sequence[int], from_block: int, page_size: int,
        max_blocks: int,
    ) -> List[HostEntry]:
        """Paged-tier hit primitive: the contiguous run of spilled page
        blocks continuing a live chain of ``from_block`` blocks — entry
        b covers ``ids[:(b+1) * page_size]``. Stops at the first gap, at
        ``max_blocks`` total blocks, and always leaves at least one tail
        token unprefilled (proper-prefix contract)."""
        out: List[HostEntry] = []
        limit = min(max_blocks, (len(ids) - 1) // page_size)
        with self._lock:
            for b in range(from_block, limit):
                entry = self._tree.get(tuple(ids[: (b + 1) * page_size]))
                if entry is None or entry.kind != "page":
                    break
                self._clock += 1
                entry.stamp = self._clock
                out.append(entry)
        return out

    def take(self, key: Sequence[int]) -> Optional[HostEntry]:
        """Remove and return an entry (restore moves ownership back to
        the device-resident tier; a later eviction re-spills it)."""
        with self._lock:
            entry = self._tree.remove(tuple(key))
            if entry is not None:
                self._bytes -= entry.nbytes
                self._gauges_locked()
            return entry

    def get(self, key: Sequence[int]) -> Optional[HostEntry]:
        with self._lock:
            return self._tree.get(tuple(key))

    def reinsert(self, entry: HostEntry) -> None:
        """Hand back an entry a restore consumed but could not complete
        (its pool was rebuilt mid-flight): the payload is already host
        numpy, so this is pure bookkeeping — the cold tier stays
        rebuild-proof."""
        with self._lock:
            old = self._tree.get(entry.key)
            if old is not None:
                self._bytes -= old.nbytes
            self._clock += 1
            entry.stamp = self._clock
            self._tree.insert(entry.key, entry)
            self._bytes += entry.nbytes
            evicted = self._evict_over_budget_locked()
            self._gauges_locked()
        self._fire_evictions(evicted)

    def clear(self) -> None:
        with self._lock:
            self._tree = RadixTree()
            self._bytes = 0
            self._gauges_locked()

    # ------------------------------------------------------------------ #
    # Sessions
    # ------------------------------------------------------------------ #

    def note_session(self, session_id: Optional[str],
                     ids: Sequence[int]) -> None:
        """Record a session's latest prompt prefix as its lineage tip:
        host entries prefixing a live lineage are eviction-protected."""
        if not session_id:
            return
        with self._lock:
            self._sessions[session_id] = tuple(ids)
            self._sessions.move_to_end(session_id)
            while len(self._sessions) > self.max_sessions:
                self._sessions.popitem(last=False)
            global_metrics.set_gauge(
                "engine.kvcache.sessions", float(len(self._sessions))
            )

    def lineage(self, session_id: Optional[str]) -> Optional[Tuple[int, ...]]:
        """The session's recorded lineage tip (latest prompt prefix), or
        None — the migration export's starting point."""
        if not session_id:
            return None
        with self._lock:
            return self._sessions.get(session_id)

    def drop_session(self, session_id: Optional[str]) -> None:
        """Forget a session's lineage pin (after its KV migrated away —
        the source tier must not keep protecting entries it no longer
        holds for a session it no longer serves)."""
        if not session_id:
            return
        with self._lock:
            self._sessions.pop(session_id, None)
            global_metrics.set_gauge(
                "engine.kvcache.sessions", float(len(self._sessions))
            )

    def prefix_entries(self, ids: Sequence[int]) -> List[HostEntry]:
        """EVERY entry whose key prefixes ``ids``, shallowest first —
        the host-resident part of a session's KV lineage, read without
        removing. The migration export COPIES these (the entries may
        serve OTHER sessions sharing the preamble, and a target-side
        budget rejection must not lose the KV from both replicas);
        dropping the migrated session's pin afterwards lets the source
        copies age out under normal budget pressure."""
        with self._lock:
            nodes = self._tree.payload_prefixes(tuple(ids))
            out: List[HostEntry] = []
            for node in nodes:
                entry = node.payload
                self._clock += 1
                entry.stamp = self._clock
                out.append(entry)
            return out

    def _protected_locked(self, entry: HostEntry) -> bool:
        k = entry.key
        n = len(k)
        for lineage in self._sessions.values():
            if len(lineage) >= n and lineage[:n] == k:
                return True
        return False

    # ------------------------------------------------------------------ #
    # Eviction
    # ------------------------------------------------------------------ #

    def _score_locked(self, entry: HostEntry) -> float:
        return eviction_score(
            entry.stamp, entry.tokens, entry.rows, self.policy
        )

    def _evict_over_budget_locked(self) -> List[Tuple[int, ...]]:
        """One ranked pass per overflow (not per victim — a multi-victim
        overflow at 'thousands of paged blocks' scale must not rescan
        every entry × every session lineage per eviction): score and
        session-protection are computed once per entry, unpinned entries
        evict coldest-first, and pinned entries only once nothing
        unpinned remains (bounded memory beats a perfect pin). Returns
        the evicted keys so callers can fire ``on_evict`` outside the
        lock."""
        evicted: List[Tuple[int, ...]] = []
        if self._bytes <= self.budget_bytes or len(self._tree) <= 1:
            return evicted
        ranked = sorted(
            ((self._score_locked(e), e) for _, e in self._tree.items()),
            key=lambda t: t[0],
        )
        deferred: List[HostEntry] = []
        for _s, entry in ranked:
            if self._bytes <= self.budget_bytes:
                return evicted
            if self._protected_locked(entry):
                deferred.append(entry)
                continue
            self._tree.remove(entry.key)
            self._bytes -= entry.nbytes
            evicted.append(entry.key)
            global_metrics.inc("engine.kvcache.evictions")
        for entry in deferred:
            if self._bytes <= self.budget_bytes or len(self._tree) <= 1:
                return evicted
            self._tree.remove(entry.key)
            self._bytes -= entry.nbytes
            evicted.append(entry.key)
            global_metrics.inc("engine.kvcache.evictions")
        return evicted

    def _fire_evictions(self, keys: List[Tuple[int, ...]]) -> None:
        """Eviction callback fan-out — OUTSIDE the tier lock (the cell's
        routing table takes its own lock; never raises into the spill
        path)."""
        cb = self.on_evict
        if cb is None or not keys:
            return
        for key in keys:
            try:
                cb(key)
            except Exception:  # noqa: BLE001 — decay is best-effort
                pass

    def _gauges_locked(self) -> None:
        global_metrics.set_gauge(
            "engine.kvcache.host_bytes", float(self._bytes)
        )
        global_metrics.set_gauge(
            "engine.kvcache.host_entries", float(len(self._tree))
        )


__all__ = ["HostTier", "HostEntry", "SpillCopy"]
