"""KV integrity framing: checksum + layout/version headers end to end.

The recovery paths the fault domain leans on (host-tier restore after
eviction, cross-replica session migration) previously trusted their
payloads blindly: a bit flipped in host RAM between spill and restore,
or a corrupted migration frame, restored as *silent wrong KV* — decode
then produced confidently wrong tokens with no contained fault anywhere.

This module gives every host-tier entry and every wire payload a frame:

* a **CRC-32 checksum** over the raw K/V bytes, sealed at the moment the
  data becomes host-resident (spill materialize / export pack) and
  re-verified at every consumption (restore, import);
* a **layout header** (``version`` / ``kind`` / per-array dtype+shape —
  dtype doubles as the quant mode: an int8 entry IS a quantized entry)
  checked before any byte is interpreted, so a version or quant-mode
  mismatch between replicas rejects cleanly instead of reshaping noise.

A failed check is a *contained* fault: the consumer drops the entry,
counts ``engine.kvcache.integrity_failures`` and falls back to
re-prefill (correct by construction — slower, never wrong). Checksums
are integrity framing against rot and truncation, not authentication.
"""

from __future__ import annotations

import zlib
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

KV_FRAME_VERSION = 1


def _byte_view(a: np.ndarray) -> np.ndarray:
    """Flat uint8 view of an array's raw bytes (copy only when the
    dtype's buffer can't reinterpret — ml_dtypes like bfloat16 can)."""
    b = np.ascontiguousarray(a)
    try:
        return b.view(np.uint8).reshape(-1)
    except (TypeError, ValueError):
        return np.frombuffer(b.tobytes(), np.uint8)


def kv_checksum(arrays: Sequence[Any], crc: int = 0) -> int:
    """CRC-32 over the concatenated raw bytes of host arrays."""
    for a in arrays:
        crc = zlib.crc32(_byte_view(np.asarray(a)), crc)
    return crc & 0xFFFFFFFF


def entry_header(arrays: Sequence[Any], kind: str) -> Dict[str, Any]:
    """Layout/quant/version header for one entry's K/V arrays. Reads
    only dtype/shape metadata — safe on device arrays pre-transfer."""
    return {
        "v": KV_FRAME_VERSION,
        "kind": kind,
        # dtype doubles as the quant mode: int8 panels ARE the
        # quantized layout; bf16/f32 the unquantized one.
        "dtype": [str(np.dtype(a.dtype)) for a in arrays],
        "shape": [tuple(int(d) for d in a.shape) for a in arrays],
    }


def header_matches(
    header: Optional[Dict[str, Any]], arrays: Sequence[Any]
) -> bool:
    """Does a sealed header describe these (host) arrays? False on
    unknown version, kind-less frames, or any dtype/shape drift —
    the caller must reject before interpreting a byte."""
    if not isinstance(header, dict):
        return False
    if header.get("v") != KV_FRAME_VERSION:
        return False
    dtypes = header.get("dtype")
    shapes = header.get("shape")
    if not isinstance(dtypes, (list, tuple)) or len(dtypes) != len(arrays):
        return False
    if not isinstance(shapes, (list, tuple)) or len(shapes) != len(arrays):
        return False
    for a, dt, sh in zip(arrays, dtypes, shapes):
        a = np.asarray(a)
        if str(np.dtype(a.dtype)) != dt:
            return False
        if tuple(int(d) for d in a.shape) != tuple(int(d) for d in sh):
            return False
    return True


def frame_ok(entry: Dict[str, Any], arrays: Sequence[Any]) -> bool:
    """Full frame check for one sealed export entry: CRC over the raw
    bytes AND the layout header, in that order. The single gate every
    import path (session migration, prefill→decode handoff) runs before
    a byte of the payload is interpreted."""
    crc = entry.get("crc")
    if crc is None or kv_checksum(arrays) != int(crc):
        return False
    return header_matches(entry.get("header"), arrays)


def corrupt_arrays(arrays: Sequence[np.ndarray]) -> None:
    """Chaos helper: flip one byte of the first non-empty array IN
    PLACE — the canonical 'host RAM rotted' injection the
    ``kvcache.*.corrupt`` fault points use."""
    for a in arrays:
        a = np.asarray(a)
        if a.size == 0:
            continue
        view = a.view(np.uint8) if a.flags["C_CONTIGUOUS"] else None
        if view is None:
            continue
        flat = view.reshape(-1)
        flat[0] ^= 0xFF
        return


__all__ = [
    "KV_FRAME_VERSION",
    "kv_checksum",
    "entry_header",
    "header_matches",
    "frame_ok",
    "corrupt_arrays",
]
