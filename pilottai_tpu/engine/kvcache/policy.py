"""Shared eviction-policy helpers for the KV cache tier.

One definition of the cost-aware score used by BOTH the device-resident
dense store (``engine/prefix_cache.py``) and the host-RAM cold tier
(``kvcache/host_tier.py``) — two private copies would silently diverge
the tiers' eviction behavior on the next tuning pass.
"""

from __future__ import annotations

POLICIES = ("cost", "lru")


def validate_policy(policy: str, who: str) -> str:
    if policy not in POLICIES:
        raise ValueError(
            f"unknown {who} policy {policy!r}; supported: "
            + ", ".join(repr(p) for p in POLICIES)
        )
    return policy


def eviction_score(stamp: int, tokens: int, rows: int, policy: str) -> float:
    """Smaller = evicted first. ``lru`` is plain recency; ``cost``
    weighs recency by reconstruction-cost density — prefill FLOPs saved
    scale with true ``tokens``, bytes held with padded ``rows``, and the
    per-model constants cancel within one engine, leaving tokens/rows in
    (0, 1] mapped to a [0.5, 1.0] recency multiplier."""
    if policy == "lru":
        return float(stamp)
    density = tokens / max(rows, 1)
    return float(stamp) * (0.5 + 0.5 * density)


__all__ = ["POLICIES", "eviction_score", "validate_policy"]
