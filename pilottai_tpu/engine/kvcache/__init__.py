"""Global KV cache tier (ISSUE 10 / ROADMAP item 2).

One radix-indexed prefix cache spanning all traffic — the dense panel
store and the paged page radix unified behind ``KVCacheIndex`` — with
cost-aware eviction and a host-RAM cold tier: evicted KV spills to host
buffers via async D2H started off the hot path, and session resumes /
repeated preambles restore via async H2D instead of re-prefilling.

Import cost: ``radix`` and ``host_tier`` are jax-free; ``index`` (the
spill/restore orchestration) imports jax and is pulled in lazily by the
engine only.
"""

from pilottai_tpu.engine.kvcache.host_tier import HostEntry, HostTier, SpillCopy
from pilottai_tpu.engine.kvcache.radix import RadixNode, RadixTree

__all__ = [
    "HostEntry",
    "HostTier",
    "KVCacheIndex",
    "PendingRestore",
    "RadixNode",
    "RadixTree",
    "SpillCopy",
]


def __getattr__(name):
    # KVCacheIndex/PendingRestore import jax; load on first touch so
    # control-plane users of the radix/host tier never pay it.
    if name in ("KVCacheIndex", "PendingRestore"):
        from pilottai_tpu.engine.kvcache import index as _index

        return getattr(_index, name)
    raise AttributeError(name)
