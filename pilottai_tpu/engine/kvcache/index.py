"""KVCacheIndex: one prefix-cache lookup over ALL traffic + spill/restore.

The tentpole facade of ISSUE 10 (ROADMAP item 2). Before it, prefix
reuse was two disconnected structures the batcher special-cased at every
call site: the dense panel store (``engine/prefix_cache.py``) and the
paged page radix (``engine/page_prefix.py``) — and eviction from either
threw KV away. This index unifies them behind one lookup and threads
both into the host-RAM cold tier (``kvcache/host_tier.py``):

* **lookup** — ``lookup_dense`` / ``lookup_paged`` are the single entry
  point the batcher's ``_prefix_hit`` calls: device-resident hit first,
  then the host tier; a host hit RESTORES (async H2D staged off the
  device thread) instead of re-prefilling.
* **spill** — wired as the eviction callbacks of both device-resident
  structures: an evicted dense entry's panels (or an evicted leaf
  page's K/V) start their D2H at eviction time (``SpillCopy`` — the
  ``_HostCopy`` discipline) and land in the host tier.
* **restore, dense** — materialize the host panels (the spill's copy
  landed long ago), ``jax.device_put`` them (async H2D, prep thread)
  and hand the batcher a normal ``PrefixEntry``: the admission path is
  byte-identical to a device-resident hit.
* **restore, paged** — take fresh pages from the allocator, register
  the chain into the live radix, upload the panels, and return a
  ``PendingRestore`` record: the DEVICE thread scatters it into the
  page pool (``apply_restores`` — a donated jitted write) before any
  dispatch can read those pages. The device thread never blocks on the
  transfer; the prep thread never mutates device state.

Threading contract: lookups and paged spills run under the batcher's
slot lock (prep or device thread); dense spills under the same lock on
the device thread; ``apply_restores`` on the device thread only. The
host tier has its own lock and survives engine-state rebuilds by
construction (epoch-stamped restore records from a dead pool are
dropped at apply time; the host entries themselves persist).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pilottai_tpu.engine.kvcache.host_tier import HostTier
from pilottai_tpu.engine.kvcache.integrity import (
    corrupt_arrays,
    entry_header,
    frame_ok,
    header_matches,
    kv_checksum,
)
from pilottai_tpu.ops.kvcache import dequantize_kv
from pilottai_tpu.ops.paged import write_prompts_paged
from pilottai_tpu.reliability.inject import global_injector
from pilottai_tpu.utils.metrics import global_metrics

# Donated pool scatter for restored page chains: same op the paged
# admission path uses for prompt writes, compiled per (pool shape, chain
# bucket) — the chain width is power-of-two bucketed by the caller so
# executables stay bounded.
_restore_write = jax.jit(write_prompts_paged, donate_argnums=(0,))


def _gather_page_fn(cache, page):
    """Read one page's K/V out of every layer's pool as stacked
    [L, K, P, H] arrays (int8 pools dequantize — the restore write
    re-quantizes with identical recomputed scales, a lossless round
    trip per the dense store's export discipline)."""
    ks_l, vs_l = [], []
    for li, (kp, vp) in enumerate(cache.layers):
        K, _, P, H = kp.shape
        gk = jax.lax.dynamic_slice(kp, (0, page, 0, 0), (K, 1, P, H))[:, 0]
        gv = jax.lax.dynamic_slice(vp, (0, page, 0, 0), (K, 1, P, H))[:, 0]
        if cache.scales is not None:
            ksc, vsc = cache.scales[li]
            gsk = jax.lax.dynamic_slice(ksc, (0, page, 0), (K, 1, P))[:, 0]
            gsv = jax.lax.dynamic_slice(vsc, (0, page, 0), (K, 1, P))[:, 0]
            gk = dequantize_kv(gk, gsk, jnp.float32)
            gv = dequantize_kv(gv, gsv, jnp.float32)
        ks_l.append(gk)
        vs_l.append(gv)
    return jnp.stack(ks_l), jnp.stack(vs_l)


_gather_page = jax.jit(_gather_page_fn)


class PendingRestore:
    """One restored page chain awaiting its device-thread pool write.
    ``epoch`` stamps the allocator generation the pages came from: a
    rebuild makes the record meaningless (fresh pool, index cleared) and
    ``apply_restores`` drops it — re-inserting the consumed host entries
    (``entries``) into the cold tier, so a restore caught mid-flight by
    a PR 8 recovery unwinds cleanly and the KV survives for the
    re-admission to restore again."""

    __slots__ = ("epoch", "table", "ks", "vs", "lengths", "tokens",
                 "entries", "pages")

    def __init__(self, epoch, table, ks, vs, lengths, tokens, entries,
                 pages):
        self.epoch = epoch
        self.table = table      # np [1, kb] — restore pages, sentinel pad
        self.ks = ks            # device [L, 1, kb*P, K, H] (device_put'd)
        self.vs = vs
        self.lengths = lengths  # np [1] — true restored tokens
        self.tokens = tokens
        self.entries = entries  # the HostEntry list the restore consumed
        self.pages = pages      # the taken pages awaiting the pool write


class KVCacheIndex:
    """Unified prefix/KV lookup + cost-aware spill/restore tiering."""

    def __init__(
        self,
        *,
        prefix_store: Optional[Any] = None,
        page_index: Optional[Any] = None,
        page_size: int = 0,
        host_bytes: int = 0,
        policy: str = "cost",
        get_cache: Optional[Callable[[], Any]] = None,
        min_len: Optional[int] = None,
        place: Optional[Callable[[Any], Any]] = None,
    ) -> None:
        self.prefix_store = prefix_store
        self.page_index = page_index
        self.page_size = page_size
        self._get_cache = get_cache
        # Host→device placement for restored panels. Default: plain
        # ``jax.device_put``. A tensor-parallel batcher passes a
        # sharding-aware placer so restored K/V uploads land already
        # split over the 'model' axis — the follow-on admission/scatter
        # consumes them without a whole-panel reshard (ISSUE 13: the
        # PR 9 gather/spill/restore paths follow the KV sharding).
        self._place = place if place is not None else jax.device_put
        # Dense entry floor (engine_prefix_min_len): prompts at or below
        # it never produce a dense entry (entries store the prompt minus
        # its last token), so lookups and pre-warms that short can never
        # hit. Documented here because the tier's callers (the batcher's
        # pre-warm path, bench workloads) must clear it — the one-shot
        # warning in the batcher fires when they don't. None = the
        # store's own floor (or 0 paged, where granularity is a page).
        self._min_len = min_len
        self.host: Optional[HostTier] = (
            HostTier(host_bytes, policy) if host_bytes > 0 else None
        )
        # Pages a PendingRestore has taken but not yet written into the
        # pool (guarded by the batcher's slot lock, like every other
        # call into this index): an eviction racing the device-thread
        # write must NOT spill their never-written contents as valid KV.
        self._unwritten: set = set()
        if self.host is not None:
            if prefix_store is not None:
                prefix_store.on_evict = self._spill_dense
            if page_index is not None:
                page_index.on_evict = self._spill_page

    @property
    def min_len(self) -> int:
        """The dense tier's caching floor in tokens (0 when paged or
        uncached — block granularity makes the dense floor moot)."""
        if self._min_len is not None:
            return self._min_len
        if self.prefix_store is not None:
            return self.prefix_store.min_len
        return 0

    # ------------------------------------------------------------------ #
    # Spill (eviction callbacks of the device-resident structures)
    # ------------------------------------------------------------------ #

    def _spill_dense(self, entry) -> None:
        """Dense-store eviction: the entry's panels are plain
        (non-donated) device arrays — start their D2H now and let the
        host tier own the handle. Nothing blocks here."""
        self.host.put(
            entry.ids, (entry.ks, entry.vs),
            tokens=len(entry.ids), rows=entry.p_bucket,
            meta=entry.p_bucket, kind="dense",
        )

    def _spill_page(self, path_ids: Tuple[int, ...], page: int) -> None:
        """Paged-radix leaf eviction (called under the batcher's slot
        lock, BEFORE the page is unpinned): enqueue the page gather —
        registered pages are immutable prompt KV, and the lock orders
        this dispatch before any re-allocation could overwrite it — and
        hand the in-flight copy to the host tier."""
        if page in self._unwritten:
            # A restored-but-not-yet-written page: its pool contents are
            # whatever the previous owner left. Spilling that as valid
            # KV would poison the host tier — drop instead (the KV this
            # chain held came FROM the host tier moments ago).
            return
        for _attempt in range(2):
            # self.cache is rebound by the DEVICE thread's donated
            # dispatches outside the slot lock, so the snapshot we read
            # here can have been consumed already — the jit call then
            # raises on deleted buffers. Re-read the fresh binding once;
            # a second failure means the pool is mid-rebuild and the
            # spill is moot.
            cache = self._get_cache()
            try:
                ks, vs = _gather_page(cache, jnp.int32(page))
                break
            except Exception:  # noqa: BLE001 — donated-buffer race
                continue
        else:
            return
        self.host.put(
            path_ids, (ks, vs),
            tokens=self.page_size, rows=self.page_size,
            meta=len(path_ids) // max(self.page_size, 1) - 1, kind="page",
        )

    # ------------------------------------------------------------------ #
    # Integrity gate (ISSUE 16)
    # ------------------------------------------------------------------ #

    def _entry_ok(self, entry) -> bool:
        """Verify a host entry's frame before any restored byte is
        consumed: layout header against the materialized arrays, then
        the sealed CRC against the current host bytes. A failed check
        is a contained fault — the CALLER drops the entry and falls
        back to re-prefill (correct, never wrong); this method only
        verifies and counts ``engine.kvcache.integrity_failures``."""
        copy = entry.copy
        arrays = copy.wait() if hasattr(copy, "wait") else list(copy)
        # Chaos point: bytes rot between materialization and THIS
        # restore (distinct from kvcache.spill.corrupt, which rots at
        # spill time — both must be caught here).
        if global_injector.fire("kvcache.restore.corrupt") is not None:
            arrays[:] = [np.array(a, copy=True) for a in arrays]
            corrupt_arrays(arrays)
        ok = True
        if entry.header is not None and not header_matches(
            entry.header, arrays
        ):
            ok = False
        if ok and hasattr(copy, "verify"):
            ok = copy.verify()
        if not ok:
            global_metrics.inc("engine.kvcache.integrity_failures")
        return ok

    # ------------------------------------------------------------------ #
    # Lookup (the ONE entry point for all traffic)
    # ------------------------------------------------------------------ #

    def lookup_dense(
        self,
        ids: Sequence[int],
        *,
        session_id: Optional[str] = None,
        fits: Optional[Callable[[int, int], bool]] = None,
        bucket: Optional[Callable[[int], int]] = None,
        count: bool = True,
    ):
        """Dense-tier lookup: hot store first, host tier second. A host
        hit restores — panels upload via async ``device_put`` (the
        admission dispatch consumes them in stream order; this thread
        never waits on the transfer) and re-enter the hot store so the
        NEXT hit is device-resident. The host match is LCP-based:
        prefix K/V is suffix-independent per position, so a stored
        previous turn serves the next turn of the same transcript by
        slicing its first ``lcp`` rows, even though the stored prompt
        diverges past the shared history. Returns a ``PrefixEntry`` or
        None. ``fits(plen, p_bucket)`` is the caller's geometry check
        (tail bucket must land inside max_seq); ``bucket`` the caller's
        prefill-bucket ladder for sliced partial restores."""
        store = self.prefix_store
        if store is None:
            return None
        if count:
            # count=False on repeat attempts for the same request (a
            # page-blocked head re-selects every prep cycle): the
            # lookups/hits counters mean one lookup per request.
            global_metrics.inc("engine.kvcache.lookups")
        if self.host is not None:
            self.host.note_session(session_id, ids)
        entry = store.match(ids)
        if entry is not None and fits is not None and not fits(
            len(entry.ids), entry.p_bucket
        ):
            # Geometry miss (tail bucket would overrun max_seq): this
            # entry is unusable for THIS prompt — a shorter host entry
            # may still fit.
            entry = None
        h, lcp = (
            self.host.match_lcp(ids) if self.host is not None
            else (None, 0)
        )
        if h is not None:
            # Sliced-restore geometry: the usable rows are the shared
            # prefix at its own bucket rung.
            p_bucket = (
                min(bucket(lcp), h.rows) if bucket is not None else h.rows
            )
        if (
            h is None
            or h.kind != "dense"
            or lcp < store.min_len
            # A hot hit at least as long is free — restoring a shorter
            # (or equal) host prefix would spend a copy to save fewer
            # tokens.
            or (entry is not None and lcp <= len(entry.ids))
            or (fits is not None and not fits(lcp, p_bucket))
        ):
            if entry is not None and count:
                global_metrics.inc("engine.kvcache.hits")
            return entry
        if not self._entry_ok(h):
            # Corrupt host entry: drop it (it can never verify) and
            # serve whatever the hot store had — the caller re-prefills
            # the rest, so output stays byte-identical, just slower.
            self.host.take(h.key)
            if entry is not None and count:
                global_metrics.inc("engine.kvcache.hits")
            return entry
        t0 = time.perf_counter()
        key = tuple(h.key[:lcp])
        # Staging runs under the batcher's slot lock (we are inside its
        # selection path): wait() is a host materialize of a D2H that
        # landed at spill time and device_put is an async enqueue, but
        # for multi-MB entries the memcpy wall is real — it is exactly
        # what engine.kvcache.restore_ms measures, and it is paid once
        # per resume-after-eviction, not per token. The device thread
        # itself never waits on the transfer.
        ks_h, vs_h = h.copy.wait()  # spill copy landed long ago
        if lcp < len(h.key) or p_bucket < h.rows:
            ks_h = ks_h[:, :, :p_bucket]
            vs_h = vs_h[:, :, :p_bucket]
        ks_d = self._place(ks_h)
        vs_d = self._place(vs_h)
        if lcp == len(h.key):
            # Whole-entry restore: ownership moves back to the hot
            # store. A partial (sliced) restore leaves the host entry in
            # place — its full depth may serve its own session's resume.
            self.host.take(h.key)
        # Back into the hot store first (best effort — capacity pressure
        # may bounce it straight back out through the spill path), and
        # return the STORE's entry object when it stuck: same-wave
        # requests sharing the prefix then match identically and group
        # into one admission dispatch.
        store.store(key, ks_d, vs_d, p_bucket)
        restored = store.match(ids)
        if restored is None or restored.ids != key:
            from pilottai_tpu.engine.prefix_cache import PrefixEntry

            restored = PrefixEntry(key, ks_d, vs_d, p_bucket)
        if count:
            global_metrics.inc("engine.kvcache.hits")
        global_metrics.inc("engine.kvcache.host_hits")
        global_metrics.inc("engine.kvcache.restores")
        global_metrics.inc("engine.kvcache.restored_tokens", lcp)
        global_metrics.observe(
            "engine.kvcache.restore_ms", (time.perf_counter() - t0) * 1e3
        )
        return restored

    def lookup_paged(
        self,
        ids: Sequence[int],
        *,
        session_id: Optional[str] = None,
        alloc: Optional[Any] = None,
        max_seq_len: int = 0,
        need_tokens: int = 0,
        epoch: int = 0,
        count: bool = True,
    ):
        """Paged-tier lookup (batcher slot lock held): live radix chain
        first, then the host tier's contiguous block extension. A host
        hit takes fresh pages, registers the extended chain into the
        live radix (pinned — it outlives the requesting slot) and
        returns ``(node, PendingRestore)``; the device thread must apply
        the record before any dispatch reads those pages (the batcher's
        ``_apply_restores`` drain guarantees it)."""
        index = self.page_index
        if index is None:
            return None, None
        if count:
            # count=False on repeat attempts for the same request — one
            # lookup per request, not per selection cycle.
            global_metrics.inc("engine.kvcache.lookups")
        if self.host is not None:
            self.host.note_session(session_id, ids)
        node = index.match(ids)
        depth = node.depth if node is not None else 0
        if self.host is None or alloc is None:
            if node is not None and count:
                global_metrics.inc("engine.kvcache.hits")
            return node, None
        P = self.page_size
        # Restored chain must leave headroom: at least one tail token
        # inside max_seq (the proper-prefix contract the caller's
        # depth-vs-max_seq check enforces for live chains).
        max_blocks = max((max_seq_len - 1) // P, 0)
        if index.capacity:
            # A chain longer than the index's pinned-page budget would
            # register and immediately re-evict its own tail — wasted
            # copies for KV the next lookup can't see.
            max_blocks = min(max_blocks, depth + index.capacity)
        ents = self.host.extension_blocks(ids, depth, P, max_blocks)
        if ents:
            # Integrity gate per block: the chain must stay contiguous,
            # so the first corrupt link truncates it — blocks past it
            # cannot restore without the dropped one, and the tail
            # re-prefills instead.
            good: List[Any] = []
            for e in ents:
                if self._entry_ok(e):
                    good.append(e)
                else:
                    self.host.take(e.key)
                    break
            ents = good
        total_need = alloc.pages_needed(min(need_tokens, max_seq_len))
        if ents and alloc.free_pages < max(total_need - depth, 0):
            # The request can't admit on this pool state anyway —
            # pinning more pages now would only deepen the blockage.
            ents = []
        if not ents:
            if node is not None and count:
                global_metrics.inc("engine.kvcache.hits")
            return node, None
        t0 = time.perf_counter()
        k = len(ents)
        pages = alloc.take(k)
        if pages is None:
            if node is not None and count:
                global_metrics.inc("engine.kvcache.hits")
            return node, None
        # Chain staging holds the slot lock for the restore_ms wall
        # (host memcpys of landed spill copies + async H2D enqueues) —
        # paid once per resume, never per token, and bounded by
        # max_blocks; the device thread never waits on the transfers
        # themselves.
        hosts = [e.copy.wait() for e in ents]  # landed at spill time
        kb = 1
        while kb < k:
            kb *= 2
        # Blocks concatenate along the token axis, pad to the bucket
        # (padded positions are masked by lengths -> scratch page), then
        # transpose to the admission write's [L, A, T, K, H] layout.
        ks_np = np.concatenate([h[0] for h in hosts], axis=2)
        vs_np = np.concatenate([h[1] for h in hosts], axis=2)
        if kb != k:
            pad = ((0, 0), (0, 0), (0, (kb - k) * P), (0, 0))
            ks_np = np.pad(ks_np, pad)
            vs_np = np.pad(vs_np, pad)
        ks_dev = self._place(
            np.ascontiguousarray(ks_np.transpose(0, 2, 1, 3)[:, None])
        )
        vs_dev = self._place(
            np.ascontiguousarray(vs_np.transpose(0, 2, 1, 3)[:, None])
        )
        table = np.full((1, kb), alloc.sentinel, np.int32)
        table[0, :k] = pages
        rec = PendingRestore(
            epoch, table, ks_dev, vs_dev,
            np.asarray([k * P], np.int32), k * P, list(ents), list(pages),
        )
        # Mark BEFORE registering: the registration's own capacity
        # eviction may pick these pages, and their pool contents are not
        # written until the device thread applies the record.
        self._unwritten.update(pages)
        chain_pages = (
            tuple(node.path_pages) if node is not None else ()
        ) + tuple(pages)
        # The whole chain is protected from the registration's own
        # capacity eviction: evicting the restored pages here would
        # free them while the PendingRestore still targets them AND
        # after their host entries were consumed — the KV would vanish
        # from both tiers. Other chains evict (and spill) normally.
        index.register(
            ids[: (depth + k) * P], chain_pages, alloc,
            protect=frozenset(chain_pages),
        )
        for p in pages:
            alloc.unpin(p)  # drop the transient take() ref; index holds
        for e in ents:
            self.host.take(e.key)
        out = index.match(ids)
        if count:
            global_metrics.inc("engine.kvcache.hits")
        global_metrics.inc("engine.kvcache.host_hits")
        global_metrics.inc("engine.kvcache.restores")
        global_metrics.inc("engine.kvcache.restored_tokens", k * P)
        global_metrics.observe(
            "engine.kvcache.restore_ms", (time.perf_counter() - t0) * 1e3
        )
        return out, rec

    # ------------------------------------------------------------------ #
    # Cross-replica session transfer (ISSUE 11)
    # ------------------------------------------------------------------ #

    def export_session(self, session_id: Optional[str]):
        """Package a session's cached KV lineage in the host tier's
        spill format — the *transfer* format of ISSUE 11: each record is
        exactly what a ``HostTier.put`` accepts, so importing on another
        replica makes the session restorable there with the normal
        resume path (and therefore byte-identical output, per the tier's
        parity contract). Called under the batcher's slot lock.

        Everything is COPIED, never moved: host entries may serve OTHER
        sessions sharing the preamble lineage, and a target-side budget
        rejection must not lose KV from both replicas. Only the session
        PIN leaves the source (``drop_session``), so the source copies
        age out under normal budget pressure once nothing pins them.
        Returns ``{"session_id", "ids", "entries"}`` or None when the
        session has no recorded lineage."""
        if self.host is None:
            return None
        ids = self.host.lineage(session_id)
        if not ids:
            return None
        entries = self._export_entries(ids)
        self.host.drop_session(session_id)
        return {"session_id": session_id, "ids": list(ids),
                "entries": entries}

    def export_request(self, ids, *, session_id: Optional[str] = None):
        """Live-request export for the prefill→decode handoff (ISSUE
        19): same sealed transfer format as :meth:`export_session`, but
        keyed by the request's explicit prompt ids rather than a
        recorded session lineage — a cold prompt that just finished
        prefill has cached KV (the admission-time dense panel or pinned
        page chain) without ever being a sticky session. Copy-only in
        the strictest sense: unlike ``export_session`` no session pin
        leaves this replica, so a handoff that fails downstream leaves
        the source able to serve the colocated fallback from its own
        warm cache. Called under the batcher's slot lock. Returns None
        when nothing covering ``ids`` is cached (the caller falls back
        to colocated serving)."""
        ids = tuple(ids)
        if not ids:
            return None
        entries = self._export_entries(ids)
        if not entries:
            return None
        return {"session_id": session_id, "ids": list(ids),
                "entries": entries}

    def _export_entries(self, ids) -> List[dict]:
        """Collect (COPY) every cached span covering a prefix of
        ``ids``: verified host-tier entries (rot is scrubbed, never
        shipped), the hot dense prefix panel, and the paged prefix
        chain gathered from the live pool — each sealed with an
        integrity frame at pack time. Shared by the session-migration
        and request-handoff exports; caller holds the slot lock."""
        entries: List[dict] = []
        have: set = set()

        def add(key, k_np, v_np, tokens, rows, meta, kind):
            key = tuple(key)
            if key in have or not key:
                return
            have.add(key)
            k_np = np.asarray(k_np)
            v_np = np.asarray(v_np)
            # Integrity frame sealed at pack time: the importer (and
            # the wire layer in between) verifies header + CRC before
            # a single byte lands in its host tier.
            entries.append({
                "key": list(key), "k": k_np, "v": v_np,
                "tokens": int(tokens), "rows": int(rows),
                "meta": meta, "kind": kind,
                "header": entry_header((k_np, v_np), kind),
                "crc": kv_checksum((k_np, v_np)),
            })

        if self.host is not None:
            for e in self.host.prefix_entries(ids):
                # A host entry that no longer verifies must not migrate
                # — exporting rot just moves the fault to another
                # replica.
                if not self._entry_ok(e):
                    self.host.take(e.key)
                    continue
                arrays = (
                    e.copy.wait() if hasattr(e.copy, "wait") else list(e.copy)
                )
                add(e.key, arrays[0], arrays[1], e.tokens, e.rows, e.meta,
                    e.kind)
        store = self.prefix_store
        if store is not None:
            hot = store.match(ids)
            if hot is not None:
                add(hot.ids, hot.ks, hot.vs, len(hot.ids), hot.p_bucket,
                    hot.p_bucket, "dense")
        index = self.page_index
        if index is not None:
            node = index.match(ids)
            if node is not None:
                path = index.path_tokens(node)
                for b, page in enumerate(node.path_pages):
                    key = tuple(path[: (b + 1) * self.page_size])
                    if key in have:
                        continue
                    for _attempt in range(2):
                        # Same donated-buffer race as _spill_page: the
                        # device thread rebinds the pool outside the
                        # slot lock — re-read the binding once.
                        cache = self._get_cache()
                        try:
                            ks, vs = _gather_page(cache, jnp.int32(page))
                            break
                        except Exception:  # noqa: BLE001 — rebind race
                            continue
                    else:
                        continue
                    add(key, ks, vs, self.page_size, self.page_size, b,
                        "page")
        entries.sort(key=lambda e: len(e["key"]))
        return entries

    def import_session(self, export) -> Dict[str, int]:
        """Accept a session export from another replica: every record
        lands in THIS host tier (``count=False`` — migrations are not
        spills in the metrics) and the session pin moves here, so the
        session's next turn restores locally. Returns
        ``{"accepted", "tokens"}`` counting only the entries that
        actually landed — budget pressure may reject some (the resume
        then re-prefills those spans, correct but slower; the source
        still holds its copy), and the metrics must not report KV as
        moved that was dropped.

        Framed entries (``header``/``crc``, sealed at export) verify
        BEFORE landing: a checksum mismatch, an unknown frame version
        or a layout/quant drift (dtype doubles as the quant mode — an
        int8 source migrating into a bf16 target rejects here, not as
        garbage panels at restore) drops that entry, counts
        ``engine.kvcache.integrity_failures`` and rides the
        ``rejected`` count back to the caller."""
        if self.host is None or not export:
            return {"accepted": 0, "tokens": 0, "rejected": 0}
        accepted = 0
        tokens = 0
        rejected = 0
        for e in export.get("entries", ()):
            arrays = (np.asarray(e["k"]), np.asarray(e["v"]))
            framed = e.get("crc") is not None or e.get("header") is not None
            if framed and not frame_ok(e, arrays):
                rejected += 1
                global_metrics.inc("engine.kvcache.integrity_failures")
                continue
            if self.host.put(
                tuple(e["key"]), arrays,
                tokens=e["tokens"], rows=e["rows"], meta=e.get("meta"),
                kind=e.get("kind", "dense"), count=False,
            ):
                accepted += 1
                tokens += int(e["tokens"])
        self.host.note_session(
            export.get("session_id"), tuple(export.get("ids") or ())
        )
        return {"accepted": accepted, "tokens": tokens,
                "rejected": rejected}

    # ------------------------------------------------------------------ #
    # Restore apply (device thread only)
    # ------------------------------------------------------------------ #

    def apply_restores(self, cache, records: List[PendingRestore],
                       epoch: int):
        """Scatter pending restored chains into the page pool (device
        thread; donated jitted write — enqueued, never awaited).
        Stale-epoch records died with their pool (the rebuild cleared
        the live index and replaced the allocator): drop the write and
        hand the consumed host entries back to the cold tier — the
        recovered request's re-admission restores them against the
        fresh pool."""
        for rec in records:
            if rec.epoch != epoch:
                if self.host is not None:
                    for e in rec.entries:
                        self.host.reinsert(e)
                continue
            cache = _restore_write(
                cache, jnp.asarray(rec.table), rec.ks, rec.vs,
                jnp.asarray(rec.lengths),
            )
        return cache

    def mark_written(self, records: List[PendingRestore]) -> None:
        """Lift the unwritten-page spill guard for applied (or dropped
        stale) records — caller holds the batcher slot lock, pairing
        every mutation site of ``_unwritten``. Runs AFTER the pool write
        is enqueued, so device program order guarantees any later spill
        gather reads the restored contents."""
        for rec in records:
            self._unwritten.difference_update(rec.pages)


__all__ = ["KVCacheIndex", "PendingRestore"]
