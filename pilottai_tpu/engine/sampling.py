"""Device-side token sampling: greedy, temperature, top-k, top-p.

Runs inside the jitted decode step (no host round-trip per token).
Per-slot temperature lets one batched decode serve requests with different
sampling settings — agent workloads mix deterministic JSON steps
(temperature 0) with creative generation in the same batch.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp


class SamplingState(NamedTuple):
    """Per-slot sampling parameters living on device."""

    temperature: jax.Array  # [B] fp32; 0 => greedy
    top_k: jax.Array        # [B] int32; 0 => disabled
    top_p: jax.Array        # [B] fp32; 1.0 => disabled
    key: jax.Array          # [B, 2] uint32 per-slot PRNG keys
    eos_id: jax.Array       # [B] int32; -1 => disabled (device EOS detect)
    # JSON grammar automaton coords (engine/json_mask.py); enabled per slot
    # by GenerationParams.json_mode on byte tokenizers.
    json_enabled: jax.Array  # [B] bool
    json_state: jax.Array    # [B] int32
    json_stack: jax.Array    # [B] int32 (container-type bit per level)
    json_depth: jax.Array    # [B] int32
    # Schema-constrained slots (engine/json_schema.py): row into the
    # engine's SchemaBank, -1 = generic JSON automaton. Schema slots
    # reuse ``json_state`` as their DFA state (start = 1, accept = 0).
    json_schema_id: jax.Array  # [B] int32

    @classmethod
    def create(cls, n_slots: int, seed: int = 0) -> "SamplingState":
        keys = jax.random.split(jax.random.PRNGKey(seed), n_slots)
        return cls(
            temperature=jnp.zeros((n_slots,), jnp.float32),
            top_k=jnp.zeros((n_slots,), jnp.int32),
            top_p=jnp.ones((n_slots,), jnp.float32),
            key=keys,
            eos_id=jnp.full((n_slots,), -1, jnp.int32),
            json_enabled=jnp.zeros((n_slots,), bool),
            json_state=jnp.zeros((n_slots,), jnp.int32),
            json_stack=jnp.zeros((n_slots,), jnp.int32),
            json_depth=jnp.zeros((n_slots,), jnp.int32),
            json_schema_id=jnp.full((n_slots,), -1, jnp.int32),
        )


def _mask_top_k(logits: jax.Array, k: jax.Array) -> jax.Array:
    """Per-row top-k mask with traced k (0 disables). [B, V]."""
    V = logits.shape[-1]
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]  # desc
    idx = jnp.clip(k - 1, 0, V - 1)
    kth = jnp.take_along_axis(sorted_logits, idx[:, None], axis=-1)
    keep = (logits >= kth) | (k[:, None] <= 0)
    return jnp.where(keep, logits, -jnp.inf)


def _mask_top_p(logits: jax.Array, p: jax.Array) -> jax.Array:
    """Nucleus mask with traced p (1.0 disables). [B, V]."""
    sort_idx = jnp.argsort(logits, axis=-1)[:, ::-1]
    sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Keep tokens whose cumulative mass (exclusive) is below p.
    keep_sorted = (cum - probs) < p[:, None]
    keep = jnp.zeros_like(keep_sorted).at[
        jnp.arange(logits.shape[0])[:, None], sort_idx
    ].set(keep_sorted)
    return jnp.where(keep | (p[:, None] >= 1.0), logits, -jnp.inf)


def _apply_json_mask(
    logits: jax.Array,
    state: SamplingState,
    remaining: jax.Array | None = None,
    token_tables: tuple[jax.Array, jax.Array] | None = None,
    schema_tables: tuple[jax.Array, jax.Array, jax.Array] | None = None,
) -> jax.Array:
    """Constrain logits of json-enabled slots to grammar-legal tokens.
    ``remaining`` (budget left, [B]) enables forced document closure.
    ``token_tables`` = (token_bytes [Vt, L], token_len [Vt]) switches from
    the byte automaton to the token→byte product (subword vocabs).
    ``schema_tables`` = the SchemaBank's (ALLOWED [N,S,256],
    NEXT [N,S,256], MINCOST [N,S]) — slots with ``json_schema_id >= 0``
    mask against their compiled schema DFA instead of the generic
    grammar (byte tokenizers only; budget feasibility is the exact
    shortest-completion cost)."""
    from pilottai_tpu.engine.json_mask import (
        S_DONE,
        json_allowed_bytes,
        json_allowed_tokens,
    )

    B, V = logits.shape
    if token_tables is not None:
        tb, tl = token_tables
        tok_ok = json_allowed_tokens(
            state.json_state, state.json_stack, state.json_depth,
            tb, tl, remaining,
        )                                               # [B, Vt]
        full = jnp.zeros((B, V), bool).at[:, : tb.shape[0]].set(
            tok_ok[:, :V]
        )
    else:
        byte_ok = json_allowed_bytes(
            state.json_state, state.json_stack, state.json_depth, remaining
        )                                               # [B, 256]
        full = jnp.zeros((B, V), bool).at[:, :256].set(byte_ok[:, :V])
    schema_slot = state.json_schema_id >= 0
    if schema_tables is not None and token_tables is None:
        s_allowed, s_next, s_cost = schema_tables
        sid = jnp.clip(state.json_schema_id, 0, s_allowed.shape[0] - 1)
        st = state.json_state
        ok = s_allowed[sid, st]                          # [B, 256]
        nxt = s_next[sid, st]                            # [B, 256]
        cost = s_cost[sid[:, None], nxt]                 # [B, 256]
        if remaining is not None:
            ok = ok & (cost <= remaining[:, None] - 1)
        s_full = jnp.zeros((B, V), bool).at[:, :256].set(ok[:, :V])
        full = jnp.where(schema_slot[:, None], s_full, full)
        done = jnp.where(schema_slot, st == 0, state.json_state == S_DONE)
    else:
        done = state.json_state == S_DONE
    # Document closed: force EOS when the slot has one (else pad spaces).
    eos_ok = done & (state.eos_id >= 0)
    eos_onehot = jax.nn.one_hot(
        jnp.clip(state.eos_id, 0, V - 1), V, dtype=bool
    )
    full = jnp.where(eos_ok[:, None], eos_onehot, full)
    # Empty-mask fallback (token mode under an infeasible budget / odd
    # vocab): an all-False row would argmax to pad-token garbage forever.
    # Degrade the way the byte path's budget-exhaustion does: end the
    # generation (EOS) when the slot has one, else sample unconstrained.
    empty = ~full.any(axis=-1)
    full = jnp.where(
        (empty & (state.eos_id >= 0))[:, None], eos_onehot, full
    )
    full = full | (empty & (state.eos_id < 0))[:, None]
    masked = jnp.where(full, logits, -2.0**30)
    return jnp.where(state.json_enabled[:, None], masked, logits)


def _advance_json(
    state: SamplingState,
    tokens: jax.Array,
    token_tables: tuple[jax.Array, jax.Array] | None = None,
    schema_tables: tuple[jax.Array, jax.Array, jax.Array] | None = None,
) -> SamplingState:
    from pilottai_tpu.engine.json_mask import (
        json_advance,
        json_advance_tokens,
    )

    if token_tables is not None:
        ns, stack, depth = json_advance_tokens(
            state.json_state, state.json_stack, state.json_depth, tokens,
            *token_tables,
        )
    else:
        ns, stack, depth = json_advance(
            state.json_state, state.json_stack, state.json_depth, tokens
        )
    if schema_tables is not None and token_tables is None:
        _, s_next, _ = schema_tables
        sid = jnp.clip(state.json_schema_id, 0, s_next.shape[0] - 1)
        byte = jnp.clip(tokens, 0, 255)
        s_ns = s_next[sid, state.json_state, byte]
        # Non-byte tokens (EOS/specials) don't advance the DFA.
        s_ns = jnp.where(tokens < 256, s_ns, state.json_state)
        schema_slot = state.json_schema_id >= 0
        ns = jnp.where(schema_slot, s_ns, ns)
        stack = jnp.where(schema_slot, state.json_stack, stack)
        depth = jnp.where(schema_slot, state.json_depth, depth)
    en = state.json_enabled
    return state._replace(
        json_state=jnp.where(en, ns, state.json_state),
        json_stack=jnp.where(en, stack, state.json_stack),
        json_depth=jnp.where(en, depth, state.json_depth),
    )


def fused_verify_rows(
    logits: jax.Array,        # [B, D-1, V] verify rows 1..D-1 of a block
    draft_tokens: jax.Array,  # [B, D-1] the draft path those rows follow
    state: SamplingState,     # coords BEFORE the block's row-0 sample
    budget: jax.Array,        # [B] remaining budget entering the block
    token_tables: tuple[jax.Array, jax.Array] | None = None,
    schema_tables: tuple[jax.Array, jax.Array, jax.Array] | None = None,
) -> jax.Array:
    """Masked-greedy verify rows for one speculative block as ONE
    vectorized mask+argmax over all D-1 rows.

    Byte-identical to the per-row loop it replaces (advance coords by
    draft token j, mask row j with ``remaining = budget - j``, argmax):
    the JSON-coordinate chain — a few [B] table lookups per row, cheap
    and inherently sequential — still walks the draft path row by row,
    but the expensive part (the [B, V] grammar/schema mask build and
    the argmax, previously one dispatch per row) flattens the (slot,
    row) pair into the batch axis and runs once per block. At D=6 that
    cuts five mask+argmax dispatches per verify block to one — the
    small-op sampler floor the r6 profile measured at ~2.3 ms/block.

    Returns the greedy rows ``[B, D-1] int32``."""
    B, Dm1, V = logits.shape
    states, stacks, depths = [], [], []
    coords = state
    for j in range(Dm1):
        coords = _advance_json(
            coords, draft_tokens[:, j], token_tables, schema_tables
        )
        states.append(coords.json_state)
        stacks.append(coords.json_stack)
        depths.append(coords.json_depth)
    # Flatten (b, j) row-major to match logits.reshape(B * Dm1, V).
    flat = state._replace(
        json_state=jnp.stack(states, axis=1).reshape(-1),
        json_stack=jnp.stack(stacks, axis=1).reshape(-1),
        json_depth=jnp.stack(depths, axis=1).reshape(-1),
        json_enabled=jnp.repeat(state.json_enabled, Dm1),
        json_schema_id=jnp.repeat(state.json_schema_id, Dm1),
        eos_id=jnp.repeat(state.eos_id, Dm1),
    )
    remaining = (
        budget[:, None] - (jnp.arange(Dm1, dtype=budget.dtype)[None, :] + 1)
    ).reshape(-1)
    masked = _apply_json_mask(
        logits.reshape(B * Dm1, V), flat, remaining,
        token_tables, schema_tables,
    )
    return jnp.argmax(masked, axis=-1).astype(jnp.int32).reshape(B, Dm1)


def split_step_keys(keys: jax.Array) -> tuple[jax.Array, jax.Array]:
    """One sampling step's PRNG advance: per-slot ``(step_keys,
    carry_keys)`` from ``[B, 2]`` keys. THE key-split scheme — shared by
    ``sample_core`` and the fused greedy epilogue
    (engine/decode.py:_advance_keys), whose bit-identity contract
    requires both paths to advance keys identically; change it here or
    nowhere."""
    new_keys = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
    return new_keys[:, 0], new_keys[:, 1]


def sample_core(
    logits: jax.Array,  # [B, V] fp32
    state: SamplingState,
    json_remaining: jax.Array | None = None,  # [B] budget incl. this token
    json_token_tables: tuple[jax.Array, jax.Array] | None = None,
    json_schema_tables: tuple[jax.Array, jax.Array, jax.Array] | None = None,
) -> tuple[jax.Array, SamplingState]:
    """Sample one token per slot; greedy where temperature == 0.

    Plain function (no jit) so the decode chunk can inline it inside its
    step scan; ``sample_tokens`` is the standalone jitted wrapper."""
    logits = _apply_json_mask(
        logits, state, json_remaining, json_token_tables, json_schema_tables
    )
    greedy = jnp.argmax(logits, axis=-1)

    temp = jnp.maximum(state.temperature, 1e-6)[:, None]
    scaled = logits / temp
    scaled = _mask_top_k(scaled, state.top_k)
    scaled = _mask_top_p(scaled, state.top_p)

    def sample_row(key, row):
        return jax.random.categorical(key, row)

    step_keys, carry_keys = split_step_keys(state.key)
    sampled = jax.vmap(sample_row)(step_keys, scaled)

    tokens = jnp.where(state.temperature <= 0.0, greedy, sampled).astype(
        jnp.int32
    )
    state = _advance_json(
        state._replace(key=carry_keys), tokens, json_token_tables,
        json_schema_tables,
    )
    return tokens, state


@partial(jax.jit, donate_argnames=("state",))
def sample_tokens(
    logits: jax.Array,  # [B, V] fp32
    state: SamplingState,
) -> tuple[jax.Array, SamplingState]:
    return sample_core(logits, state)


def update_slot(
    state: SamplingState,
    slot: int | jax.Array,
    temperature: float,
    top_k: int,
    top_p: float,
    seed: int,
    eos_id: int = -1,
    json_mode: bool = False,
    json_schema_id: int = -1,
) -> SamplingState:
    """Host-side admission: install one request's sampling params."""
    return state._replace(
        temperature=state.temperature.at[slot].set(temperature),
        top_k=state.top_k.at[slot].set(top_k),
        top_p=state.top_p.at[slot].set(top_p),
        key=state.key.at[slot].set(jax.random.PRNGKey(seed)[None][0]),
        eos_id=state.eos_id.at[slot].set(eos_id),
        json_enabled=state.json_enabled.at[slot].set(json_mode),
        # Schema DFAs start at state 1 (engine/json_schema.py:START);
        # the generic automaton at 0.
        json_state=state.json_state.at[slot].set(
            1 if json_schema_id >= 0 else 0
        ),
        json_stack=state.json_stack.at[slot].set(0),
        json_depth=state.json_depth.at[slot].set(0),
        json_schema_id=state.json_schema_id.at[slot].set(json_schema_id),
    )


@partial(jax.jit, donate_argnames=("state",))
def admit_sampling(
    state: SamplingState,
    slots: jax.Array,        # [A] int32; out-of-range rows are dropped
    temperature: jax.Array,  # [A] fp32
    top_k: jax.Array,        # [A] int32
    top_p: jax.Array,        # [A] fp32
    seeds: jax.Array,        # [A] int32
    eos_id: jax.Array,       # [A] int32
    json_mode: jax.Array,    # [A] bool — grammar-constrained decoding
    schema_ids: jax.Array | None = None,  # [A] int32; -1 = generic
) -> SamplingState:
    """Batched admission: install a group of requests' sampling params."""
    keys = jax.vmap(jax.random.PRNGKey)(seeds)
    zeros = jnp.zeros_like(slots)
    if schema_ids is None:
        schema_ids = jnp.full_like(slots, -1)
    # Schema DFAs start at state 1 (engine/json_schema.py:START).
    init_state = jnp.where(schema_ids >= 0, 1, 0).astype(jnp.int32)
    return state._replace(
        temperature=state.temperature.at[slots].set(temperature, mode="drop"),
        top_k=state.top_k.at[slots].set(top_k, mode="drop"),
        top_p=state.top_p.at[slots].set(top_p, mode="drop"),
        key=state.key.at[slots].set(keys, mode="drop"),
        eos_id=state.eos_id.at[slots].set(eos_id, mode="drop"),
        json_enabled=state.json_enabled.at[slots].set(json_mode, mode="drop"),
        json_state=state.json_state.at[slots].set(init_state, mode="drop"),
        json_stack=state.json_stack.at[slots].set(zeros, mode="drop"),
        json_depth=state.json_depth.at[slots].set(zeros, mode="drop"),
        json_schema_id=state.json_schema_id.at[slots].set(
            schema_ids, mode="drop"
        ),
    )
