"""Device-side token sampling: greedy, temperature, top-k, top-p.

Runs inside the jitted decode step (no host round-trip per token).
Per-slot temperature lets one batched decode serve requests with different
sampling settings — agent workloads mix deterministic JSON steps
(temperature 0) with creative generation in the same batch.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp


class SamplingState(NamedTuple):
    """Per-slot sampling parameters living on device."""

    temperature: jax.Array  # [B] fp32; 0 => greedy
    top_k: jax.Array        # [B] int32; 0 => disabled
    top_p: jax.Array        # [B] fp32; 1.0 => disabled
    key: jax.Array          # [B, 2] uint32 per-slot PRNG keys

    @classmethod
    def create(cls, n_slots: int, seed: int = 0) -> "SamplingState":
        keys = jax.random.split(jax.random.PRNGKey(seed), n_slots)
        return cls(
            temperature=jnp.zeros((n_slots,), jnp.float32),
            top_k=jnp.zeros((n_slots,), jnp.int32),
            top_p=jnp.ones((n_slots,), jnp.float32),
            key=keys,
        )


def _mask_top_k(logits: jax.Array, k: jax.Array) -> jax.Array:
    """Per-row top-k mask with traced k (0 disables). [B, V]."""
    V = logits.shape[-1]
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]  # desc
    idx = jnp.clip(k - 1, 0, V - 1)
    kth = jnp.take_along_axis(sorted_logits, idx[:, None], axis=-1)
    keep = (logits >= kth) | (k[:, None] <= 0)
    return jnp.where(keep, logits, -jnp.inf)


def _mask_top_p(logits: jax.Array, p: jax.Array) -> jax.Array:
    """Nucleus mask with traced p (1.0 disables). [B, V]."""
    sort_idx = jnp.argsort(logits, axis=-1)[:, ::-1]
    sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Keep tokens whose cumulative mass (exclusive) is below p.
    keep_sorted = (cum - probs) < p[:, None]
    keep = jnp.zeros_like(keep_sorted).at[
        jnp.arange(logits.shape[0])[:, None], sort_idx
    ].set(keep_sorted)
    return jnp.where(keep | (p[:, None] >= 1.0), logits, -jnp.inf)


@partial(jax.jit, donate_argnames=("state",))
def sample_tokens(
    logits: jax.Array,  # [B, V] fp32
    state: SamplingState,
) -> tuple[jax.Array, SamplingState]:
    """Sample one token per slot; greedy where temperature == 0."""
    B = logits.shape[0]
    greedy = jnp.argmax(logits, axis=-1)

    temp = jnp.maximum(state.temperature, 1e-6)[:, None]
    scaled = logits / temp
    scaled = _mask_top_k(scaled, state.top_k)
    scaled = _mask_top_p(scaled, state.top_p)

    def sample_row(key, row):
        return jax.random.categorical(key, row)

    new_keys = jax.vmap(lambda k: jax.random.split(k, 2))(state.key)
    step_keys, carry_keys = new_keys[:, 0], new_keys[:, 1]
    sampled = jax.vmap(sample_row)(step_keys, scaled)

    tokens = jnp.where(state.temperature <= 0.0, greedy, sampled)
    del B
    return tokens.astype(jnp.int32), state._replace(key=carry_keys)


def update_slot(
    state: SamplingState,
    slot: int | jax.Array,
    temperature: float,
    top_k: int,
    top_p: float,
    seed: int,
) -> SamplingState:
    """Host-side admission: install one request's sampling params."""
    return SamplingState(
        temperature=state.temperature.at[slot].set(temperature),
        top_k=state.top_k.at[slot].set(top_k),
        top_p=state.top_p.at[slot].set(top_p),
        key=state.key.at[slot].set(jax.random.PRNGKey(seed)[None][0]),
    )
