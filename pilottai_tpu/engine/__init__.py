"""LLM engine: the only inference path in the framework.

Reference parity: ``pilott/engine/llm.py`` — but instead of delegating to
remote HTTP APIs via litellm, providers here are in-tree:

* ``"tpu"`` — JAX/XLA engine serving Llama/Gemma on TPU (continuous
  batching over a device thread, pjit-sharded weights).
* ``"cpu"`` — identical engine on host JAX devices (CI path).
* ``"mock"`` — deterministic scripted backend speaking the framework's
  structured-JSON prompt protocol (the first-class test fixture the
  reference never had, SURVEY.md §4).
"""

from pilottai_tpu.engine.types import (
    ChatMessage,
    GenerationParams,
    LLMResponse,
    ToolCall,
    ToolSpec,
)
from pilottai_tpu.engine.base import LLMBackend
from pilottai_tpu.engine.handler import LLMHandler, create_backend, register_backend

__all__ = [
    "ChatMessage",
    "GenerationParams",
    "LLMResponse",
    "ToolCall",
    "ToolSpec",
    "LLMBackend",
    "LLMHandler",
    "create_backend",
    "register_backend",
]
