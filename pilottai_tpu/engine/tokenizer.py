"""Tokenizers for the in-tree engine.

Two implementations:

* ``ByteTokenizer`` — dependency-free byte-level tokenizer (tokens 0-255 are
  raw bytes, specials above). Default for tests, randomly-initialized
  models and the benchmark; needs no downloaded vocab files (this image has
  zero network egress).
* ``HFTokenizer`` — wraps a *locally available* Hugging Face tokenizer for
  real checkpoints (gated on files existing; never downloads).

The reference has no tokenizer at all (tokenization happened inside remote
APIs, ``pilott/engine/llm.py``); this is new TPU-native surface.
"""

from __future__ import annotations

import abc
from pathlib import Path
from typing import List, Optional, Sequence


class Tokenizer(abc.ABC):
    pad_id: int
    bos_id: int
    eos_id: int
    vocab_size: int

    @abc.abstractmethod
    def encode(self, text: str, add_bos: bool = True) -> List[int]: ...

    @abc.abstractmethod
    def decode(self, ids: Sequence[int]) -> str: ...

    def token_bytes(self, i: int) -> Optional[bytes]:
        """The exact byte string token ``i`` contributes to decoded text,
        or None when it has none / it can't be derived (specials, partial
        UTF-8 pieces). Powers the JSON grammar mask's token→byte product
        (engine/json_mask.py:token_byte_table)."""
        return None

    def render_chat(self, messages) -> Optional[str]:
        """Model-specific chat rendering for ``[{role, content}, ...]``,
        or None when the tokenizer has no template — the engine then
        falls back to the generic ``<|role|>`` transcript
        (engine/base.py:render_chat). Real checkpoints care: a Llama-3
        instruct model fine-tuned on its header format produces garbage
        on any other framing."""
        return None


class ByteTokenizer(Tokenizer):
    """Byte-level tokenizer: ids 0..255 are raw bytes; specials follow.

    vocab_size is padded to a multiple of 128 (lane width) so the embedding
    and logits matmuls tile cleanly onto the MXU.
    """

    BYTE_VOCAB = 256

    def __init__(self, n_extra_specials: int = 0) -> None:
        self.pad_id = self.BYTE_VOCAB + 0
        self.bos_id = self.BYTE_VOCAB + 1
        self.eos_id = self.BYTE_VOCAB + 2
        base = self.BYTE_VOCAB + 3 + n_extra_specials
        self.vocab_size = ((base + 127) // 128) * 128

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = list(text.encode("utf-8", errors="replace"))
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < self.BYTE_VOCAB)
        return data.decode("utf-8", errors="replace")

    def token_bytes(self, i: int) -> Optional[bytes]:
        return bytes([i]) if 0 <= i < self.BYTE_VOCAB else None


class HFTokenizer(Tokenizer):
    """Local Hugging Face tokenizer wrapper (no downloads)."""

    def __init__(self, path: str | Path) -> None:
        path = Path(path)
        if not path.exists():
            raise FileNotFoundError(
                f"tokenizer path {path} does not exist (no network egress; "
                "tokenizer files must be local)"
            )
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(str(path), local_files_only=True)
        self.vocab_size = len(self._tok)
        self.pad_id = self._tok.pad_token_id or 0
        self.bos_id = self._tok.bos_token_id or 1
        self.eos_id = self._tok.eos_token_id or 2
        self._special_ids = set(self._tok.all_special_ids or [])
        # Anchor for token_bytes: a plain ascii token with an unambiguous
        # decode (see token_bytes). Candidates cover code/text vocabs;
        # without one the derivation would LIE for word-initial pieces
        # (decode-alone strips SentencePiece space markers), so we give up
        # and token_bytes returns None for everything — the engine then
        # falls back to unconstrained sampling rather than masking against
        # wrong byte strings.
        self._anchor = None
        for cand in (")", "0", "a", "."):
            aid = self._tok.encode(cand, add_special_tokens=False)
            if len(aid) == 1:
                self._anchor = (
                    aid[0],
                    self._tok.decode(
                        [aid[0]], skip_special_tokens=False,
                        clean_up_tokenization_spaces=False,
                    ),
                )
                break

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        # No cleanup: emitted text must equal the concatenation of
        # token_bytes, or grammar-masked output could be silently edited
        # after the automaton validated it (e.g. ' ,' → ',').
        return self._tok.decode(
            list(ids), skip_special_tokens=True,
            clean_up_tokenization_spaces=False,
        )

    def render_chat(self, messages) -> Optional[str]:
        """Apply the checkpoint's own chat template when it ships one
        (``tokenizer_config.json``'s ``chat_template``). Returns the
        rendered PROMPT (generation prompt appended) as text — encode()
        then tokenizes it like any other prompt. None when the local
        tokenizer has no template or rendering fails (never guess a
        format for an instruct model)."""
        if not getattr(self._tok, "chat_template", None):
            return None
        try:
            return self._tok.apply_chat_template(
                [
                    {"role": m.get("role", "user"),
                     "content": m.get("content", "")}
                    for m in messages
                ],
                tokenize=False,
                add_generation_prompt=True,
            )
        except Exception:  # noqa: BLE001 — fall back to generic framing
            return None

    def token_bytes(self, i: int) -> Optional[bytes]:
        """Derive token i's decoded byte string by anchored difference:
        decode(anchor + token) minus decode(anchor). The anchor sidesteps
        leading-space normalization (SentencePiece strips a word-initial
        marker at text start, so decoding the token alone would lie about
        its bytes). Tokens that aren't self-contained text (specials,
        partial UTF-8 sequences → U+FFFD) return None — the JSON grammar
        only emits printable ASCII, so excluding them costs nothing."""
        if i in self._special_ids or self._anchor is None:
            return None
        anchor, anchor_text = self._anchor
        # clean_up_tokenization_spaces collapses e.g. ') ,' to '),' —
        # space+punctuation tokens would lose their leading space and the
        # JSON automaton's view of the byte stream would silently diverge
        # from emitted text (advisor r3).
        joined = self._tok.decode(
            [anchor, i], skip_special_tokens=False,
            clean_up_tokenization_spaces=False,
        )
        if not joined.startswith(anchor_text):
            return None
        piece = joined[len(anchor_text):]
        if not piece or "�" in piece:
            return None
        try:
            return piece.encode("ascii")
        except UnicodeEncodeError:
            return None


class IncrementalDecoder:
    """Streaming detokenizer: ``push`` token ids as they arrive, get back
    text deltas whose concatenation equals ``decode(all_ids)``.

    Each push re-decodes the accumulated ids and emits the new suffix —
    O(n²) over a response, irrelevant at agent-step lengths (≤ a few
    hundred tokens) and the only strategy that is correct for ANY
    tokenizer (subword merges can only be rendered once their
    neighbours exist). Two holdbacks keep deltas append-only:

    * a trailing U+FFFD is withheld — it is how a partial multi-byte
      UTF-8 sequence renders before the next token completes it;
    * if a new decode does NOT extend what was already emitted (a
      tokenizer whose decode is not prefix-monotonic), the divergent
      text is withheld until ``flush`` rather than emitted twice.
    """

    def __init__(self, tokenizer: Tokenizer) -> None:
        self._tok = tokenizer
        self._ids: List[int] = []
        self._emitted = ""

    def push(self, ids: Sequence[int]) -> str:
        self._ids.extend(ids)
        text = self._tok.decode(self._ids)
        if not text.startswith(self._emitted):
            return ""  # non-monotonic decode: defer to flush
        safe = len(text)
        while safe > len(self._emitted) and text[safe - 1] == "�":
            safe -= 1
        delta = text[len(self._emitted):safe]
        self._emitted += delta
        return delta

    def flush(self) -> str:
        """Emit everything still held back (stream end). After a
        non-monotonic divergence the delta resumes from the longest
        common prefix — the stream differs from ``decode(all)`` only
        inside the divergent span, never by duplication."""
        text = self._tok.decode(self._ids)
        p = 0
        limit = min(len(text), len(self._emitted))
        while p < limit and text[p] == self._emitted[p]:
            p += 1
        delta = text[p:] if p < len(self._emitted) else text[len(self._emitted):]
        self._emitted += delta
        return delta

    @property
    def text(self) -> str:
        return self._emitted


def load_tokenizer(path: Optional[str] = None) -> Tokenizer:
    if path:
        return HFTokenizer(path)
    return ByteTokenizer()
