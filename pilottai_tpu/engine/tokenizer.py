"""Tokenizers for the in-tree engine.

Two implementations:

* ``ByteTokenizer`` — dependency-free byte-level tokenizer (tokens 0-255 are
  raw bytes, specials above). Default for tests, randomly-initialized
  models and the benchmark; needs no downloaded vocab files (this image has
  zero network egress).
* ``HFTokenizer`` — wraps a *locally available* Hugging Face tokenizer for
  real checkpoints (gated on files existing; never downloads).

The reference has no tokenizer at all (tokenization happened inside remote
APIs, ``pilott/engine/llm.py``); this is new TPU-native surface.
"""

from __future__ import annotations

import abc
from pathlib import Path
from typing import List, Optional, Sequence


class Tokenizer(abc.ABC):
    pad_id: int
    bos_id: int
    eos_id: int
    vocab_size: int

    @abc.abstractmethod
    def encode(self, text: str, add_bos: bool = True) -> List[int]: ...

    @abc.abstractmethod
    def decode(self, ids: Sequence[int]) -> str: ...


class ByteTokenizer(Tokenizer):
    """Byte-level tokenizer: ids 0..255 are raw bytes; specials follow.

    vocab_size is padded to a multiple of 128 (lane width) so the embedding
    and logits matmuls tile cleanly onto the MXU.
    """

    BYTE_VOCAB = 256

    def __init__(self, n_extra_specials: int = 0) -> None:
        self.pad_id = self.BYTE_VOCAB + 0
        self.bos_id = self.BYTE_VOCAB + 1
        self.eos_id = self.BYTE_VOCAB + 2
        base = self.BYTE_VOCAB + 3 + n_extra_specials
        self.vocab_size = ((base + 127) // 128) * 128

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = list(text.encode("utf-8", errors="replace"))
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < self.BYTE_VOCAB)
        return data.decode("utf-8", errors="replace")


class HFTokenizer(Tokenizer):
    """Local Hugging Face tokenizer wrapper (no downloads)."""

    def __init__(self, path: str | Path) -> None:
        path = Path(path)
        if not path.exists():
            raise FileNotFoundError(
                f"tokenizer path {path} does not exist (no network egress; "
                "tokenizer files must be local)"
            )
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(str(path), local_files_only=True)
        self.vocab_size = len(self._tok)
        self.pad_id = self._tok.pad_token_id or 0
        self.bos_id = self._tok.bos_token_id or 1
        self.eos_id = self._tok.eos_token_id or 2

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)


def load_tokenizer(path: Optional[str] = None) -> Tokenizer:
    if path:
        return HFTokenizer(path)
    return ByteTokenizer()
