"""Engine wire types: messages, tool specs, generation parameters, responses.

Reference parity: the dict shapes flowing through
``pilott/engine/llm.py:91-120`` (OpenAI-style messages/tools in, normalized
{content, role, tool_calls, model, usage} out) — typed here instead of
ad-hoc dicts.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Literal, Optional

from pydantic import BaseModel, Field

Role = Literal["system", "user", "assistant", "tool"]


class ChatMessage(BaseModel):
    role: Role = "user"
    content: str = ""
    name: Optional[str] = None
    tool_call_id: Optional[str] = None

    @classmethod
    def coerce(cls, value: Any) -> "ChatMessage":
        if isinstance(value, ChatMessage):
            return value
        if isinstance(value, dict):
            return cls(**value)
        return cls(role="user", content=str(value))


class ToolSpec(BaseModel):
    """Function-calling tool description (reference ``llm.py:91-104``)."""

    name: str
    description: str = ""
    parameters: Dict[str, Any] = Field(default_factory=dict)  # JSON schema

    def to_openai(self) -> Dict[str, Any]:
        return {
            "type": "function",
            "function": {
                "name": self.name,
                "description": self.description,
                "parameters": self.parameters or {"type": "object", "properties": {}},
            },
        }


class ToolCall(BaseModel):
    id: str = ""
    name: str
    arguments: Dict[str, Any] = Field(default_factory=dict)


class GenerationParams(BaseModel):
    """Per-request decode parameters (overrides the engine defaults)."""

    max_new_tokens: int = 256
    temperature: float = 0.7
    top_k: int = 0
    top_p: float = 1.0
    seed: Optional[int] = None
    stop: List[str] = Field(default_factory=list)
    json_mode: bool = False
    # Schema-constrained decoding (engine/json_schema.py): a JSON Schema
    # dict the output must match exactly — OpenAI's response_format
    # json_schema. Byte-tokenizer engines enforce it by construction;
    # unsupported schemas / subword vocabs degrade to generic json_mode.
    json_schema: Optional[Dict[str, Any]] = None
    # End-to-end request deadline: ABSOLUTE ``time.monotonic()`` time (not
    # a relative budget — a deadline survives queueing and retries without
    # re-arming). Set by the HTTP edge from ``timeout``/``x-request-timeout``
    # (reliability.deadline_from_timeout); every layer that can spend time
    # (handler retry loop, batcher admission and decode) checks it and
    # fails with reliability.DeadlineExceeded when it passes. None = no
    # deadline (the seed behavior).
    deadline: Optional[float] = None


class Usage(BaseModel):
    prompt_tokens: int = 0
    completion_tokens: int = 0

    @property
    def total_tokens(self) -> int:
        return self.prompt_tokens + self.completion_tokens


class LLMResponse(BaseModel):
    """Normalized engine response (reference ``llm.py:106-120``)."""

    content: str = ""
    role: Role = "assistant"
    tool_calls: List[ToolCall] = Field(default_factory=list)
    model: str = ""
    usage: Usage = Field(default_factory=Usage)
    finish_reason: str = "stop"
    latency: float = 0.0
    created_at: float = Field(default_factory=time.time)
    # Tri-state: None = no json_schema was requested; True = the output
    # was DFA-constrained to the requested schema; False = the request
    # asked for a schema but the engine degraded to the generic JSON
    # grammar (unsupported schema, full bank, subword vocab) — callers
    # (the HTTP server) surface this instead of silently claiming
    # enforcement.
    schema_enforced: Optional[bool] = None

    def to_dict(self) -> Dict[str, Any]:
        return self.model_dump()
