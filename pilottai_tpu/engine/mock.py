"""Deterministic mock backend speaking the framework's JSON prompt protocol.

SURVEY.md §4: "the TPU build should make the fake LLM backend a first-class
test fixture (a provider=\"mock\" engine — also BASELINE.json config #1)".
The reference has no fake backend at all, which is why its agent reasoning
loop is untested.

The mock recognizes which rules.yaml template produced a prompt (by the JSON
contract fields the template demands) and returns a well-formed response, so
the full orchestrator → agent → engine loop runs without a model. Scripted
overrides allow tests to force specific behaviors.
"""

from __future__ import annotations

import asyncio
import json
import re
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from pilottai_tpu.engine.base import LLMBackend, parse_tool_calls
from pilottai_tpu.engine.types import (
    ChatMessage,
    GenerationParams,
    LLMResponse,
    ToolSpec,
    Usage,
)

Responder = Callable[[str], Optional[Dict[str, Any]]]


class MockBackend(LLMBackend):
    """Protocol-aware deterministic backend.

    Args:
        script: optional list of raw response strings consumed in order
            (takes precedence over protocol detection).
        responders: optional list of callables ``prompt -> dict | None``
            tried before the built-in protocol detection.
        latency: artificial per-call latency in seconds (for scheduler and
            load-balancer tests).
        steps_to_complete: how many ``step_planning`` rounds an agent takes
            before the mock declares ``task_complete``.
        fail_pattern: prompts matching this regex raise RuntimeError (for
            fault-tolerance tests).
    """

    name = "mock"

    def __init__(
        self,
        script: Optional[List[str]] = None,
        responders: Optional[List[Responder]] = None,
        latency: float = 0.0,
        steps_to_complete: int = 1,
        fail_pattern: Optional[str] = None,
        model_name: str = "mock-1",
    ) -> None:
        self._script = list(script or [])
        self._responders = list(responders or [])
        self.latency = latency
        self.steps_to_complete = steps_to_complete
        self._fail_re = re.compile(fail_pattern) if fail_pattern else None
        self.model_name = model_name
        self.calls: List[str] = []  # full prompt log for assertions
        self._step_counts: Dict[str, int] = {}
        self._lock = asyncio.Lock()

    # ------------------------------------------------------------------ #

    async def generate(
        self,
        messages: Sequence[ChatMessage],
        tools: Optional[Sequence[ToolSpec]] = None,
        params: Optional[GenerationParams] = None,
    ) -> LLMResponse:
        start = time.perf_counter()
        prompt = "\n".join(m.content for m in messages)
        async with self._lock:
            self.calls.append(prompt)
            if self._fail_re and self._fail_re.search(prompt):
                raise RuntimeError(f"mock backend failure injected for: {self._fail_re.pattern}")
            if self._script:
                content = self._script.pop(0)
            else:
                payload = self._respond(prompt, tools)
                content = json.dumps(payload) if isinstance(payload, dict) else str(payload)
        if self.latency:
            await asyncio.sleep(self.latency)
        tool_calls = parse_tool_calls(
            content, [t.name for t in tools] if tools else []
        )
        return LLMResponse(
            content=content,
            tool_calls=tool_calls,
            model=self.model_name,
            usage=Usage(
                prompt_tokens=len(prompt) // 4, completion_tokens=len(content) // 4
            ),
            latency=time.perf_counter() - start,
        )

    async def generate_stream(
        self,
        messages: Sequence[ChatMessage],
        tools: Optional[Sequence[ToolSpec]] = None,
        params: Optional[GenerationParams] = None,
        info: Optional[Dict[str, Any]] = None,
    ):
        """Word-granular streaming (whitespace kept on the leading word)
        so consumer tests see real multi-delta behavior."""
        response = await self.generate(messages, tools, params)
        if info is not None:
            info["finish_reason"] = response.finish_reason
            info["completion_tokens"] = response.usage.completion_tokens
        content = response.content
        pos = 0
        while pos < len(content):
            nxt = content.find(" ", pos + 1)
            nxt = len(content) if nxt < 0 else nxt
            yield content[pos:nxt]
            pos = nxt
            if self.latency:
                await asyncio.sleep(self.latency / max(len(content), 1))

    # ------------------------------------------------------------------ #
    # Protocol detection — keyed on the JSON contract fields each
    # rules.yaml template demands (pilottai_tpu/prompts/rules.yaml).
    # ------------------------------------------------------------------ #

    def _respond(self, prompt: str, tools: Optional[Sequence[ToolSpec]]) -> Dict[str, Any]:
        for responder in self._responders:
            out = responder(prompt)
            if out is not None:
                return out

        if '"requires_decomposition"' in prompt:
            return {
                "requires_decomposition": False,
                "complexity": 2,
                "estimated_resources": {"agents": 1, "llm_calls": 4},
                "reasoning": "simple task",
            }
        if '"subtasks"' in prompt:
            return {
                "subtasks": [
                    {"description": "extract the content", "type": "extract",
                     "priority": "normal", "depends_on": []},
                    {"description": "analyze the content", "type": "analyze",
                     "priority": "normal", "depends_on": [0]},
                    {"description": "summarize the findings", "type": "summarize",
                     "priority": "normal", "depends_on": [1]},
                ]
            }
        if '"selected_tools"' in prompt:
            names = [t.name for t in tools] if tools else []
            listed = re.findall(r"^\s*([a-zA-Z0-9_\-]+):", prompt, re.MULTILINE)
            return {"selected_tools": names or listed[:1], "reasoning": "best fit"}
        if '"task_complete"' in prompt:
            key = self._task_key(prompt)
            count = self._step_counts.get(key, 0) + 1
            self._step_counts[key] = count
            if count >= self.steps_to_complete:
                return {
                    "task_complete": True,
                    "action": "respond",
                    "arguments": {},
                    "output": f"completed after {count} step(s)",
                    "reasoning": "work finished",
                }
            return {
                "task_complete": False,
                "action": "respond",
                "arguments": {},
                "output": f"intermediate result {count}",
                "reasoning": "more work needed",
            }
        if '"quality"' in prompt and '"requires_retry"' in prompt:
            return {"quality": 0.9, "requires_retry": False, "feedback": ""}
        if '"success"' in prompt and '"quality"' in prompt:
            return {"success": True, "quality": 0.9, "issues": [], "suggestions": []}
        if '"understanding"' in prompt:
            return {
                "understanding": "task understood",
                "approach": "direct execution",
                "estimated_steps": 1,
                "risks": [],
            }
        if '"agent_id"' in prompt:
            ids = re.findall(r"^\s*([a-zA-Z0-9\-]{4,}):", prompt, re.MULTILINE)
            return {"agent_id": ids[0] if ids else "", "reasoning": "least loaded"}
        if '"strategy"' in prompt:
            return {"strategy": "parallel", "max_parallel": 4, "reasoning": "independent tasks"}
        # Free-form generation fallback.
        return {"output": f"mock response to: {prompt[-120:]}"}

    @staticmethod
    def _task_key(prompt: str) -> str:
        m = re.search(r"Task ID: ([a-f0-9\-]+)", prompt)
        return m.group(1) if m else "default"

    def get_metrics(self) -> Dict[str, Any]:
        return {"backend": self.name, "calls": len(self.calls)}
