"""LLMHandler: the facade every agent and the orchestrator call.

Reference parity: ``pilott/engine/llm.py`` — ``generate_response(messages,
tools)`` (:38) with a sliding-window max_rpm limiter (:68-89), a concurrency
semaphore (:36), retry-with-backoff (:57-66); plain-string ``apredict``
(:181-199) used by the orchestrator's manager path; ``apredict_messages``
with functions (:201-218). Providers are in-tree backends instead of
litellm remote calls.
"""

from __future__ import annotations

import asyncio
import random
import time
import uuid
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

from pilottai_tpu.core.config import LLMConfig
from pilottai_tpu.engine.base import LLMBackend
from pilottai_tpu.engine.types import (
    ChatMessage,
    GenerationParams,
    LLMResponse,
    ToolSpec,
)
from pilottai_tpu.obs import (
    global_blackbox,
    global_dag,
    global_flight,
    global_steps,
)
from pilottai_tpu.reliability import (
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExceeded,
    EngineOverloaded,
    global_engine_health,
    global_injector,
)
from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import global_metrics
from pilottai_tpu.utils.tracing import global_tracer

_BACKEND_REGISTRY: Dict[str, Callable[[LLMConfig], LLMBackend]] = {}


def register_backend(name: str, factory: Callable[[LLMConfig], LLMBackend]) -> None:
    """Register a provider factory under ``config.provider`` name."""
    _BACKEND_REGISTRY[name] = factory


def create_backend(config: LLMConfig) -> LLMBackend:
    """Instantiate the backend for ``config.provider``.

    ``mock`` is registered eagerly; ``tpu``/``cpu`` import the JAX engine
    lazily so control-plane users never pay the jax import.
    """
    provider = config.provider
    if provider not in _BACKEND_REGISTRY:
        if provider in ("tpu", "cpu"):
            from pilottai_tpu.engine.native import register_native_backends

            register_native_backends()
        else:
            raise ValueError(f"unknown LLM provider {provider!r}")
    return _BACKEND_REGISTRY[provider](config)


def _register_mock(config: LLMConfig) -> LLMBackend:
    from pilottai_tpu.engine.mock import MockBackend

    return MockBackend(model_name=config.model_name)


register_backend("mock", _register_mock)


class RateLimiter:
    """Sliding-window requests-per-minute limiter (reference
    ``engine/llm.py:68-89``), lock-protected and non-blocking for peers."""

    def __init__(self, max_rpm: int, window: float = 60.0) -> None:
        self.max_rpm = max_rpm
        self.window = window
        self._stamps: deque = deque()
        self._lock = asyncio.Lock()

    async def acquire(self) -> None:
        while True:
            async with self._lock:
                now = time.monotonic()
                while self._stamps and now - self._stamps[0] > self.window:
                    self._stamps.popleft()
                if len(self._stamps) < self.max_rpm:
                    self._stamps.append(now)
                    return
                wait = self.window - (now - self._stamps[0]) + 0.01
            await asyncio.sleep(wait)


class LLMHandler:
    """Provider-agnostic inference facade with throttling and retries."""

    def __init__(
        self,
        config: Optional[LLMConfig | Dict[str, Any]] = None,
        backend: Optional[LLMBackend] = None,
    ) -> None:
        if isinstance(config, dict):
            config = LLMConfig(**config)
        self.config = config or LLMConfig()
        self.backend = backend or create_backend(self.config)
        self._semaphore = asyncio.Semaphore(self.config.max_concurrent_requests)
        self._limiter = (
            RateLimiter(self.config.max_rpm) if self.config.max_rpm else None
        )
        # Circuit breaker over every engine call: repeated backend
        # failures flip to fast-fail (the HTTP edge maps CircuitOpenError
        # to 503) instead of piling retry budgets onto a dead device.
        rel = self.config.reliability
        self.breaker: Optional[CircuitBreaker] = (
            CircuitBreaker(
                failure_threshold=rel.breaker_failure_threshold,
                recovery_timeout=rel.breaker_recovery_timeout,
                half_open_max=rel.breaker_half_open_max,
                name=self.config.model_name,
            )
            if rel.breaker_enabled else None
        )
        if self.breaker is not None:
            # Black-box context for every open: the step ring shows what
            # the engine was doing while failures crossed the threshold.
            self.breaker.on_open = lambda name: global_blackbox.dump(
                "breaker_open", breaker=name, model=self.config.model_name,
            )
            # A watchdog-declared engine stall force-opens this breaker:
            # a HUNG backend produces no failures to count (calls never
            # return), so without this new requests would queue onto a
            # dead device until their own timeouts. Weakly held — a
            # collected handler's breaker just drops off the registry.
            global_engine_health.subscribe(self.breaker.on_engine_stall)
        self._log = get_logger("engine.handler")
        self._started = False

    # ------------------------------------------------------------------ #

    async def start(self) -> None:
        if not self._started:
            await self.backend.start()
            self._started = True

    async def stop(self) -> None:
        # Unconditional: the backend may have started itself lazily on the
        # first generate() without flipping _started — gating on the flag
        # leaked live device threads past stop() (crash at process exit).
        await self.backend.stop()
        self._started = False

    # ------------------------------------------------------------------ #

    def _normalize(
        self,
        messages: Sequence[ChatMessage | Dict[str, Any] | str],
        tools: Optional[Sequence[ToolSpec | Dict[str, Any]]],
        params: Optional[GenerationParams],
        json_mode: Optional[bool],
        json_schema: Optional[Dict[str, Any]] = None,
        slo_class: Optional[str] = None,
        session_id: Optional[str] = None,
        priority: Optional[int] = None,
        gang_id: Optional[str] = None,
        gang_size: int = 0,
    ):
        """One request-normalization path for the streaming AND
        non-streaming calls — the two must never drift in default-params
        or json_mode semantics."""
        msgs = [ChatMessage.coerce(m) for m in messages]
        specs = [
            t if isinstance(t, ToolSpec) else ToolSpec(**t) for t in (tools or [])
        ]
        if params is None:
            s = self.config.sampling
            params = GenerationParams(
                max_new_tokens=s.max_new_tokens,
                temperature=s.temperature,
                top_k=s.top_k,
                top_p=s.top_p,
                seed=s.seed,
                json_mode=s.json_mode,
            )
        if json_mode is not None and json_mode != params.json_mode:
            params = params.model_copy(update={"json_mode": json_mode})
        if json_schema is not None:
            # Schema implies JSON mode (the schema DFA subsumes it).
            params = params.model_copy(
                update={"json_schema": json_schema, "json_mode": True}
            )
        if slo_class is not None and params.slo_class is None:
            # Caller-level default (the agent's task kind): fills in only
            # when params carry no class, so an explicit per-request
            # class (the HTTP edge's) always survives.
            params = params.model_copy(update={"slo_class": slo_class})
        if session_id is not None and params.session_id is None:
            # KV-cache session lineage (engine/kvcache/): same
            # fill-don't-override rule as slo_class.
            params = params.model_copy(update={"session_id": session_id})
        if priority is not None and params.priority is None:
            # DAG-aware scheduling (pilottai_tpu/sched/): the caller's
            # task-priority rung — same fill-don't-override rule, so an
            # explicit per-request priority always survives.
            params = params.model_copy(update={"priority": priority})
        if gang_id is not None and params.gang_id is None:
            params = params.model_copy(
                update={"gang_id": gang_id, "gang_size": gang_size}
            )
        return msgs, specs, params

    def _ensure_trace(self, params: GenerationParams) -> GenerationParams:
        """Every engine request flies with a trace id: the HTTP edge sets
        one from ``x-request-id``; orchestrator-driven calls adopt the
        ambient span's trace (serve.execute_task / agent spans — the
        nested engine.generate span inherits that trace anyway, and the
        batcher's emitted span must land in the SAME trace or the tree
        splits); bare callers get a fresh per-call id. Either way the
        flight recorder covers all traffic, not just HTTP.

        ``flight_id`` is always minted fresh: one ledger per engine
        request even when many share a trace."""
        update: Dict[str, Any] = {}
        if params.trace_id is None:
            ambient = global_tracer.current()
            update["trace_id"] = (
                ambient.trace_id if ambient is not None
                else uuid.uuid4().hex[:16]
            )
        if params.flight_id is None:
            update["flight_id"] = uuid.uuid4().hex[:16]
        return params.model_copy(update=update) if update else params

    @staticmethod
    def _dag_context() -> Dict[str, Any]:
        """The ambient task-DAG node issuing this request, captured at
        flight start (the dag ledger's finish listener joins the flight
        into that task's DAG; the listener fires on the reader thread,
        where the asyncio context is long gone — so it rides on the
        flight's attributes). Empty outside any orchestrated task."""
        cur = global_dag.current()
        if cur is None:
            return {}
        return {"dag_task": cur[0], "dag_node": cur[1]}

    def _finish_flight(
        self,
        flight_id: str,
        trace_id: str,
        status: str,
        dump_reason: Optional[str] = None,
        tokens: Optional[int] = None,
        latency_s: Optional[float] = None,
        **dump_extra: Any,
    ) -> None:
        """Close the request's flight record, append a handler step to
        the telemetry ring, and (for failures worth a postmortem) write a
        black-box dump."""
        summary = global_flight.finish(flight_id, status)
        step: Dict[str, Any] = {
            "model": self.config.model_name, "status": status,
        }
        if tokens is not None:
            step["tokens"] = tokens
        if latency_s is not None:
            step["latency_s"] = round(latency_s, 6)
        if summary:
            for key in ("ttft_s", "tpot_s", "e2e_s"):
                if key in summary:
                    step[key] = summary[key]
        global_steps.record("handler.request", trace_id=trace_id, **step)
        if dump_reason is not None:
            global_blackbox.dump(
                dump_reason, trace_id=trace_id,
                model=self.config.model_name, **dump_extra,
            )

    async def generate_response(
        self,
        messages: Sequence[ChatMessage | Dict[str, Any] | str],
        tools: Optional[Sequence[ToolSpec | Dict[str, Any]]] = None,
        params: Optional[GenerationParams] = None,
        json_mode: Optional[bool] = None,
        json_schema: Optional[Dict[str, Any]] = None,
        slo_class: Optional[str] = None,
        session_id: Optional[str] = None,
        priority: Optional[int] = None,
        gang_id: Optional[str] = None,
        gang_size: int = 0,
    ) -> LLMResponse:
        """Chat completion with retry/backoff (reference ``llm.py:38-66``).

        ``json_mode`` overrides the config/params flag — protocol call
        sites (rules.yaml prompts demand strict JSON) set it True to get
        grammar-constrained decoding on byte-tokenizer engines.
        ``slo_class`` fills the request's SLO class when params carry
        none (the orchestrator passes its task-derived class here);
        ``session_id`` likewise fills the KV-cache session handle so
        multi-turn callers pin their prefix lineage across turns.
        ``priority``/``gang_id``/``gang_size`` are the DAG scheduler's
        admission hints (pilottai_tpu/sched/) — same fill-don't-override
        rule.
        """
        msgs, specs, params = self._normalize(
            messages, tools, params, json_mode, json_schema, slo_class,
            session_id, priority, gang_id, gang_size,
        )
        params = self._ensure_trace(params)
        trace_id, flight_id = params.trace_id, params.flight_id
        global_flight.start(
            flight_id, trace_id=trace_id, model=self.config.model_name,
            slo_class=params.slo_class, session_id=params.session_id,
            **self._dag_context(),
        )

        deadline = params.deadline
        try:
            return await self._generate_attempts(msgs, specs, params, deadline)
        except EngineOverloaded:
            self._finish_flight(flight_id, trace_id, "shed")
            raise
        except CircuitOpenError:
            self._finish_flight(flight_id, trace_id, "breaker_open")
            raise
        except DeadlineExceeded:
            self._finish_flight(
                flight_id, trace_id, "deadline",
                dump_reason="deadline_expired",
            )
            raise
        except asyncio.CancelledError:
            self._finish_flight(flight_id, trace_id, "cancelled")
            raise
        except Exception as exc:  # noqa: BLE001 — flight/dump then re-raise
            self._finish_flight(
                flight_id, trace_id, "error", dump_reason="request_error",
                error=str(exc),
            )
            raise

    async def _generate_attempts(
        self,
        msgs: List[ChatMessage],
        specs: List[ToolSpec],
        params: GenerationParams,
        deadline: Optional[float],
    ) -> LLMResponse:
        """The retry loop proper (flight/dump bookkeeping lives in
        ``generate_response`` so every exit path settles exactly once)."""
        trace_id, flight_id = params.trace_id, params.flight_id
        last_error: Optional[Exception] = None
        for attempt in range(self.config.retries + 1):
            if attempt:
                # Retry boundary: drop the aborted attempt's token
                # timeline so the new attempt's first token doesn't read
                # as a backoff-sized inter-token gap.
                global_flight.reset_tokens(flight_id)
            # Deadline first (before the breaker reserves a probe slot):
            # a request whose budget is gone must not consume anything.
            if deadline is not None and time.monotonic() >= deadline:
                raise DeadlineExceeded(
                    f"request deadline exhausted after {attempt} attempt(s)"
                ) from last_error
            if self.breaker is not None and not self.breaker.allow():
                raise self.breaker.open_error() from last_error
            # allow() may have reserved a half-open probe slot; every exit
            # from this attempt must settle it (record_*) or release it
            # (the finally below) — a cancellation between the two would
            # otherwise leak the slot and wedge the breaker permanently.
            settled = False
            try:
                # Chaos point: simulate a wedged backend at the handler
                # boundary (arm with exc=asyncio.TimeoutError).
                global_injector.fire("handler.timeout")
                if self._limiter:
                    await self._limiter.acquire()
                async with self._semaphore:
                    with global_tracer.span(
                        "engine.generate", trace_id=trace_id,
                        model=self.config.model_name, attempt=attempt,
                    ) as span:
                        # The batcher's threads can't see this asyncio
                        # context; hand them the span id so the engine's
                        # emitted span nests under this one.
                        call_params = params.model_copy(
                            update={"parent_span_id": span.span_id}
                        )
                        start = time.perf_counter()
                        budget = self.config.timeout
                        if deadline is not None:
                            budget = min(budget, deadline - time.monotonic())
                        try:
                            response = await asyncio.wait_for(
                                self.backend.generate(
                                    msgs, specs or None, call_params
                                ),
                                timeout=max(budget, 1e-3),
                            )
                        except asyncio.TimeoutError:
                            if (
                                deadline is not None
                                and time.monotonic() >= deadline
                            ):
                                raise DeadlineExceeded(
                                    "request deadline exceeded mid-generation"
                                ) from None
                            raise
                if self.breaker is not None:
                    self.breaker.record_success()
                settled = True
                latency = time.perf_counter() - start
                global_metrics.observe("engine.request_latency", latency)
                global_metrics.inc("engine.requests")
                global_metrics.inc(
                    "engine.prompt_tokens", response.usage.prompt_tokens
                )
                global_metrics.inc(
                    "engine.completion_tokens", response.usage.completion_tokens
                )
                # Length shape for the workload profiler: the usage
                # envelope is the only place prompt length is known, and
                # start() is the idempotent attribute-merge hook.
                global_flight.start(
                    flight_id,
                    prompt_tokens=response.usage.prompt_tokens,
                    completion_tokens=response.usage.completion_tokens,
                )
                # Backends with no token visibility (mock, custom): model
                # the tokens over the call envelope so TTFT/TPOT
                # percentiles exist for every deployment. A no-op when
                # the batcher already recorded real token marks.
                global_flight.synthesize_tokens(
                    flight_id, response.usage.completion_tokens,
                    start, time.perf_counter(),
                )
                self._finish_flight(
                    flight_id, trace_id, "ok",
                    tokens=response.usage.completion_tokens,
                    latency_s=latency,
                )
                return response
            except EngineOverloaded:
                # Shed at admission: the engine is alive and protecting
                # itself. Not a device failure (it must not open the
                # breaker) and not retryable here — an immediate retry
                # defeats the shed; push-back belongs to the caller.
                if self.breaker is not None:
                    self.breaker.record_success()
                settled = True
                global_metrics.inc("engine.errors")
                raise
            except DeadlineExceeded:
                # Terminal for this request. It still counts against the
                # breaker: deadline blowouts cluster exactly when the
                # backend is wedged or drowning, and fast-failing the
                # herd until a probe succeeds is the desired behavior.
                if self.breaker is not None:
                    self.breaker.record_failure()
                settled = True
                global_metrics.inc("engine.errors")
                raise
            except Exception as exc:  # noqa: BLE001 - retry boundary
                last_error = exc
                if self.breaker is not None:
                    self.breaker.record_failure()
                settled = True
                global_metrics.inc("engine.errors")
                if attempt < self.config.retries:
                    delay = self._backoff_delay(attempt)
                    if (
                        deadline is not None
                        and time.monotonic() + delay >= deadline
                    ):
                        # The backoff sleep alone would outlive the
                        # deadline — fail now, not after sleeping.
                        raise DeadlineExceeded(
                            f"request deadline exhausted after "
                            f"{attempt + 1} attempt(s)"
                        ) from exc
                    self._log.warning(
                        "generate attempt %d failed (%s); retrying in %.2fs",
                        attempt + 1,
                        exc,
                        delay,
                    )
                    await asyncio.sleep(delay)
            finally:
                if self.breaker is not None and not settled:
                    # Cancelled (or otherwise aborted) with no verdict:
                    # give the half-open probe slot back.
                    self.breaker.release_probe()
        raise RuntimeError(
            f"LLM generation failed after {self.config.retries + 1} attempts"
        ) from last_error

    def _backoff_delay(self, attempt: int) -> float:
        """Capped exponential backoff with jitter. The seed's linear
        ``retry_delay * (attempt + 1)`` schedule had no randomness, so a
        wave of requests failing together retried in lockstep against a
        just-recovered backend (thundering herd). Jitter spreads each
        delay uniformly over [0.5x, 1.0x] of the exponential step."""
        rel = self.config.reliability
        delay = min(
            self.config.retry_delay * (2.0 ** attempt), rel.retry_max_delay
        )
        if rel.retry_jitter and delay > 0:
            delay *= 0.5 + 0.5 * random.random()
        return delay

    async def astream(
        self,
        messages: Sequence[ChatMessage | Dict[str, Any] | str] | str,
        tools: Optional[Sequence[ToolSpec | Dict[str, Any]]] = None,
        params: Optional[GenerationParams] = None,
        json_mode: Optional[bool] = None,
        json_schema: Optional[Dict[str, Any]] = None,
        slo_class: Optional[str] = None,
        session_id: Optional[str] = None,
        priority: Optional[int] = None,
        gang_id: Optional[str] = None,
        gang_size: int = 0,
        info: Optional[Dict[str, Any]] = None,
    ):
        """Streaming chat completion: an async generator of text deltas
        whose concatenation equals ``generate_response(...).content`` for
        the same request. No retry once tokens flow (a consumer has
        already observed partial output — silently replaying from a
        fresh sample would splice two generations); errors surface to
        the consumer instead. ``config.timeout`` applies as an
        INACTIVITY timeout — the longest wait for the next delta, not a
        bound on the whole stream (a healthy stream of any length never
        trips it; a wedged engine does, instead of pinning the
        concurrency semaphore forever). The rpm limiter and semaphore
        apply for the stream's whole lifetime."""
        if isinstance(messages, str):
            messages = [messages]
        msgs, specs, params = self._normalize(
            messages, tools, params, json_mode, json_schema, slo_class,
            session_id, priority, gang_id, gang_size,
        )
        params = self._ensure_trace(params)
        trace_id, flight_id = params.trace_id, params.flight_id
        global_flight.start(
            flight_id, trace_id=trace_id,
            model=self.config.model_name, stream=True,
            slo_class=params.slo_class, session_id=params.session_id,
            **self._dag_context(),
        )

        deadline = params.deadline
        if self.breaker is not None and not self.breaker.allow():
            self._finish_flight(flight_id, trace_id, "breaker_open")
            raise self.breaker.open_error()
        # allow() may have reserved a half-open probe slot: every exit
        # path must settle it (the inner finally below) or release it
        # (the BaseException arm at the bottom — cancellation while
        # acquiring the limiter/semaphore, or a failed generator
        # creation, would otherwise leak the slot and wedge the breaker).
        settled = False
        try:
            if self._limiter:
                await self._limiter.acquire()
            async with self._semaphore:
                with global_tracer.span(
                    "engine.generate_stream", trace_id=trace_id,
                    model=self.config.model_name,
                ) as span:
                    call_params = params.model_copy(
                        update={"parent_span_id": span.span_id}
                    )
                    start = time.perf_counter()
                    n_chars = 0
                    n_deltas = 0
                    first_delta_at: Optional[float] = None
                    last_delta_at: Optional[float] = None
                    try:
                        gen = self.backend.generate_stream(
                            msgs, specs or None, call_params, info=info
                        )
                    except TypeError:
                        # Pre-`info` backend signature (user-supplied
                        # backends): argument binding fails at call time,
                        # before any iteration — safe to retry without.
                        gen = self.backend.generate_stream(
                            msgs, specs or None, call_params
                        )
                    agen = gen.__aiter__()
                    failed = True  # error until proven otherwise
                    shed = False
                    # The in-flight exception, captured explicitly: an
                    # async generator's finally can observe the CONSUMER
                    # frame's already-handled exception via sys.exc_info()
                    # on normal exhaustion, which would misclassify a
                    # successful stream (review finding).
                    stream_exc: Optional[BaseException] = None
                    try:
                        while True:
                            wait = self.config.timeout
                            if deadline is not None:
                                wait = min(wait, deadline - time.monotonic())
                            try:
                                delta = await asyncio.wait_for(
                                    agen.__anext__(), timeout=max(wait, 1e-3)
                                )
                            except StopAsyncIteration:
                                break
                            except asyncio.TimeoutError:
                                if (
                                    deadline is not None
                                    and time.monotonic() >= deadline
                                ):
                                    raise DeadlineExceeded(
                                        "request deadline exceeded mid-stream"
                                    ) from None
                                raise
                            n_chars += len(delta)
                            n_deltas += 1
                            now = time.perf_counter()
                            if first_delta_at is None:
                                first_delta_at = now
                                global_flight.mark(
                                    flight_id, "first_delta", at=now
                                )
                            last_delta_at = now
                            yield delta
                        failed = False
                    except GeneratorExit as exc:
                        failed = False  # consumer chose to stop — not an error
                        stream_exc = exc
                        raise
                    except EngineOverloaded as exc:
                        # Shed at admission: counts as an error for the
                        # request metrics but NOT against the breaker —
                        # unary-path parity (a shed proves the engine is
                        # alive and protecting itself).
                        shed = True
                        stream_exc = exc
                        raise
                    except BaseException as exc:
                        stream_exc = exc
                        raise
                    finally:
                        # Consumer break / timeout / error: close the backend
                        # generator so its request is cancelled and the slot
                        # freed (native engines cancel in their finally).
                        await agen.aclose()
                        # Metrics land on EVERY outcome (generate_response
                        # parity: errors are counted, requests never vanish).
                        global_metrics.observe(
                            "engine.request_latency",
                            time.perf_counter() - start,
                        )
                        global_metrics.inc("engine.requests")
                        global_metrics.inc("engine.stream_chars", n_chars)
                        if failed:
                            global_metrics.inc("engine.errors")
                        # Flight close — exactly once, on every outcome
                        # (generate_response parity). Real token marks
                        # come from the batcher; token-blind backends
                        # fall back to the delta envelope the consumer
                        # actually observed.
                        n_tok = n_deltas
                        if info is not None and isinstance(
                            info.get("completion_tokens"), int
                        ):
                            n_tok = info["completion_tokens"]
                        if info is not None and isinstance(
                            info.get("prompt_tokens"), int
                        ):
                            global_flight.start(
                                flight_id,
                                prompt_tokens=info["prompt_tokens"],
                            )
                        if n_tok and first_delta_at is not None:
                            global_flight.set_token_envelope(
                                flight_id, n_tok,
                                first_delta_at, last_delta_at,
                            )
                        if isinstance(stream_exc, DeadlineExceeded):
                            self._finish_flight(
                                flight_id, trace_id, "deadline",
                                dump_reason="deadline_expired",
                            )
                        elif shed:
                            self._finish_flight(flight_id, trace_id, "shed")
                        elif isinstance(
                            stream_exc,
                            (GeneratorExit, asyncio.CancelledError),
                        ):
                            self._finish_flight(
                                flight_id, trace_id, "cancelled"
                            )
                        elif failed:
                            self._finish_flight(
                                flight_id, trace_id, "error",
                                dump_reason="request_error",
                                error=(
                                    str(stream_exc)
                                    if stream_exc is not None else None
                                ),
                            )
                        else:
                            self._finish_flight(
                                flight_id, trace_id, "ok", tokens=n_tok,
                                latency_s=time.perf_counter() - start,
                            )
                        settled = True
                        if self.breaker is not None:
                            # Pair the allow() above: streams report into
                            # the breaker like unary calls (consumer breaks
                            # count as success — the backend was serving
                            # fine).
                            if failed and not shed:
                                self.breaker.record_failure()
                            else:
                                self.breaker.record_success()
        except BaseException as exc:
            if self.breaker is not None and not settled:
                self.breaker.release_probe()
            if not settled:
                # Failure before the stream's own finally ran (limiter
                # acquire cancelled, generator creation failed): the
                # flight is still open and must not leak as "active".
                self._finish_flight(
                    flight_id, trace_id,
                    "cancelled"
                    if isinstance(exc, (asyncio.CancelledError, GeneratorExit))
                    else "error",
                )
            raise

    async def apredict(self, prompt: str, **kwargs: Any) -> str:
        """Plain string-in/string-out (reference ``llm.py:181-199``)."""
        response = await self.generate_response(
            [ChatMessage(role="user", content=prompt)], **kwargs
        )
        return response.content

    async def apredict_messages(
        self,
        messages: Sequence[ChatMessage | Dict[str, Any]],
        functions: Optional[Sequence[ToolSpec | Dict[str, Any]]] = None,
        **kwargs: Any,
    ) -> LLMResponse:
        """Messages + function-calling form (reference ``llm.py:201-218``)."""
        return await self.generate_response(messages, tools=functions, **kwargs)

    # ------------------------------------------------------------------ #

    def get_metrics(self) -> Dict[str, Any]:
        return {
            "model": self.config.model_name,
            "provider": self.config.provider,
            "backend": self.backend.get_metrics(),
            "requests": global_metrics.get("engine.requests"),
            "errors": global_metrics.get("engine.errors"),
            **(
                {"breaker": self.breaker.snapshot()}
                if self.breaker is not None else {}
            ),
        }
