"""Attention kernels: grouped-query attention with optional logit soft-cap
and sliding windows, in pure XLA (the Pallas flash kernel in
``pilottai_tpu/ops/pallas`` is used for large prefills; this path is the
reference implementation and the decode path).

Design notes (TPU):
* softmax statistics in float32, matmuls in bfloat16 — the MXU accumulates
  in fp32 anyway, so only the exp/sum need explicit widening;
* GQA is expressed by reshaping queries to [B, K, G, T, H] and batching the
  einsum over kv-heads, which XLA tiles onto the MXU without materializing
  repeated K/V.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -2.0**30  # large negative, safe in bf16 after cast


def flash_enabled() -> bool:
    """Use the Pallas flash kernel for full-sequence attention on TPU.

    Gated off on CPU (interpret mode is far slower than XLA there) and by
    ``PILOTTAI_NO_FLASH=1`` for A/B comparison."""
    if os.environ.get("PILOTTAI_NO_FLASH"):
        return False
    return jax.default_backend() == "tpu"


_FLASH_KV_VMEM_BUDGET = 8 * 1024 * 1024  # bytes for resident K+V per grid cell


def flash_shapes_ok(
    T: int,
    S: int,
    head_dim: int = 128,
    itemsize: int = 2,
    block_q: int = 128,
    block_k: int = 128,
) -> bool:
    """Size floor plus a VMEM bound: the kernel keeps the full [S, H]
    K and V resident (double-buffered by the pipeline), so the PADDED S
    must fit the budget or Mosaic fails allocation where XLA would have
    run. Ragged T/S are fine — ``flash_attention`` pads to block
    multiples internally (VERDICT r2 next-step 8); only tiny shapes,
    where the pad waste dwarfs the work, stay on XLA."""
    if T < 16 or S < 16:
        return False
    s_padded = -(-S // block_k) * block_k
    kv_bytes = 2 * s_padded * head_dim * itemsize * 2  # K+V, double-buffered
    return kv_bytes <= _FLASH_KV_VMEM_BUDGET


def dot_product_attention(
    q: jax.Array,  # [B, T, N, H]
    k: jax.Array,  # [B, S, K, H]
    v: jax.Array,  # [B, S, K, H]
    mask: Optional[jax.Array] = None,  # [B, 1, T, S] or [B, T, S], True = attend
    scale: Optional[float] = None,
    logit_softcap: float = 0.0,
) -> jax.Array:
    """Grouped-query attention. Returns [B, T, N, H]."""
    B, T, N, H = q.shape
    _, S, K, _ = k.shape
    assert N % K == 0, f"query heads {N} not divisible by kv heads {K}"
    G = N // K
    scale = scale if scale is not None else H ** -0.5

    q = q.reshape(B, T, K, G, H)
    # [B, K, G, T, S]
    logits = jnp.einsum("btkgh,bskh->bkgts", q, k).astype(jnp.float32) * scale
    if logit_softcap > 0.0:
        logits = jnp.tanh(logits / logit_softcap) * logit_softcap
    if mask is not None:
        if mask.ndim == 3:
            mask = mask[:, None, :, :]
        # mask [B, 1, T, S] -> broadcast over (K, G)
        logits = jnp.where(mask[:, :, None, :, :], logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgts,bskh->btkgh", weights, v)
    return out.reshape(B, T, N, H)


def causal_mask(T: int, dtype=jnp.bool_) -> jax.Array:
    """[T, T] lower-triangular causal mask."""
    return jnp.tril(jnp.ones((T, T), dtype=dtype))


def make_attention_mask(
    q_positions: jax.Array,  # [B, T] absolute positions of the query tokens
    kv_length: int,          # S — static cache length
    kv_valid: jax.Array,     # [B] number of valid cache entries (incl. current)
    window: int = 0,         # 0 = global; >0 = sliding window size
) -> jax.Array:
    """Causal (+ optional sliding-window) mask against a fixed-size cache.

    True where query at absolute position p may attend cache slot j, i.e.
    j <= p, j < kv_valid, and (window == 0 or p - j < window). Cache slot j
    holds the token at absolute position j (contiguous cache).
    Returns [B, T, S].
    """
    j = jnp.arange(kv_length)[None, None, :]          # [1, 1, S]
    p = q_positions[:, :, None]                        # [B, T, 1]
    mask = (j <= p) & (j < kv_valid[:, None, None])
    if window > 0:
        mask &= (p - j) < window
    return mask


def sliding_window_row_mask(
    positions: jax.Array, kv_length: int, windows: jax.Array
) -> jax.Array:
    """Per-layer-window variant used inside the layer scan: ``windows`` is a
    scalar (traced per scan step). 0 disables the window."""
    j = jnp.arange(kv_length)[None, None, :]
    p = positions[:, :, None]
    base = j <= p
    win = (p - j) < jnp.maximum(windows, 1)
    return jnp.where(windows > 0, base & win, base)
