"""Paged decode attention as a Pallas TPU kernel.

The paged cache (``ops/paged.py``) stores K/V in a shared page pool with
block-table indirection; this kernel reads ONLY the pages a slot
actually occupies. The trick is scalar-prefetched index maps: the block
table lands in SMEM before the grid runs, and each grid cell's
BlockSpecs *compute their pool coordinates from the table* — pages
stream HBM→VMEM directly by id, no dense [B, S, H] gather ever exists.

Grid is (B, page-strip-count) with the strip dim innermost. Each cell
processes a **strip of ``n_strip`` pages** (round-5 profiling: one page
per cell left the 8K section grid-cell-latency bound — page A/B
64→268, 128→243, 256→309 device ms/step showed a per-cell launch/index
floor, not a bandwidth floor). The strip rides as ``n_strip`` replicated
BlockSpecs over the same pool, each with its own scalar-prefetched index
map, so one cell's prefetch wave covers N pages and the launch/index
overhead amortizes N-fold. The (acc, m, l) online-softmax outputs map to
the same block for every strip step, so they stay VMEM-resident and
accumulate across the whole strip sequence (the same revisited-output
reduction the flash backward uses). Pages that are unallocated, fully
past the valid length, or padding past ``n_blocks`` clamp their DMA to
the scratch page and skip compute with ``pl.when`` — page-for-page the
math is identical to the single-page kernel, so strip results are
bit-identical (pinned by tests/test_paged_strip.py).

Optionally the **in-chunk ring attention fuses into the same
invocation** (``ring_k``/``ring_v``/``ring_step``): the final grid cell
runs the ring block and merges it with the page stats exactly like
``engine/decode.py:_merge_stats``, eliminating the separate per-layer
ring dispatch + combine the plain decode chunk used to pay per step.
The speculative chunk keeps its separate passes (its block attention
carries intra-block causal masking this kernel does not model — the
stats contract does not allow the fusion there).

Returns unnormalized (acc, m, l) stats — with the ring fused the caller
only normalizes; without it the fused decode chunk combines them with
the in-chunk ring attention, same contract as
``decode_attention(return_stats=True)``.

Design follows the ragged paged attention literature cited in PAPERS.md.
No reference counterpart; VERDICT r5 next-step 1 (amortize the paged
kernel's grid-cell latency).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -2.0**30


def _paged_kernel(
    *refs,
    # refs layout (scalar prefetch first):
    #   table_ref  SMEM (B, max_pages) int32
    #   last_ref   SMEM (B,) int32 — max valid key index per slot
    #   qpos_ref   SMEM (B,) int32 — query absolute position (window)
    #   [rstep_ref SMEM (1,) int32 — valid ring rows - 1, when ring]
    #   q_ref      VMEM (1, K, G, H)
    #   k_refs × n_strip   VMEM (K, 1, P, H) — one page each
    #   v_refs × n_strip   VMEM (K, 1, P, H)
    #   [ks/vs_refs × n_strip  VMEM (K, 1, P, 1) when quantized]
    #   [ringk_ref, ringv_ref  VMEM (1, K, R, H) when ring]
    #   acc_ref (1, K, G, H) f32, m_ref (1, K, G, 1), l_ref (1, K, G, 1)
    scale: float,
    softcap: float,
    window: int,
    page_size: int,
    sentinel: int,
    max_pages: int,
    q_blocks: int,
    quantized: bool,
    n_strip: int,
    n_blocks: int,
    ring: bool,
):
    it = iter(range(len(refs)))
    table_ref, last_ref, qpos_ref = (refs[next(it)] for _ in range(3))
    rstep_ref = refs[next(it)] if ring else None
    q_ref = refs[next(it)]
    k_refs = [refs[next(it)] for _ in range(n_strip)]
    v_refs = [refs[next(it)] for _ in range(n_strip)]
    if quantized:
        ks_refs = [refs[next(it)] for _ in range(n_strip)]
        vs_refs = [refs[next(it)] for _ in range(n_strip)]
    else:
        ks_refs = vs_refs = [None] * n_strip
    if ring:
        ringk_ref = refs[next(it)]
        ringv_ref = refs[next(it)]
    acc_ref, m_ref, l_ref = (refs[next(it)] for _ in range(3))

    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    last = last_ref[b]
    qpos = qpos_ref[b]

    def _attend_page(k_ref, v_ref, ks_ref, vs_ref, j0):
        """One page's online-softmax update — byte-identical math to the
        pre-strip single-page kernel (the parity suite pins this)."""
        q = q_ref[0]                                      # [K, G, H]
        k = k_ref[:, 0]                                   # [K, P, H]
        v = v_ref[:, 0]
        if quantized:
            # In-VMEM dequant: the HBM→VMEM stream stays int8-sized.
            # Scale blocks ride as (K, 1, P, 1) — the trailing singleton
            # satisfies the TPU lowering's last-two-dims constraint.
            k = k.astype(jnp.float32) * ks_ref[:, 0]
            v = v.astype(jnp.float32) * vs_ref[:, 0]
            q = q.astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale                                         # [K, G, P]
        if softcap > 0.0:
            s = jnp.tanh(s / softcap) * softcap
        col = j0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        mask = col <= last
        if window > 0:
            # Speculative blocks pack D queries per G row (row = g*D + d,
            # query d at position qpos + d).
            qpos_row = qpos + (
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) % q_blocks
                if q_blocks > 1 else 0
            )
            mask &= (qpos_row - col) < window
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[0, :, :, :]                        # [K, G, 1]
        l_prev = l_ref[0, :, :, :]
        acc_prev = acc_ref[0]
        m_blk = jnp.max(s, axis=-1, keepdims=True)        # [K, G, 1]
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.where(m_new > NEG_INF / 2, jnp.exp(s - m_new), 0.0)
        corr = jnp.where(
            m_prev > NEG_INF / 2, jnp.exp(m_prev - m_new), 0.0
        )
        l_ref[0, :, :, :] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                                 # [K, G, H]
        acc_ref[0] = acc_prev * corr + pv
        m_ref[0, :, :, :] = m_new

    # The strip: pages j*n_strip .. j*n_strip + n_strip - 1, in order —
    # same visit order as the single-page grid, so accumulation order
    # (and therefore every float) is unchanged. Dead strip elements
    # (unallocated page, fully past `last`, outside the window, or
    # padding past n_blocks) skip their update entirely.
    for t in range(n_strip):
        jt = j * n_strip + t
        j0 = jt * page_size
        page = table_ref[b, jnp.minimum(jt, max_pages - 1)]
        live = (jt < n_blocks) & (page != sentinel) & (j0 <= last)
        if window > 0:
            # Most-permissive query decides page liveness: (qpos_row -
            # col) < window is EASIEST to satisfy at the smallest
            # position, i.e. row d=0 at qpos — later rows only tighten,
            # and the per-row mask inside applies them exactly.
            live &= (qpos - (j0 + page_size - 1)) < window

        @pl.when(live)
        def _attend(t=t, j0=j0):
            _attend_page(k_refs[t], v_refs[t], ks_refs[t], vs_refs[t], j0)

    if ring:
        # Fused in-chunk ring attention: the LAST cell computes the ring
        # block's own stats and merges them exactly like
        # engine/decode.py:_merge_stats (ring row r sits at
        # chunk-relative offset r; rows 0..step are valid — decode.py's
        # _ring_stats contract). Row `step` is always live, so m_r is
        # never NEG_INF.
        @pl.when(j == pl.num_programs(1) - 1)
        def _ring():
            step = rstep_ref[0]
            q = q_ref[0]                                  # [K, G, H]
            rk = ringk_ref[0]                             # [K, R, H]
            rv = ringv_ref[0]
            s = jax.lax.dot_general(
                q, rk,
                dimension_numbers=(((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            ) * scale                                     # [K, G, R]
            if softcap > 0.0:
                s = jnp.tanh(s / softcap) * softcap
            r = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
            mask = r <= step
            if window > 0:
                mask &= (step - r) < window
            s = jnp.where(mask, s, NEG_INF)
            m_r = jnp.max(s, axis=-1, keepdims=True)      # [K, G, 1]
            p = jnp.exp(s - m_r)
            l_r = jnp.sum(p, axis=-1, keepdims=True)
            acc_r = jax.lax.dot_general(
                p.astype(rv.dtype), rv,
                dimension_numbers=(((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            m_prev = m_ref[0, :, :, :]
            l_prev = l_ref[0, :, :, :]
            acc_prev = acc_ref[0]
            m_new = jnp.maximum(m_prev, m_r)
            wa = jnp.where(
                m_prev > NEG_INF / 2, jnp.exp(m_prev - m_new), 0.0
            )
            wb = jnp.where(m_r > NEG_INF / 2, jnp.exp(m_r - m_new), 0.0)
            acc_ref[0] = acc_prev * wa + acc_r * wb
            l_ref[0, :, :, :] = l_prev * wa + l_r * wb
            m_ref[0, :, :, :] = m_new


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_blocks", "scale", "softcap", "window", "q_blocks", "n_strip",
        "interpret",
    ),
)
def paged_decode_attention(
    q: jax.Array,        # [B, N, H] current-token queries; with q_blocks=D
                         # the N axis packs D block queries per head
                         # (row = head * D + d, query d at position
                         # q_positions + d) — the speculative-decode shape
    k_pool: jax.Array,   # [K, num_pages, P, H]
    v_pool: jax.Array,
    table: jax.Array,    # [B, max_pages] int32 (sentinel = num_pages - 1)
    last_valid: jax.Array,   # [B] int32 — keys at s <= last_valid[b] attend
    q_positions: Optional[jax.Array] = None,  # [B]; defaults to last_valid
    n_blocks: int = 0,   # static — page slots to visit (bounded by host)
    scale: Optional[float] = None,
    softcap: float = 0.0,
    window: int = 0,
    q_blocks: int = 1,   # static — queries per head row (speculation's D)
    k_scales: Optional[jax.Array] = None,  # [K, num_pages, P] — int8 pools
    v_scales: Optional[jax.Array] = None,
    n_strip: int = 1,    # static — pages per grid cell (autotuned by the
                         # batcher at warmup; amortizes per-cell latency)
    ring_k: Optional[jax.Array] = None,  # [B, K, R, H] — fuse the chunk
    ring_v: Optional[jax.Array] = None,  # ring into this invocation
    ring_step: Optional[jax.Array] = None,  # scalar int32 — rows 0..step
                                            # of the ring are valid
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Ragged paged GQA decode attention. Returns unnormalized
    ``(acc [B,N,H] fp32, m [B,N], l [B,N])`` online-softmax stats over
    each slot's first ``n_blocks`` pages, processed ``n_strip`` pages
    per grid cell — plus the in-chunk ring when ``ring_k`` is given."""
    B, N, H = q.shape
    K, num_pages, P, _ = k_pool.shape
    assert N % K == 0
    G = N // K
    assert G % q_blocks == 0
    max_pages = table.shape[1]
    assert 1 <= n_blocks <= max_pages
    scale = scale if scale is not None else H ** -0.5
    sentinel = num_pages - 1
    # A strip wider than the visit count just re-reads clamped pages for
    # masked-off cells; clamp so the grid never carries dead DMA waves.
    n_strip = max(1, min(n_strip, n_blocks))
    n_cells = -(-n_blocks // n_strip)

    qg = q.reshape(B, K, G, H)
    last_valid = jnp.asarray(last_valid, jnp.int32).reshape(B)
    if q_positions is None:
        q_positions = last_valid
    q_positions = jnp.asarray(q_positions, jnp.int32).reshape(B)
    table = jnp.asarray(table, jnp.int32)

    quantized = k_scales is not None
    assert (k_scales is None) == (v_scales is None)
    ring = ring_k is not None
    if ring:
        assert ring_v is not None and ring_step is not None
        assert q_blocks == 1, "ring fusion is the plain-decode contract"
    kernel = functools.partial(
        _paged_kernel,
        scale=scale, softcap=softcap, window=window,
        page_size=P, sentinel=sentinel, max_pages=max_pages,
        q_blocks=q_blocks, quantized=quantized,
        n_strip=n_strip, n_blocks=n_blocks, ring=ring,
    )

    def page_map(t):
        # Strip element t of cell j covers logical page slot
        # j*n_strip + t. Clamp twice: the slot index to the table width
        # (padding cells past n_blocks) and the sentinel to a real page
        # id (the DMA must target valid memory); the kernel's `live`
        # predicate skips the compute either way.
        def _map(b, j, table_ref, *_):
            jt = jnp.minimum(j * n_strip + t, max_pages - 1)
            return (0, jnp.minimum(table_ref[b, jt], sentinel), 0, 0)
        return _map

    in_specs = [pl.BlockSpec((1, K, G, H), lambda b, j, *_: (b, 0, 0, 0))]
    operands = [qg]
    # The strip rides as n_strip replicated pool operands, one
    # scalar-prefetched index map each: one grid cell's prefetch wave
    # fetches the whole strip.
    in_specs += [pl.BlockSpec((K, 1, P, H), page_map(t)) for t in range(n_strip)]
    operands += [k_pool] * n_strip
    in_specs += [pl.BlockSpec((K, 1, P, H), page_map(t)) for t in range(n_strip)]
    operands += [v_pool] * n_strip
    if quantized:
        # Trailing singleton: TPU lowering requires the last two block
        # dims be (8k, 128k) or equal the array dims — (P, 1) qualifies.
        ks_op = k_scales.astype(jnp.float32)[..., None]
        vs_op = v_scales.astype(jnp.float32)[..., None]
        in_specs += [
            pl.BlockSpec((K, 1, P, 1), page_map(t)) for t in range(n_strip)
        ]
        operands += [ks_op] * n_strip
        in_specs += [
            pl.BlockSpec((K, 1, P, 1), page_map(t)) for t in range(n_strip)
        ]
        operands += [vs_op] * n_strip
    scalars = [table, last_valid, q_positions]
    if ring:
        R = ring_k.shape[2]
        scalars.append(jnp.asarray(ring_step, jnp.int32).reshape(1))
        in_specs += [
            pl.BlockSpec((1, K, R, H), lambda b, j, *_: (b, 0, 0, 0)),
            pl.BlockSpec((1, K, R, H), lambda b, j, *_: (b, 0, 0, 0)),
        ]
        operands += [ring_k, ring_v]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(scalars),  # table, last, qpos[, step]
        grid=(B, n_cells),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, K, G, H), lambda b, j, *_: (b, 0, 0, 0)),
            pl.BlockSpec((1, K, G, 1), lambda b, j, *_: (b, 0, 0, 0)),
            pl.BlockSpec((1, K, G, 1), lambda b, j, *_: (b, 0, 0, 0)),
        ),
    )
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((B, K, G, H), jnp.float32),
            jax.ShapeDtypeStruct((B, K, G, 1), jnp.float32),
            jax.ShapeDtypeStruct((B, K, G, 1), jnp.float32),
        ),
        interpret=interpret,
    )(*scalars, *operands)
    return acc.reshape(B, N, H), m.reshape(B, N), l.reshape(B, N)


def strip_vmem_bytes(
    n_strip: int, page_size: int, n_kv_heads: int, head_dim: int,
    itemsize: int, quantized: bool,
) -> int:
    """Estimated VMEM the strip's K/V blocks pin per pipeline stage —
    the batcher's autotuner rejects candidates whose double-buffered
    strip would crowd the ~16 MB VMEM budget."""
    kv = 2 * n_kv_heads * page_size * head_dim * itemsize
    sc = 2 * n_kv_heads * page_size * 4 if quantized else 0
    return n_strip * (kv + sc)


# --------------------------------------------------------------------- #
# Multi-chip dispatch (shard_map) — ISSUE 13: tensor-parallel serving
# --------------------------------------------------------------------- #

def paged_sharding_ok(
    mesh,
    n_slots: int,
    n_kv_heads: int,
    batch_axes: Tuple[str, ...] = ("data", "fsdp"),
    head_axis: str = "model",
    seq_axis: str = "seq",
) -> bool:
    """True when the paged kernel can run per-shard with no cross-device
    work inside the attention itself: kv-heads divide the TP axis (the
    pool's K dim and the query rows' head-major packing split along the
    same boundary), the slot count divides the batch axes, and the
    sequence axis is unsharded. GQA heads are independent, so sharding
    them needs no collective — the cross-shard merge happens at the
    attention OUTPUT projection, whose row-parallel matmul all-reduces
    over ``model`` (the same contract as ``flash_sharding_ok``)."""
    shape = dict(mesh.shape)
    if int(shape.get(seq_axis, 1)) != 1:
        return False
    tp = int(shape.get(head_axis, 1))
    db = 1
    for a in batch_axes:
        db *= int(shape.get(a, 1))
    return n_kv_heads % tp == 0 and n_slots % db == 0


def paged_decode_attention_sharded(
    mesh,
    q: jax.Array,        # [B, N, H] — N packs (kv_head, group[, q_block])
    k_pool: jax.Array,   # [K, num_pages, P, H]
    v_pool: jax.Array,
    table: jax.Array,    # [B, max_pages]
    last_valid: jax.Array,
    q_positions: Optional[jax.Array] = None,
    n_blocks: int = 0,
    scale: Optional[float] = None,
    softcap: float = 0.0,
    window: int = 0,
    q_blocks: int = 1,
    k_scales: Optional[jax.Array] = None,
    v_scales: Optional[jax.Array] = None,
    n_strip: int = 1,
    ring_k: Optional[jax.Array] = None,
    ring_v: Optional[jax.Array] = None,
    ring_step: Optional[jax.Array] = None,
    interpret: bool = False,
    batch_axes: Tuple[str, ...] = ("data", "fsdp"),
    head_axis: str = "model",
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """:func:`paged_decode_attention` under ``shard_map``: the page
    pool's kv-head dim shards over the TP axis, slots over the data
    axes, and each shard runs the single-chip strip kernel on its own
    heads and pages — the pool never materializes whole on any chip.
    The query rows are head-major (``N = K·G[·D]``), so a contiguous N
    split lands each shard exactly its own kv-heads' queries. Attention
    over heads is embarrassingly parallel: the returned per-head stats
    need no cross-shard combine — the merge over the model axis is the
    attention output projection's all-reduce, emitted by GSPMD around
    this call. Same call contract and bit-identical per-shard math as
    the unsharded kernel (tests/test_multichip.py pins parity)."""
    from jax.sharding import PartitionSpec as P

    from pilottai_tpu.parallel.mesh import compat_shard_map

    shape = dict(mesh.shape)
    present = [
        a for a in batch_axes
        if a in mesh.axis_names and int(shape.get(a, 1)) > 1
    ]
    bspec = tuple(present) if present else None
    head = (
        head_axis
        if head_axis in mesh.axis_names and int(shape.get(head_axis, 1)) > 1
        else None
    )
    if q_positions is None:
        q_positions = jnp.asarray(last_valid, jnp.int32)

    in_specs = [
        P(bspec, head, None),        # q
        P(head, None, None, None),   # k_pool
        P(head, None, None, None),   # v_pool
        P(bspec, None),              # table
        P(bspec),                    # last_valid
        P(bspec),                    # q_positions
    ]
    operands = [q, k_pool, v_pool, table, last_valid, q_positions]
    quantized = k_scales is not None
    if quantized:
        in_specs += [P(head, None, None), P(head, None, None)]
        operands += [k_scales, v_scales]
    ring = ring_k is not None
    if ring:
        in_specs += [
            P(bspec, head, None, None),
            P(bspec, head, None, None),
            P(),                     # ring_step scalar
        ]
        operands += [ring_k, ring_v, jnp.asarray(ring_step, jnp.int32)]

    def fn(q_, kp_, vp_, tb_, lv_, qp_, *rest):
        i = 0
        ks_ = vs_ = None
        if quantized:
            ks_, vs_ = rest[0], rest[1]
            i = 2
        rk_ = rv_ = rs_ = None
        if ring:
            rk_, rv_, rs_ = rest[i], rest[i + 1], rest[i + 2]
        return paged_decode_attention(
            q_, kp_, vp_, tb_, lv_, q_positions=qp_,
            n_blocks=n_blocks, scale=scale, softcap=softcap,
            window=window, q_blocks=q_blocks,
            k_scales=ks_, v_scales=vs_, n_strip=n_strip,
            ring_k=rk_, ring_v=rv_, ring_step=rs_,
            interpret=interpret,
        )

    return compat_shard_map(
        fn,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(
            P(bspec, head, None),    # acc [B, N, H]
            P(bspec, head),          # m   [B, N]
            P(bspec, head),          # l   [B, N]
        ),
        check_vma=False,
    )(*operands)


__all__ = [
    "paged_decode_attention",
    "paged_decode_attention_sharded",
    "paged_sharding_ok",
    "strip_vmem_bytes",
]
