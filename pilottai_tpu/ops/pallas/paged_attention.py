"""Paged decode attention as a Pallas TPU kernel.

The paged cache (``ops/paged.py``) stores K/V in a shared page pool with
block-table indirection; this kernel reads ONLY the pages a slot
actually occupies. The trick is scalar-prefetched index maps: the block
table lands in SMEM before the grid runs, and each (slot, page-slot)
grid cell's BlockSpec *computes its pool coordinates from the table* —
pages stream HBM→VMEM directly by id, no dense [B, S, H] gather ever
exists.

Grid is (B, bounded-page-count) with the page dim innermost; the
(acc, m, l) online-softmax outputs map to the same block for every page
step, so they stay VMEM-resident and accumulate across pages (the same
revisited-output reduction the flash backward uses). Cells whose page
slot is unallocated or fully past the valid length clamp their DMA to
the scratch page and skip compute with ``pl.when``.

Returns unnormalized (acc, m, l) stats — the fused decode chunk
(``engine/decode.py``) combines them with the in-chunk ring attention,
same contract as ``decode_attention(return_stats=True)``.

Design follows the ragged paged attention literature cited in PAPERS.md.
No reference counterpart; VERDICT.md next-step 7.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -2.0**30


def _paged_kernel(
    table_ref,  # SMEM (B, max_pages) int32 (scalar prefetch)
    last_ref,   # SMEM (B,) int32 — max valid key index per slot
    qpos_ref,   # SMEM (B,) int32 — query absolute position (sliding window)
    q_ref,      # VMEM (1, K, G, H)
    k_ref,      # VMEM (K, 1, P, H) — one page, all kv heads
    v_ref,      # VMEM (K, 1, P, H)
    *rest,      # [ks_ref (K,1,P,1), vs_ref (K,1,P,1) when quantized,]
                # acc_ref (1,K,G,H) f32, m_ref (1,K,G,1), l_ref (1,K,G,1)
    scale: float,
    softcap: float,
    window: int,
    page_size: int,
    sentinel: int,
    q_blocks: int,
    quantized: bool,
):
    if quantized:
        ks_ref, vs_ref, acc_ref, m_ref, l_ref = rest
    else:
        acc_ref, m_ref, l_ref = rest
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    last = last_ref[b]
    qpos = qpos_ref[b]
    page = table_ref[b, j]
    j0 = j * page_size
    live = (page != sentinel) & (j0 <= last)
    if window > 0:
        # Most-permissive query decides page liveness: (qpos_row - col) <
        # window is EASIEST to satisfy at the smallest position, i.e.
        # row d=0 at qpos — later rows only tighten, and the per-row
        # mask below applies them exactly.
        live &= (qpos - (j0 + page_size - 1)) < window

    @pl.when(live)
    def _attend():
        q = q_ref[0]                                          # [K, G, H]
        k = k_ref[:, 0]                                       # [K, P, H]
        v = v_ref[:, 0]
        if quantized:
            # In-VMEM dequant: the HBM→VMEM stream stays int8-sized.
            # Scale blocks ride as (K, 1, P, 1) — the trailing singleton
            # satisfies the TPU lowering's last-two-dims constraint.
            k = k.astype(jnp.float32) * ks_ref[:, 0]
            v = v.astype(jnp.float32) * vs_ref[:, 0]
            q = q.astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale                                             # [K, G, P]
        if softcap > 0.0:
            s = jnp.tanh(s / softcap) * softcap
        col = j0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        mask = col <= last
        if window > 0:
            # Speculative blocks pack D queries per G row (row = g*D + d,
            # query d at position qpos + d).
            qpos_row = qpos + (
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) % q_blocks
                if q_blocks > 1 else 0
            )
            mask &= (qpos_row - col) < window
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[0, :, :, :]                            # [K, G, 1]
        l_prev = l_ref[0, :, :, :]
        acc_prev = acc_ref[0]
        m_blk = jnp.max(s, axis=-1, keepdims=True)            # [K, G, 1]
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.where(m_new > NEG_INF / 2, jnp.exp(s - m_new), 0.0)
        corr = jnp.where(
            m_prev > NEG_INF / 2, jnp.exp(m_prev - m_new), 0.0
        )
        l_ref[0, :, :, :] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                                     # [K, G, H]
        acc_ref[0] = acc_prev * corr + pv
        m_ref[0, :, :, :] = m_new


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_blocks", "scale", "softcap", "window", "q_blocks", "interpret"
    ),
)
def paged_decode_attention(
    q: jax.Array,        # [B, N, H] current-token queries; with q_blocks=D
                         # the N axis packs D block queries per head
                         # (row = head * D + d, query d at position
                         # q_positions + d) — the speculative-decode shape
    k_pool: jax.Array,   # [K, num_pages, P, H]
    v_pool: jax.Array,
    table: jax.Array,    # [B, max_pages] int32 (sentinel = num_pages - 1)
    last_valid: jax.Array,   # [B] int32 — keys at s <= last_valid[b] attend
    q_positions: Optional[jax.Array] = None,  # [B]; defaults to last_valid
    n_blocks: int = 0,   # static — page slots to visit (bounded by host)
    scale: Optional[float] = None,
    softcap: float = 0.0,
    window: int = 0,
    q_blocks: int = 1,   # static — queries per head row (speculation's D)
    k_scales: Optional[jax.Array] = None,  # [K, num_pages, P] — int8 pools
    v_scales: Optional[jax.Array] = None,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Ragged paged GQA decode attention. Returns unnormalized
    ``(acc [B,N,H] fp32, m [B,N], l [B,N])`` online-softmax stats over
    each slot's first ``n_blocks`` pages."""
    B, N, H = q.shape
    K, num_pages, P, _ = k_pool.shape
    assert N % K == 0
    G = N // K
    assert G % q_blocks == 0
    assert 1 <= n_blocks <= table.shape[1]
    scale = scale if scale is not None else H ** -0.5
    sentinel = num_pages - 1

    qg = q.reshape(B, K, G, H)
    last_valid = jnp.asarray(last_valid, jnp.int32).reshape(B)
    if q_positions is None:
        q_positions = last_valid
    q_positions = jnp.asarray(q_positions, jnp.int32).reshape(B)
    table = jnp.asarray(table, jnp.int32)

    quantized = k_scales is not None
    assert (k_scales is None) == (v_scales is None)
    kernel = functools.partial(
        _paged_kernel,
        scale=scale, softcap=softcap, window=window,
        page_size=P, sentinel=sentinel, q_blocks=q_blocks,
        quantized=quantized,
    )

    def page_map(b, j, table_ref, last_ref, qpos_ref):
        # Clamp sentinel to a real page id: the DMA must target valid
        # memory; the kernel's `live` predicate skips the compute.
        return (0, jnp.minimum(table_ref[b, j], sentinel), 0, 0)

    in_specs = [
        pl.BlockSpec((1, K, G, H), lambda b, j, *_: (b, 0, 0, 0)),
        pl.BlockSpec((K, 1, P, H), page_map),
        pl.BlockSpec((K, 1, P, H), page_map),
    ]
    operands = [qg, k_pool, v_pool]
    if quantized:
        # Trailing singleton: TPU lowering requires the last two block
        # dims be (8k, 128k) or equal the array dims — (P, 1) qualifies.
        in_specs += [
            pl.BlockSpec((K, 1, P, 1), page_map),
            pl.BlockSpec((K, 1, P, 1), page_map),
        ]
        operands += [
            k_scales.astype(jnp.float32)[..., None],
            v_scales.astype(jnp.float32)[..., None],
        ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # table, last, qpos in SMEM
        grid=(B, n_blocks),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, K, G, H), lambda b, j, *_: (b, 0, 0, 0)),
            pl.BlockSpec((1, K, G, 1), lambda b, j, *_: (b, 0, 0, 0)),
            pl.BlockSpec((1, K, G, 1), lambda b, j, *_: (b, 0, 0, 0)),
        ),
    )
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((B, K, G, H), jnp.float32),
            jax.ShapeDtypeStruct((B, K, G, 1), jnp.float32),
            jax.ShapeDtypeStruct((B, K, G, 1), jnp.float32),
        ),
        interpret=interpret,
    )(table, last_valid, q_positions, *operands)
    return acc.reshape(B, N, H), m.reshape(B, N), l.reshape(B, N)


__all__ = ["paged_decode_attention"]
