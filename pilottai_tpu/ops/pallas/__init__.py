"""Pallas TPU kernels for the hot ops (SURVEY.md §7 hard part 1)."""

from pilottai_tpu.ops.pallas.flash_attention import flash_attention

__all__ = ["flash_attention"]
