"""Flash attention (online softmax) as a Pallas TPU kernel.

Replaces the O(T·S)-memory XLA attention (``ops/attention.py``) for large
prefills: logits are never materialized; each (batch, head, q-block) grid
cell streams KV blocks through VMEM keeping running max/sum statistics in
fp32. Matmuls hit the MXU in bf16; masking (causal from absolute
positions, per-layer sliding window, valid-length) is computed in-kernel
so no [B, T, S] mask array ever exists in HBM.

Fully-masked KV blocks (beyond the causal horizon or the valid length)
are skipped with ``lax.cond`` — for causal prefill that halves the work.

No reference counterpart: the reference computes no attention at all
(SURVEY.md §2.13); this is the serving engine's hot op.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -2.0**30


def _flash_kernel(
    window_ref,   # SMEM (1,) int32 (scalar prefetch) — sliding window; 0 = global
    valid_ref,    # SMEM (B,) int32 (scalar prefetch) — valid kv length per batch row
    qpos_ref,     # VMEM (1, 1, bq)     — absolute positions of the q block
    kpos_ref,     # VMEM (1, 1, S)      — absolute positions of all keys
    q_ref,        # VMEM (1, 1, bq, H)  — head-major layout
    k_ref,        # VMEM (1, 1, S, H)
    v_ref,        # VMEM (1, 1, S, H)
    o_ref,        # VMEM (1, 1, bq, H)
    *,
    scale: float,
    softcap: float,
    block_k: int,
):
    bq = q_ref.shape[2]
    H = q_ref.shape[3]
    S = k_ref.shape[2]
    n_kb = S // block_k

    q = q_ref[0, 0, :, :]                                    # [bq, H] bf16

    qpos = qpos_ref[0, 0, :].reshape(bq, 1)                  # [bq, 1]
    window = window_ref[0]
    valid = valid_ref[pl.program_id(0)]
    qpos_max = jnp.max(qpos)

    def body(kb, carry):
        m, l, acc = carry
        j0 = kb * block_k
        kpos = kpos_ref[0, 0, pl.ds(j0, block_k)].reshape(1, block_k)
        jidx = j0 + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)

        # Block-level skip: every key in this block is after every query
        # (causal), past the valid length, or older than the sliding
        # window for every query -> contributes nothing.
        block_live = (jnp.min(kpos) <= qpos_max) & (j0 < valid)
        block_live &= (window <= 0) | ((jnp.min(qpos) - jnp.max(kpos)) < window)

        def attend(carry):
            m, l, acc = carry
            k = k_ref[0, 0, pl.ds(j0, block_k), :]           # [bk, H]
            v = v_ref[0, 0, pl.ds(j0, block_k), :]           # [bk, H]
            # bf16 × bf16 on the MXU, fp32 accumulate; scale folded in
            # afterwards so the matmul itself stays at full MXU rate.
            s = jax.lax.dot_general(
                q, k,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                         # [bq, bk]
            if softcap > 0.0:
                s = jnp.tanh(s / softcap) * softcap
            mask = (kpos <= qpos) & (jidx < valid)
            # (window <= 0) | in_window, as pure boolean algebra — Mosaic
            # cannot legalize select over i1 vectors.
            mask &= (window <= 0) | ((qpos - kpos) < window)
            s = jnp.where(mask, s, NEG_INF)

            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)                            # [bq, bk]
            corr = jnp.exp(m - m_new)                         # [bq, 1]
            l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
            pv = jax.lax.dot_general(
                p.astype(v.dtype), v,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                                 # [bq, H]
            acc_new = acc * corr + pv
            return m_new, l_new, acc_new

        return jax.lax.cond(block_live, attend, lambda c: c, (m, l, acc))

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, H), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_kb, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-30)
    out = jnp.where(l > 0.0, out, 0.0)                        # fully-masked rows
    o_ref[0, 0, :, :] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "softcap", "block_q", "block_k", "interpret"),
)
def flash_attention(
    q: jax.Array,          # [B, T, N, H]
    k: jax.Array,          # [B, S, K, H]
    v: jax.Array,          # [B, S, K, H]
    q_positions: jax.Array,   # [B, T] absolute positions
    kv_positions: jax.Array,  # [B, S] absolute positions
    valid: jax.Array,         # [B] valid kv length (sequence index bound)
    window: jax.Array,        # scalar int32; 0 = global attention
    scale: Optional[float] = None,
    softcap: float = 0.0,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Causal GQA flash attention. Mask semantics match
    ``models/transformer.py`` prefill: attend iff kv_pos <= q_pos, kv index
    < valid, and (window == 0 or q_pos - kv_pos < window)."""
    B, T, N, H = q.shape
    _, S, K, _ = k.shape
    assert N % K == 0
    G = N // K
    assert T % block_q == 0, f"T={T} not divisible by block_q={block_q}"
    assert S % block_k == 0, f"S={S} not divisible by block_k={block_k}"
    scale = scale if scale is not None else H ** -0.5

    window = jnp.asarray(window, jnp.int32).reshape(1)
    valid = jnp.asarray(valid, jnp.int32).reshape(B)
    qpos = jnp.asarray(q_positions, jnp.int32)[:, None, :]   # [B, 1, T]
    kpos = jnp.asarray(kv_positions, jnp.int32)[:, None, :]  # [B, 1, S]

    # Head-major layout so blocks tile as (bq, H)/(S, H) — the TPU lowering
    # requires the last two block dims be tile-aligned or full.
    q_t = q.transpose(0, 2, 1, 3)                            # [B, N, T, H]
    k_t = k.transpose(0, 2, 1, 3)                            # [B, K, S, H]
    v_t = v.transpose(0, 2, 1, 3)

    kernel = functools.partial(
        _flash_kernel, scale=scale, softcap=softcap, block_k=block_k
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # window, valid land in SMEM pre-kernel
        grid=(B, N, T // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q), lambda b, n, i, *_: (b, 0, i)),
            pl.BlockSpec((1, 1, S), lambda b, n, i, *_: (b, 0, 0)),
            pl.BlockSpec((1, 1, block_q, H), lambda b, n, i, *_: (b, n, i, 0)),
            pl.BlockSpec((1, 1, S, H), lambda b, n, i, *_: (b, n // G, 0, 0)),
            pl.BlockSpec((1, 1, S, H), lambda b, n, i, *_: (b, n // G, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, H), lambda b, n, i, *_: (b, n, i, 0)
        ),
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q_t.shape, q.dtype),
        interpret=interpret,
    )(window, valid, qpos, kpos, q_t, k_t, v_t)
    return out.transpose(0, 2, 1, 3)                         # back to [B, T, N, H]
