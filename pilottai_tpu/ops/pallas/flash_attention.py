"""Flash attention (online softmax) as Pallas TPU kernels — fwd AND bwd.

Replaces the O(T·S)-memory XLA attention (``ops/attention.py``) for large
prefills: logits are never materialized; each (batch, head, q-block) grid
cell streams KV blocks through VMEM keeping running max/sum statistics in
fp32. Matmuls hit the MXU in bf16; masking (causal from absolute
positions, per-layer sliding window, valid-length) is computed in-kernel
so no [B, T, S] mask array ever exists in HBM.

The op carries a ``jax.custom_vjp``: the forward kernel also emits the
log-sum-exp rows, and two backward kernels recompute probabilities
blockwise (the standard flash backward) —

* ``dq``: grid (B, N, T/bq), K/V resident, accumulate dq per q-block;
* ``dk/dv``: grid (B, K, S/bk, T/bq) with the q-block dim innermost, so
  the kv-block outputs stay resident across q steps and accumulate
  in-place (Mosaic's revisited-output reduction pattern); the G query
  heads of each kv head are processed in-cell, so dk/dv come out already
  group-summed.

so training runs through the kernel instead of silently falling back to
XLA attention (VERDICT.md Weak #4 / next-step 8).

Fully-masked KV blocks (beyond the causal horizon or the valid length)
are skipped with ``lax.cond`` — for causal prefill that halves the work.

Multi-chip: ``flash_attention_sharded`` wraps the kernel in ``shard_map``
(batch over data/fsdp, heads over model — attention is embarrassingly
parallel across both), so TP meshes keep the fast path instead of
dropping to XLA dense.

No reference counterpart: the reference computes no attention at all
(SURVEY.md §2.13); this is the serving engine's hot op.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from pilottai_tpu.parallel.mesh import compat_shard_map

NEG_INF = -2.0**30


# --------------------------------------------------------------------- #
# Forward kernel
# --------------------------------------------------------------------- #

def _flash_kernel(
    window_ref,   # SMEM (1,) int32 (scalar prefetch) — sliding window; 0 = global
    valid_ref,    # SMEM (B,) int32 (scalar prefetch) — valid kv length per batch row
    qpos_ref,     # VMEM (1, 1, bq)     — absolute positions of the q block
    kpos_ref,     # VMEM (1, 1, S)      — absolute positions of all keys
    q_ref,        # VMEM (1, 1, bq, H)  — head-major layout
    k_ref,        # VMEM (1, 1, S, H)
    v_ref,        # VMEM (1, 1, S, H)
    o_ref,        # VMEM (1, 1, bq, H)
    lse_ref,      # VMEM (1, 1, bq, 1) fp32 — log-sum-exp rows (for the VJP;
                  # trailing singleton keeps the last two block dims
                  # Mosaic-tileable: (bq, 1) vs array dims (T, 1))
    *,
    scale: float,
    softcap: float,
    block_k: int,
):
    bq = q_ref.shape[2]
    H = q_ref.shape[3]
    S = k_ref.shape[2]
    n_kb = S // block_k

    q = q_ref[0, 0, :, :]                                    # [bq, H] bf16

    qpos = qpos_ref[0, 0, :].reshape(bq, 1)                  # [bq, 1]
    window = window_ref[0]
    valid = valid_ref[pl.program_id(0)]
    qpos_max = jnp.max(qpos)

    def body(kb, carry):
        m, l, acc = carry
        j0 = kb * block_k
        kpos = kpos_ref[0, 0, pl.ds(j0, block_k)].reshape(1, block_k)
        jidx = j0 + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)

        # Block-level skip: every key in this block is after every query
        # (causal), past the valid length, or older than the sliding
        # window for every query -> contributes nothing.
        block_live = (jnp.min(kpos) <= qpos_max) & (j0 < valid)
        block_live &= (window <= 0) | ((jnp.min(qpos) - jnp.max(kpos)) < window)

        def attend(carry):
            m, l, acc = carry
            k = k_ref[0, 0, pl.ds(j0, block_k), :]           # [bk, H]
            v = v_ref[0, 0, pl.ds(j0, block_k), :]           # [bk, H]
            # bf16 × bf16 on the MXU, fp32 accumulate; scale folded in
            # afterwards so the matmul itself stays at full MXU rate.
            s = jax.lax.dot_general(
                q, k,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                         # [bq, bk]
            if softcap > 0.0:
                s = jnp.tanh(s / softcap) * softcap
            mask = (kpos <= qpos) & (jidx < valid)
            # (window <= 0) | in_window, as pure boolean algebra — Mosaic
            # cannot legalize select over i1 vectors.
            mask &= (window <= 0) | ((qpos - kpos) < window)
            s = jnp.where(mask, s, NEG_INF)

            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)                            # [bq, bk]
            corr = jnp.exp(m - m_new)                         # [bq, 1]
            l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
            pv = jax.lax.dot_general(
                p.astype(v.dtype), v,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                                 # [bq, H]
            acc_new = acc * corr + pv
            return m_new, l_new, acc_new

        return jax.lax.cond(block_live, attend, lambda c: c, (m, l, acc))

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, H), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_kb, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-30)
    out = jnp.where(l > 0.0, out, 0.0)                        # fully-masked rows
    o_ref[0, 0, :, :] = out.astype(o_ref.dtype)
    lse = jnp.where(
        l > 0.0, m + jnp.log(jnp.maximum(l, 1e-30)), NEG_INF
    )                                                         # [bq, 1]
    lse_ref[0, 0, :, :] = lse


def _fwd_impl(
    q, k, v, q_positions, kv_positions, valid, window,
    scale, softcap, block_q, block_k, interpret,
) -> Tuple[jax.Array, jax.Array]:
    """Runs the forward kernel. Returns (o [B,T,N,H], lse [B,N,T] fp32)."""
    B, T, N, H = q.shape
    _, S, K, _ = k.shape
    assert N % K == 0
    G = N // K
    assert T % block_q == 0, f"T={T} not divisible by block_q={block_q}"
    assert S % block_k == 0, f"S={S} not divisible by block_k={block_k}"

    window = jnp.asarray(window, jnp.int32).reshape(1)
    valid = jnp.asarray(valid, jnp.int32).reshape(B)
    qpos = jnp.asarray(q_positions, jnp.int32)[:, None, :]   # [B, 1, T]
    kpos = jnp.asarray(kv_positions, jnp.int32)[:, None, :]  # [B, 1, S]

    # Head-major layout so blocks tile as (bq, H)/(S, H) — the TPU lowering
    # requires the last two block dims be tile-aligned or full.
    q_t = q.transpose(0, 2, 1, 3)                            # [B, N, T, H]
    k_t = k.transpose(0, 2, 1, 3)                            # [B, K, S, H]
    v_t = v.transpose(0, 2, 1, 3)

    kernel = functools.partial(
        _flash_kernel, scale=scale, softcap=softcap, block_k=block_k
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # window, valid land in SMEM pre-kernel
        grid=(B, N, T // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q), lambda b, n, i, *_: (b, 0, i)),
            pl.BlockSpec((1, 1, S), lambda b, n, i, *_: (b, 0, 0)),
            pl.BlockSpec((1, 1, block_q, H), lambda b, n, i, *_: (b, n, i, 0)),
            pl.BlockSpec((1, 1, S, H), lambda b, n, i, *_: (b, n // G, 0, 0)),
            pl.BlockSpec((1, 1, S, H), lambda b, n, i, *_: (b, n // G, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, block_q, H), lambda b, n, i, *_: (b, n, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, n, i, *_: (b, n, i, 0)),
        ),
    )
    o_t, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct(q_t.shape, q.dtype),
            jax.ShapeDtypeStruct((B, N, T, 1), jnp.float32),
        ),
        interpret=interpret,
    )(window, valid, qpos, kpos, q_t, k_t, v_t)
    return o_t.transpose(0, 2, 1, 3), lse                    # o [B,T,N,H]; lse [B,N,T,1]


# --------------------------------------------------------------------- #
# Backward kernels
# --------------------------------------------------------------------- #

def _bwd_dq_kernel(
    window_ref,   # SMEM (1,)
    valid_ref,    # SMEM (B,)
    qpos_ref,     # VMEM (1, 1, bq)
    kpos_ref,     # VMEM (1, 1, S)
    q_ref,        # VMEM (1, 1, bq, H)
    k_ref,        # VMEM (1, 1, S, H)
    v_ref,        # VMEM (1, 1, S, H)
    do_ref,       # VMEM (1, 1, bq, H)
    lse_ref,      # VMEM (1, 1, bq, 1) fp32
    delta_ref,    # VMEM (1, 1, bq, 1) fp32 — rowsum(dO * O)
    dq_ref,       # VMEM (1, 1, bq, H)
    *,
    scale: float,
    softcap: float,
    block_k: int,
):
    bq, H = q_ref.shape[2], q_ref.shape[3]
    S = k_ref.shape[2]
    n_kb = S // block_k

    q = q_ref[0, 0, :, :]
    do = do_ref[0, 0, :, :].astype(jnp.float32)
    lse = lse_ref[0, 0, :, :]                                 # [bq, 1]
    delta = delta_ref[0, 0, :, :]                             # [bq, 1]
    qpos = qpos_ref[0, 0, :].reshape(bq, 1)
    window = window_ref[0]
    valid = valid_ref[pl.program_id(0)]
    qpos_max = jnp.max(qpos)

    def body(kb, dq_acc):
        j0 = kb * block_k
        kpos = kpos_ref[0, 0, pl.ds(j0, block_k)].reshape(1, block_k)
        jidx = j0 + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        block_live = (jnp.min(kpos) <= qpos_max) & (j0 < valid)
        block_live &= (window <= 0) | ((jnp.min(qpos) - jnp.max(kpos)) < window)

        def attend(dq_acc):
            k = k_ref[0, 0, pl.ds(j0, block_k), :]
            v = v_ref[0, 0, pl.ds(j0, block_k), :]
            s = jax.lax.dot_general(
                q, k, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                         # [bq, bk]
            if softcap > 0.0:
                t = jnp.tanh(s / softcap)
                s_c = t * softcap
            else:
                s_c = s
            mask = (kpos <= qpos) & (jidx < valid)
            mask &= (window <= 0) | ((qpos - kpos) < window)
            p = jnp.where(mask, jnp.exp(s_c - lse), 0.0)      # true softmax rows
            dp = jax.lax.dot_general(
                do, v.astype(jnp.float32),
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                                 # [bq, bk]
            ds = p * (dp - delta)
            if softcap > 0.0:
                ds = ds * (1.0 - t * t)
            return dq_acc + jax.lax.dot_general(
                ds.astype(k.dtype), k,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale

        return jax.lax.cond(block_live, attend, lambda a: a, dq_acc)

    dq = jax.lax.fori_loop(0, n_kb, body, jnp.zeros((bq, H), jnp.float32))
    dq_ref[0, 0, :, :] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    window_ref,   # SMEM (1,)
    valid_ref,    # SMEM (B,)
    qpos_ref,     # VMEM (1, 1, bq)
    kpos_ref,     # VMEM (1, 1, bk)
    q_ref,        # VMEM (1, G, bq, H) — all G query heads of this kv head
    k_ref,        # VMEM (1, 1, bk, H)
    v_ref,        # VMEM (1, 1, bk, H)
    do_ref,       # VMEM (1, G, bq, H)
    lse_ref,      # VMEM (1, G, bq, 1) fp32
    delta_ref,    # VMEM (1, G, bq, 1) fp32
    dk_ref,       # VMEM (1, 1, bk, H) fp32 — accumulated across q blocks
    dv_ref,       # VMEM (1, 1, bk, H) fp32
    *,
    scale: float,
    softcap: float,
):
    G = q_ref.shape[1]
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]
    i = pl.program_id(3)  # q-block index — innermost, outputs revisited

    @pl.when(i == 0)
    def _init():
        dk_ref[...] = jnp.zeros_like(dk_ref)
        dv_ref[...] = jnp.zeros_like(dv_ref)

    qpos = qpos_ref[0, 0, :].reshape(bq, 1)
    kpos = kpos_ref[0, 0, :].reshape(1, bk)
    window = window_ref[0]
    valid = valid_ref[pl.program_id(0)]
    j0 = pl.program_id(2) * bk
    jidx = j0 + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)

    block_live = (jnp.min(kpos) <= jnp.max(qpos)) & (j0 < valid)
    block_live &= (window <= 0) | ((jnp.min(qpos) - jnp.max(kpos)) < window)

    @pl.when(block_live)
    def _body():
        kk = k_ref[0, 0, :, :]                                # [bk, H]
        vv = v_ref[0, 0, :, :]
        mask = (kpos <= qpos) & (jidx < valid)
        mask &= (window <= 0) | ((qpos - kpos) < window)
        dk_acc = jnp.zeros((bk, kk.shape[1]), jnp.float32)
        dv_acc = jnp.zeros_like(dk_acc)
        for g in range(G):                                    # static unroll
            qg = q_ref[0, g, :, :]                            # [bq, H]
            dog = do_ref[0, g, :, :].astype(jnp.float32)
            lse = lse_ref[0, g, :, :]                         # [bq, 1]
            delta = delta_ref[0, g, :, :]                     # [bq, 1]
            s = jax.lax.dot_general(
                qg, kk, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                         # [bq, bk]
            if softcap > 0.0:
                t = jnp.tanh(s / softcap)
                s_c = t * softcap
            else:
                s_c = s
            p = jnp.where(mask, jnp.exp(s_c - lse), 0.0)
            # dv += p^T @ dO
            dv_acc += jax.lax.dot_general(
                p.astype(vv.dtype), dog.astype(vv.dtype),
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dp = jax.lax.dot_general(
                dog, vv.astype(jnp.float32),
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta)
            if softcap > 0.0:
                ds = ds * (1.0 - t * t)
            # dk += ds^T @ q * scale
            dk_acc += jax.lax.dot_general(
                ds.astype(qg.dtype), qg,
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
        dk_ref[0, 0, :, :] += dk_acc
        dv_ref[0, 0, :, :] += dv_acc


def _bwd_impl(
    q, k, v, q_positions, kv_positions, valid, window, o, lse, do,
    scale, softcap, block_q, block_k, interpret, dlse=None,
):
    B, T, N, H = q.shape
    _, S, K, _ = k.shape
    G = N // K

    window = jnp.asarray(window, jnp.int32).reshape(1)
    valid = jnp.asarray(valid, jnp.int32).reshape(B)
    qpos = jnp.asarray(q_positions, jnp.int32)[:, None, :]
    kpos = jnp.asarray(kv_positions, jnp.int32)[:, None, :]

    q_t = q.transpose(0, 2, 1, 3)                            # [B, N, T, H]
    k_t = k.transpose(0, 2, 1, 3)                            # [B, K, S, H]
    v_t = v.transpose(0, 2, 1, 3)
    do_t = do.transpose(0, 2, 1, 3)
    # delta = rowsum(dO * O), fp32 — [B, N, T, 1]
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1)[..., None]
    if dlse is not None:
        # lse cotangent (flash_attention_with_lse): d lse_i / d s_ij = p_ij,
        # so ds_ij = p_ij (dp_ij - delta_i + dlse_i) — exactly the delta
        # operand shifted. No kernel change needed.
        delta = delta - dlse.astype(jnp.float32)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, softcap=softcap, block_k=block_k
    )
    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, N, T // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q), lambda b, n, i, *_: (b, 0, i)),
            pl.BlockSpec((1, 1, S), lambda b, n, i, *_: (b, 0, 0)),
            pl.BlockSpec((1, 1, block_q, H), lambda b, n, i, *_: (b, n, i, 0)),
            pl.BlockSpec((1, 1, S, H), lambda b, n, i, *_: (b, n // G, 0, 0)),
            pl.BlockSpec((1, 1, S, H), lambda b, n, i, *_: (b, n // G, 0, 0)),
            pl.BlockSpec((1, 1, block_q, H), lambda b, n, i, *_: (b, n, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, n, i, *_: (b, n, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, n, i, *_: (b, n, i, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, H), lambda b, n, i, *_: (b, n, i, 0)
        ),
    )
    dq_t = pl.pallas_call(
        dq_kernel,
        grid_spec=dq_spec,
        out_shape=jax.ShapeDtypeStruct(q_t.shape, q.dtype),
        interpret=interpret,
    )(window, valid, qpos, kpos, q_t, k_t, v_t, do_t, lse, delta)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, softcap=softcap
    )
    # q-block dim innermost: dk/dv blocks are revisited and accumulate.
    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, K, S // block_k, T // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q), lambda b, h, j, i, *_: (b, 0, i)),
            pl.BlockSpec((1, 1, block_k), lambda b, h, j, i, *_: (b, 0, j)),
            pl.BlockSpec(
                (1, G, block_q, H), lambda b, h, j, i, *_: (b, h, i, 0)
            ),
            pl.BlockSpec((1, 1, block_k, H), lambda b, h, j, i, *_: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, H), lambda b, h, j, i, *_: (b, h, j, 0)),
            pl.BlockSpec(
                (1, G, block_q, H), lambda b, h, j, i, *_: (b, h, i, 0)
            ),
            pl.BlockSpec(
                (1, G, block_q, 1), lambda b, h, j, i, *_: (b, h, i, 0)
            ),
            pl.BlockSpec(
                (1, G, block_q, 1), lambda b, h, j, i, *_: (b, h, i, 0)
            ),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, block_k, H), lambda b, h, j, i, *_: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, H), lambda b, h, j, i, *_: (b, h, j, 0)),
        ),
    )
    dk_t, dv_t = pl.pallas_call(
        dkv_kernel,
        grid_spec=dkv_spec,
        out_shape=(
            jax.ShapeDtypeStruct(k_t.shape, jnp.float32),
            jax.ShapeDtypeStruct(v_t.shape, jnp.float32),
        ),
        interpret=interpret,
    )(window, valid, qpos, kpos, q_t, k_t, v_t, do_t, lse, delta)

    dq = dq_t.transpose(0, 2, 1, 3)
    dk = dk_t.transpose(0, 2, 1, 3).astype(k.dtype)
    dv = dv_t.transpose(0, 2, 1, 3).astype(v.dtype)
    return dq, dk, dv


# --------------------------------------------------------------------- #
# custom_vjp wiring
# --------------------------------------------------------------------- #

@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def _flash_lse(scale, softcap, block_q, block_k, interpret,
               q, k, v, q_positions, kv_positions, valid, window):
    """THE vjp-carrying op: forward returns (o, lse). Plain
    ``flash_attention`` discards lse (its zero cotangent makes
    ``delta - dlse`` collapse to the standard flash backward), so one
    set of vjp rules serves both entry points."""
    return _fwd_impl(
        q, k, v, q_positions, kv_positions, valid, window,
        scale, softcap, block_q, block_k, interpret,
    )


def _flash_lse_fwd_rule(scale, softcap, block_q, block_k, interpret,
                        q, k, v, q_positions, kv_positions, valid, window):
    o, lse = _fwd_impl(
        q, k, v, q_positions, kv_positions, valid, window,
        scale, softcap, block_q, block_k, interpret,
    )
    return (o, lse), (q, k, v, q_positions, kv_positions, valid, window, o, lse)


def _flash_lse_bwd_rule(scale, softcap, block_q, block_k, interpret, res, ct):
    q, k, v, q_positions, kv_positions, valid, window, o, lse = res
    do, dlse = ct
    dq, dk, dv = _bwd_impl(
        q, k, v, q_positions, kv_positions, valid, window, o, lse, do,
        scale, softcap, block_q, block_k, interpret, dlse=dlse,
    )

    def f0(x):
        return np.zeros(jnp.shape(x), dtype=jax.dtypes.float0)

    return (dq, dk, dv, f0(q_positions), f0(kv_positions), f0(valid), f0(window))


_flash_lse.defvjp(_flash_lse_fwd_rule, _flash_lse_bwd_rule)


def _pad_to_blocks(q, k, v, q_positions, kv_positions, block_q, block_k):
    """Pad T to a block_q multiple and S to a block_k multiple so ragged
    training shapes stay on the Pallas path (VERDICT r2 next-step 8).
    Positions edge-replicate (keeps the causal horizon and block-skip
    bounds sane); K/V pad with zeros and are masked by the kernel's
    ``jidx < valid`` check; padded QUERY rows produce garbage the caller
    slices off — and since the pad/slice pair differentiates cleanly,
    their gradient contribution is exactly zero."""
    T, S = q.shape[1], k.shape[1]
    Tp = -(-T // block_q) * block_q
    Sp = -(-S // block_k) * block_k
    if Tp != T:
        q = jnp.pad(q, ((0, 0), (0, Tp - T), (0, 0), (0, 0)))
        q_positions = jnp.pad(
            q_positions, ((0, 0), (0, Tp - T)), mode="edge"
        )
    if Sp != S:
        k = jnp.pad(k, ((0, 0), (0, Sp - S), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, Sp - S), (0, 0), (0, 0)))
        kv_positions = jnp.pad(
            kv_positions, ((0, 0), (0, Sp - S)), mode="edge"
        )
    return q, k, v, q_positions, kv_positions, T


@functools.partial(
    jax.jit,
    static_argnames=("scale", "softcap", "block_q", "block_k", "interpret"),
)
def flash_attention_with_lse(
    q: jax.Array,             # [B, T, N, H]
    k: jax.Array,             # [B, S, K, H]
    v: jax.Array,             # [B, S, K, H]
    q_positions: jax.Array,   # [B, T]
    kv_positions: jax.Array,  # [B, S]
    valid: jax.Array,         # [B] valid kv length (kv INDEX bound)
    window: jax.Array,
    scale: Optional[float] = None,
    softcap: float = 0.0,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Like ``flash_attention`` but also returns the log-sum-exp rows
    ``[B, T, N, 1]`` (NEG_INF where the row saw no keys) so disjoint
    KV chunks can be merged exactly — ring attention's per-step form.
    Differentiable in (q, k, v) INCLUDING through lse. Ragged T/S pad
    to block multiples internally."""
    H = q.shape[-1]
    scale = scale if scale is not None else H ** -0.5
    q, k, v, q_positions, kv_positions, T = _pad_to_blocks(
        q, k, v, q_positions, kv_positions, block_q, block_k
    )
    o, lse = _flash_lse(
        scale, softcap, block_q, block_k, interpret,
        q, k, v, q_positions, kv_positions, valid, window,
    )
    return o[:, :T], lse.transpose(0, 2, 1, 3)[:, :T]  # lse -> [B, T, N, 1]


@functools.partial(
    jax.jit,
    static_argnames=("scale", "softcap", "block_q", "block_k", "interpret"),
)
def flash_attention(
    q: jax.Array,          # [B, T, N, H]
    k: jax.Array,          # [B, S, K, H]
    v: jax.Array,          # [B, S, K, H]
    q_positions: jax.Array,   # [B, T] absolute positions
    kv_positions: jax.Array,  # [B, S] absolute positions
    valid: jax.Array,         # [B] valid kv length (sequence index bound)
    window: jax.Array,        # scalar int32; 0 = global attention
    scale: Optional[float] = None,
    softcap: float = 0.0,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Causal GQA flash attention, differentiable in (q, k, v). Mask
    semantics match ``models/transformer.py`` prefill: attend iff
    kv_pos <= q_pos, kv index < valid, and (window == 0 or
    q_pos - kv_pos < window). Ragged T/S pad to block multiples
    internally (the pad/slice pair contributes zero gradient)."""
    H = q.shape[-1]
    scale = scale if scale is not None else H ** -0.5
    q, k, v, q_positions, kv_positions, T = _pad_to_blocks(
        q, k, v, q_positions, kv_positions, block_q, block_k
    )
    out, _ = _flash_lse(
        scale, softcap, block_q, block_k, interpret,
        q, k, v, q_positions, kv_positions, valid, window,
    )
    return out[:, :T]


# --------------------------------------------------------------------- #
# Multi-chip dispatch (shard_map)
# --------------------------------------------------------------------- #

def flash_sharding_ok(
    mesh: Mesh,
    B: int,
    n_heads: int,
    n_kv_heads: int,
    batch_axes: Sequence[str] = ("data", "fsdp"),
    head_axis: str = "model",
    seq_axis: str = "seq",
) -> bool:
    """True when the kernel can run per-shard with no cross-device work:
    batch divides the data axes, both head counts divide the TP axis, and
    the sequence axis is unsharded (sequence parallelism goes through
    ``parallel/ring_attention.py`` instead)."""
    shape = dict(mesh.shape)
    db = 1
    for a in batch_axes:
        db *= shape.get(a, 1)
    tp = shape.get(head_axis, 1)
    if shape.get(seq_axis, 1) != 1:
        return False
    return B % db == 0 and n_heads % tp == 0 and n_kv_heads % tp == 0


def flash_attention_sharded(
    mesh: Mesh,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_positions: jax.Array,
    kv_positions: jax.Array,
    valid: jax.Array,
    window: jax.Array,
    scale: Optional[float] = None,
    softcap: float = 0.0,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
    batch_axes: Sequence[str] = ("data", "fsdp"),
    head_axis: str = "model",
) -> jax.Array:
    """The flash kernel under ``shard_map``: batch shards over the data
    axes, heads over the TP axis. Attention is independent across both, so
    there are no collectives — each chip runs the single-chip kernel on
    its shard and TP meshes keep the fast path (VERDICT.md Weak #4).
    Differentiable: shard_map transposes through the kernel's custom VJP.
    """
    H = q.shape[-1]
    scale = scale if scale is not None else H ** -0.5
    present = [a for a in batch_axes if a in mesh.axis_names]
    bspec = tuple(present) if present else None
    fn = functools.partial(
        flash_attention,
        scale=scale, softcap=softcap,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    head = head_axis if head_axis in mesh.axis_names else None
    return compat_shard_map(
        fn,
        mesh=mesh,
        in_specs=(
            P(bspec, None, head, None),   # q
            P(bspec, None, head, None),   # k
            P(bspec, None, head, None),   # v
            P(bspec, None),               # q_positions
            P(bspec, None),               # kv_positions
            P(bspec),                     # valid
            P(),                          # window (replicated scalar)
        ),
        out_specs=P(bspec, None, head, None),
        check_vma=False,
    )(q, k, v, q_positions, kv_positions, valid,
      jnp.asarray(window, jnp.int32))
