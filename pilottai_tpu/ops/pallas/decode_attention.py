"""Single-token (decode) GQA attention as a Pallas TPU kernel.

The decode hot path reads the whole KV cache every token; XLA's batched
tiny matvecs ([G, H] x [H, S] per (batch, kv-head)) stream it at a
fraction of HBM bandwidth. This kernel makes the cache read the *only*
traffic: grid (B, K/Kb), each cell DMAs contiguous [Kb, S, H] K/V panels
into VMEM once (pipelined across grid steps by Mosaic) and does the
q.K^T -> softmax -> .V chain on-chip in fp32.

Cache layout is K-major ([B, K, S, H]) so each grid cell's panels are
contiguous HBM regions — the S-reduction never strides across heads.

Two modes:
* ``return_stats=False`` — normalized attention output (drop-in for the
  dense path).
* ``return_stats=True`` — unnormalized (acc, m, l) online-softmax stats,
  so the decode chunk can combine this *read-only prefix* pass with a
  small in-chunk attention over tokens generated since the last cache
  write (``engine/decode.py``). Read-only matters: a kernel that wrote
  the cache would force XLA to copy the panels around every custom call
  inside the chunk scan.

No reference counterpart (the reference computes no attention at all,
SURVEY.md §2.13); this is the serving engine's per-token hot op, the
fix for VERDICT.md Weak #4.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -2.0**30

# K+V panel bytes per grid cell. Mosaic's scoped allocation lands at ~4x
# this (double-buffered panels + fp32 score intermediates), and the v5e
# VMEM limit is 16 MiB — 3 MiB panels keep ~4 MiB of headroom.
_DECODE_KV_VMEM_BUDGET = 3 * 1024 * 1024


def decode_shapes_ok(S: int, head_dim: int, itemsize: int = 2) -> bool:
    """Even one kv-head per cell must fit the VMEM budget."""
    return 2 * S * head_dim * itemsize <= _DECODE_KV_VMEM_BUDGET


def _decode_kernel(
    last_ref,  # SMEM (B,) int32 (scalar prefetch) — max valid key index
    qpos_ref,  # SMEM (B,) int32 (scalar prefetch) — query absolute position
    q_ref,     # VMEM (1, Kb, G, H)
    k_ref,     # VMEM (1, Kb, S, H)
    v_ref,     # VMEM (1, Kb, S, H)
    *o_refs,
    scale: float,
    softcap: float,
    window: int,
    return_stats: bool,
):
    b = pl.program_id(0)
    last = last_ref[b]
    qpos = qpos_ref[b]

    q = q_ref[0]                                          # [Kb, G, H]
    k = k_ref[0]                                          # [Kb, S, H]
    v = v_ref[0]

    # Batched over the Kb kv-heads resident in this cell: one MXU call
    # instead of Kb tiny ones.
    s = jax.lax.dot_general(
        q, k,
        dimension_numbers=(((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    ) * scale                                             # [Kb, G, S]
    if softcap > 0.0:
        s = jnp.tanh(s / softcap) * softcap

    col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
    mask = col <= last
    if window > 0:
        mask &= (qpos - col) < window
    s = jnp.where(mask, s, NEG_INF)

    m = jnp.max(s, axis=-1, keepdims=True)                # [Kb, G, 1]
    p = jnp.where(m > NEG_INF / 2, jnp.exp(s - m), 0.0)   # fully-masked rows
    denom = jnp.sum(p, axis=-1, keepdims=True)

    if return_stats:
        acc = jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                                 # [Kb, G, H] fp32
        o_refs[0][0] = acc
        o_refs[1][0] = m
        o_refs[2][0] = denom
    else:
        w = (p / jnp.maximum(denom, 1e-30)).astype(v.dtype)
        o = jax.lax.dot_general(
            w, v,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        o_refs[0][0] = o.astype(o_refs[0].dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "softcap", "window", "return_stats", "interpret"),
)
def decode_attention(
    q: jax.Array,          # [B, N, H] current-token queries
    k_cache: jax.Array,    # [B, K, S, H] (K-major cache layout)
    v_cache: jax.Array,    # [B, K, S, H]
    last_valid: jax.Array,  # [B] int32 — keys at s <= last_valid[b] attend
    q_positions: Optional[jax.Array] = None,  # [B] int32 — for the sliding
                           # window; defaults to last_valid (self-decode)
    scale: Optional[float] = None,
    softcap: float = 0.0,
    window: int = 0,
    return_stats: bool = False,
    interpret: bool = False,
):
    """GQA decode attention against a fixed-size cache.

    Attend iff s <= last_valid[b] and (window == 0 or
    q_positions[b] - s < window). Returns [B, N, H], or with
    ``return_stats`` the unnormalized ``(acc [B,N,H] fp32, m [B,N],
    l [B,N])`` online-softmax triple.
    """
    B, N, H = q.shape
    _, K, S, _ = k_cache.shape
    assert N % K == 0
    G = N // K
    scale = scale if scale is not None else H ** -0.5

    qg = q.reshape(B, K, G, H)
    last_valid = jnp.asarray(last_valid, jnp.int32).reshape(B)
    if q_positions is None:
        q_positions = last_valid
    q_positions = jnp.asarray(q_positions, jnp.int32).reshape(B)

    # Largest kv-head chunk whose K+V panels fit the VMEM budget — bigger
    # panels amortize per-grid-cell pipeline cost.
    itemsize = jnp.dtype(k_cache.dtype).itemsize
    Kb = K
    while Kb > 1 and 2 * Kb * S * H * itemsize > _DECODE_KV_VMEM_BUDGET:
        Kb //= 2

    kernel = functools.partial(
        _decode_kernel,
        scale=scale, softcap=softcap, window=window, return_stats=return_stats,
    )
    if return_stats:
        # m/l carry a trailing singleton so the last two block dims stay
        # equal to the array dims (Mosaic tiling rule) even when Kb < K.
        out_shape = (
            jax.ShapeDtypeStruct((B, K, G, H), jnp.float32),
            jax.ShapeDtypeStruct((B, K, G, 1), jnp.float32),
            jax.ShapeDtypeStruct((B, K, G, 1), jnp.float32),
        )
        out_specs = (
            pl.BlockSpec((1, Kb, G, H), lambda b, k, *_: (b, k, 0, 0)),
            pl.BlockSpec((1, Kb, G, 1), lambda b, k, *_: (b, k, 0, 0)),
            pl.BlockSpec((1, Kb, G, 1), lambda b, k, *_: (b, k, 0, 0)),
        )
    else:
        out_shape = jax.ShapeDtypeStruct((B, K, G, H), q.dtype)
        out_specs = pl.BlockSpec((1, Kb, G, H), lambda b, k, *_: (b, k, 0, 0))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # last_valid, q_positions land in SMEM
        grid=(B, K // Kb),
        in_specs=[
            pl.BlockSpec((1, Kb, G, H), lambda b, k, *_: (b, k, 0, 0)),
            pl.BlockSpec((1, Kb, S, H), lambda b, k, *_: (b, k, 0, 0)),
            pl.BlockSpec((1, Kb, S, H), lambda b, k, *_: (b, k, 0, 0)),
        ],
        out_specs=out_specs,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(last_valid, q_positions, qg, k_cache, v_cache)
    if return_stats:
        acc, m, l = out
        return acc.reshape(B, N, H), m.reshape(B, N), l.reshape(B, N)
    return out.reshape(B, N, H)


__all__ = ["decode_attention", "decode_shapes_ok"]
