"""Device ops: attention (XLA + Pallas), KV caches, sampling primitives.

New TPU-native surface — the reference computes nothing on-device
(SURVEY.md §2: "no tensor computation").
"""

from pilottai_tpu.ops.attention import dot_product_attention
from pilottai_tpu.ops.kvcache import KVCache

__all__ = ["dot_product_attention", "KVCache"]
