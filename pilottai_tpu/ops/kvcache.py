"""Slot-based KV cache for continuous batching.

Layout: one ``(k, v)`` pair per layer, each ``[B, K, S, H]`` — B serving
*slots*, K kv-heads, S max context, H head dim. Two deliberate choices:

* **K-major panels.** Each (slot, kv-head) owns a contiguous ``[S, H]``
  region, so the decode-attention kernel's S-reduction streams HBM
  sequentially instead of striding across heads (the transposed layout
  measured ~5x slower cache reads on v5e).
* **Per-layer arrays, not one stacked ``[L, ...]``.** The decode chunk
  unrolls layers and feeds each layer's panels to a Pallas call; separate
  arrays mean the operands are the buffers themselves — a stacked array
  would force a per-layer dynamic-slice copy of the whole layer cache in
  front of every custom call.

Shapes are static (jit-stable). ``lengths[b]`` counts valid entries; the
stale bytes past it are masked at attention time, so freeing a slot is a
single scalar write. Admission/eviction happen on the host between device
chunks; the device only ever sees full, fixed-shape arrays.

New TPU-native surface (the reference has no KV anything). This dense
cache is the default for short contexts; long ragged contexts use the
paged (block-table) cache in ``ops/paged.py`` with the Pallas kernel in
``ops/pallas/paged_attention.py`` (``LLMConfig.engine_paged_kv``).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


def quantize_kv(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-token-per-head int8: ``x[..., H] -> (q int8[..., H],
    scale f32[...])``. Round-trips losslessly through dequantize →
    requantize (the recomputed scale is bit-identical), which is what
    lets the prefix store hand full-precision panels around while the
    resident cache stays int8."""
    s = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1) / 127.0
    s = jnp.maximum(s, 1e-8)
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / s[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, s


def dequantize_kv(q: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    """Inverse of :func:`quantize_kv`; XLA fuses the broadcast multiply
    into the consuming attention contraction, so the HBM read stays
    int8-sized."""
    return (q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)).astype(dtype)


class KVCache(NamedTuple):
    layers: Tuple[Tuple[jax.Array, jax.Array], ...]  # per-layer (k, v) [B, K, S, H]
    lengths: jax.Array                               # [B] int32 — valid entries
    # Per-layer (k_scale, v_scale) [B, K, S] when the panels are int8
    # (symmetric per-token-per-head); None for full-precision panels.
    # Decode is HBM-bound and the cache is ~1/3 of its traffic at short
    # contexts — int8 halves that for ~1e-3 relative attention error.
    scales: Optional[Tuple[Tuple[jax.Array, jax.Array], ...]] = None

    @property
    def n_layers(self) -> int:
        return len(self.layers)

    @property
    def n_slots(self) -> int:
        return self.layers[0][0].shape[0]

    @property
    def max_len(self) -> int:
        return self.layers[0][0].shape[2]

    @property
    def n_kv_heads(self) -> int:
        return self.layers[0][0].shape[1]

    @property
    def head_dim(self) -> int:
        return self.layers[0][0].shape[3]

    @classmethod
    def create(
        cls,
        n_layers: int,
        n_slots: int,
        max_len: int,
        n_kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
        quantized: bool = False,
    ) -> "KVCache":
        shape = (n_slots, n_kv_heads, max_len, head_dim)
        store_dtype = jnp.int8 if quantized else dtype
        layers = tuple(
            (jnp.zeros(shape, dtype=store_dtype),
             jnp.zeros(shape, dtype=store_dtype))
            for _ in range(n_layers)
        )
        scales = (
            tuple(
                (jnp.zeros(shape[:-1], jnp.float32),
                 jnp.zeros(shape[:-1], jnp.float32))
                for _ in range(n_layers)
            )
            if quantized else None
        )
        return cls(
            layers=layers, lengths=jnp.zeros((n_slots,), dtype=jnp.int32),
            scales=scales,
        )


def write_prompts(
    cache: KVCache,
    slots: jax.Array,      # [A] int32 — target slot per admitted prompt
    ks: jax.Array,         # [L, A, T, K, H] — prefill K for every layer
    vs: jax.Array,         # [L, A, T, K, H]
    lengths: jax.Array,    # [A] int32 — true (unpadded) prompt lengths;
                           # <= 0 marks a padding row (dropped)
) -> KVCache:
    """Insert a batch of freshly prefilled prompts (host-driven admission).

    T may be padded; entries beyond ``lengths[a]`` are zeros and masked out
    at attention time. Padding rows (``lengths[a] <= 0``) are routed to an
    out-of-bounds slot index so XLA scatter semantics drop them.
    """
    A = ks.shape[1]
    # dynamic_update_slice (not scatter): XLA aliases it in place on the
    # donated cache, where an advanced-index scatter measured a full-cache
    # copy per admission. dus clamps out-of-range starts instead of
    # dropping, so padding rows are routed to the *first* row's slot and
    # written before it (reversed order) — row 0 is always a live request,
    # and its later write overwrites the padding garbage.
    safe_slots = jnp.where(lengths > 0, slots, slots[0])
    new_layers = []
    new_scales = [] if cache.scales is not None else None
    for layer_idx, (k, v) in enumerate(cache.layers):
        # [A, T, K, H] -> [A, K, T, H] to match the K-major panels.
        k_new = jnp.swapaxes(ks[layer_idx], 1, 2)
        v_new = jnp.swapaxes(vs[layer_idx], 1, 2)
        if cache.scales is not None:
            k_new, ksc = quantize_kv(k_new)
            v_new, vsc = quantize_kv(v_new)
            ks_p, vs_p = cache.scales[layer_idx]
            for a in reversed(range(A)):
                sstart = (safe_slots[a], 0, 0)
                ks_p = jax.lax.dynamic_update_slice(ks_p, ksc[a][None], sstart)
                vs_p = jax.lax.dynamic_update_slice(vs_p, vsc[a][None], sstart)
            new_scales.append((ks_p, vs_p))
        else:
            k_new = k_new.astype(k.dtype)
            v_new = v_new.astype(v.dtype)
        for a in reversed(range(A)):
            start = (safe_slots[a], 0, 0, 0)
            k = jax.lax.dynamic_update_slice(k, k_new[a][None], start)
            v = jax.lax.dynamic_update_slice(v, v_new[a][None], start)
        new_layers.append((k, v))
    new_lengths = cache.lengths
    for a in reversed(range(A)):
        new_lengths = jax.lax.dynamic_update_slice(
            new_lengths, jnp.maximum(lengths[a], 0)[None], (safe_slots[a],)
        )
    return cache._replace(
        layers=tuple(new_layers), lengths=new_lengths,
        scales=tuple(new_scales) if new_scales is not None else None,
    )


def write_chunk_rows(
    cache: KVCache,
    ring_ks,               # list per layer: [B, K, n, H] chunk ring
    ring_vs,
    start: jax.Array,      # [B] int32 — slot length at chunk start
    accepted: jax.Array,   # [B] int32 — rows actually generated this chunk
) -> KVCache:
    """Scatter one decode chunk's ring buffers into the big cache.

    Row j of slot b lands at position start[b] + j when j < accepted[b];
    rejected rows (beyond EOS/budget) are routed past S and dropped.
    """
    B = cache.n_slots
    S = cache.max_len
    n = ring_ks[0].shape[2]
    j = jnp.arange(n)[None, :]                               # [1, n]
    pos = jnp.where(j < accepted[:, None], start[:, None] + j, S)  # [B, n]
    bidx = jnp.arange(B)[:, None]
    new_layers = []
    new_scales = [] if cache.scales is not None else None
    for li, ((k, v), rk, rv) in enumerate(zip(cache.layers, ring_ks, ring_vs)):
        if cache.scales is not None:
            rk, ksc = quantize_kv(rk)                        # [B, K, n]
            rv, vsc = quantize_kv(rv)
            ks_p, vs_p = cache.scales[li]
            ks_p = ks_p.at[bidx, :, pos].set(
                ksc.transpose(0, 2, 1), mode="drop"
            )
            vs_p = vs_p.at[bidx, :, pos].set(
                vsc.transpose(0, 2, 1), mode="drop"
            )
            new_scales.append((ks_p, vs_p))
        # Advanced indices (bidx, pos) broadcast to [B, n]; the kv-head
        # slice rides along -> update values [B, n, K, H].
        k = k.at[bidx, :, pos].set(
            rk.transpose(0, 2, 1, 3).astype(k.dtype), mode="drop"
        )
        v = v.at[bidx, :, pos].set(
            rv.transpose(0, 2, 1, 3).astype(v.dtype), mode="drop"
        )
        new_layers.append((k, v))
    new_lengths = jnp.minimum(cache.lengths + accepted, S)
    return cache._replace(
        layers=tuple(new_layers), lengths=new_lengths,
        scales=tuple(new_scales) if new_scales is not None else None,
    )


def free_slots(cache: KVCache, slots: jax.Array) -> KVCache:
    """Mark slots empty (host calls when sequences finish). The stale K/V
    bytes stay — masked out by lengths — so no panel writes needed."""
    return cache._replace(
        lengths=cache.lengths.at[slots].set(0, mode="drop")
    )
