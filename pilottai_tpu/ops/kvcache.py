"""Slot-based KV cache for continuous batching.

Shapes are static (jit-stable): ``k``/``v`` are [L, B, S, K, H] where B is
the number of serving *slots* and S the max context. Each slot holds one
in-flight sequence; ``lengths[b]`` is how many cache entries are valid.
Admission/eviction happen on the host between device steps (the batcher);
the device only ever sees full, fixed-shape arrays — no dynamic shapes, no
recompiles.

New TPU-native surface (the reference has no KV anything); the paged
variant for long ragged contexts lives in ``pilottai_tpu/ops/pallas``.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class KVCache(NamedTuple):
    k: jax.Array        # [L, B, S, K, H]
    v: jax.Array        # [L, B, S, K, H]
    lengths: jax.Array  # [B] int32 — valid entries per slot

    @property
    def n_layers(self) -> int:
        return self.k.shape[0]

    @property
    def n_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    @classmethod
    def create(
        cls,
        n_layers: int,
        n_slots: int,
        max_len: int,
        n_kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
    ) -> "KVCache":
        shape = (n_layers, n_slots, max_len, n_kv_heads, head_dim)
        return cls(
            k=jnp.zeros(shape, dtype=dtype),
            v=jnp.zeros(shape, dtype=dtype),
            lengths=jnp.zeros((n_slots,), dtype=jnp.int32),
        )


def write_prompt(
    cache: KVCache,
    slot: jax.Array,      # scalar int32
    k_new: jax.Array,     # [L, T, K, H] — prompt K for every layer
    v_new: jax.Array,     # [L, T, K, H]
    length: jax.Array,    # scalar int32 — true (unpadded) prompt length
) -> KVCache:
    """Insert a freshly prefilled prompt into ``slot`` (host-driven admission).

    T may be padded; entries beyond ``length`` are zeros and masked out at
    attention time via ``lengths``.
    """
    T = k_new.shape[1]
    k = jax.lax.dynamic_update_slice(
        cache.k, k_new[:, None], (0, slot, 0, 0, 0)
    )
    v = jax.lax.dynamic_update_slice(
        cache.v, v_new[:, None], (0, slot, 0, 0, 0)
    )
    del T
    lengths = cache.lengths.at[slot].set(length)
    return KVCache(k=k, v=v, lengths=lengths)


def append_token(
    layer_k: jax.Array,   # [B, S, K, H] one layer's cache
    layer_v: jax.Array,
    k_new: jax.Array,     # [B, 1, K, H]
    v_new: jax.Array,
    positions: jax.Array,  # [B] int32 — write index per slot (= current length)
) -> Tuple[jax.Array, jax.Array]:
    """Scatter one decode step's K/V into each slot at its own position.

    Uses one-hot matmul-free scatter via ``at[...]`` with batched indices —
    lowers to an efficient dynamic-update on TPU.
    """
    B = layer_k.shape[0]
    batch_idx = jnp.arange(B)
    k = layer_k.at[batch_idx, positions].set(k_new[:, 0])
    v = layer_v.at[batch_idx, positions].set(v_new[:, 0])
    return k, v


def free_slot(cache: KVCache, slot: jax.Array) -> KVCache:
    """Mark a slot empty (host calls when a sequence finishes). The stale
    K/V bytes stay — masked out by lengths — so no device writes needed."""
    return cache._replace(lengths=cache.lengths.at[slot].set(0))
