"""Paged KV cache: block-table indirection over a shared page pool.

The dense cache (``ops/kvcache.py``) reserves ``slots × max_seq`` HBM
whether contexts use it or not — at 32 slots × 8 K context × 16 layers
that is more HBM than a v5e has. Here each layer owns one page pool
``[K, num_pages, P, H]`` (K-major, so a page is a contiguous ``[P, H]``
panel per kv-head) and slots map positions to pages through a block
table; a slot holding 300 tokens pins 3 pages, not an 8 K row.

Division of labor:

* **Allocation is host-side** (``PageAllocator``): a free-list push/pop
  per admission/completion. The block table is a small host numpy array
  passed into each device dispatch (8 KB for 32×64 — sub-ms H2D), so
  the device carries no allocator state and admission backpressure is
  just "not enough free pages → request stays pending".
* **Pages are allocated for prompt + full generation budget up front**,
  so no mid-decode growth path exists; completion frees them all.
* Device ops here mirror the dense API: batched prompt scatter, ring
  scatter at chunk end, gather-based prefix attention reads (the Pallas
  paged-attention kernel in ``ops/pallas/paged_attention.py`` replaces
  the gather on TPU).

Design follows the ragged/paged attention literature cited in PAPERS.md;
closes VERDICT.md next-step 7 (the docstring-only "paged variant" of
round 1). No reference counterpart (the reference has no KV anything —
it calls a remote API, ``pilott/engine/llm.py:59``).
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pilottai_tpu.ops.kvcache import quantize_kv


class PagedKVCache(NamedTuple):
    # per-layer (k_pool, v_pool), each [K, num_pages, P, H]. The LAST page
    # (index num_pages - 1) is a scratch page: scatter targets for dropped
    # writes and gather source for unallocated table slots — never handed
    # to the allocator.
    layers: Tuple[Tuple[jax.Array, jax.Array], ...]
    lengths: jax.Array  # [B] int32 — valid tokens per slot
    # Per-layer (k_scale, v_scale) pools [K, num_pages, P] when the page
    # pools are int8 (symmetric per-token-per-head); None otherwise.
    # Halves decode cache traffic and doubles resident context per HBM GB.
    scales: Optional[Tuple[Tuple[jax.Array, jax.Array], ...]] = None

    @property
    def n_layers(self) -> int:
        return len(self.layers)

    @property
    def n_kv_heads(self) -> int:
        return self.layers[0][0].shape[0]

    @property
    def num_pages(self) -> int:
        return self.layers[0][0].shape[1]

    @property
    def page_size(self) -> int:
        return self.layers[0][0].shape[2]

    @property
    def head_dim(self) -> int:
        return self.layers[0][0].shape[3]

    @property
    def n_slots(self) -> int:
        return self.lengths.shape[0]

    @classmethod
    def create(
        cls,
        n_layers: int,
        n_slots: int,
        num_pages: int,
        page_size: int,
        n_kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
        quantized: bool = False,
    ) -> "PagedKVCache":
        shape = (n_kv_heads, num_pages, page_size, head_dim)
        store_dtype = jnp.int8 if quantized else dtype
        layers = tuple(
            (jnp.zeros(shape, dtype=store_dtype),
             jnp.zeros(shape, dtype=store_dtype))
            for _ in range(n_layers)
        )
        scales = (
            tuple(
                (jnp.zeros(shape[:-1], jnp.float32),
                 jnp.zeros(shape[:-1], jnp.float32))
                for _ in range(n_layers)
            )
            if quantized else None
        )
        return cls(
            layers=layers, lengths=jnp.zeros((n_slots,), dtype=jnp.int32),
            scales=scales,
        )


class PageAllocator:
    """Host-side free-list + block table (single-threaded: the device
    thread owns admission and completion bookkeeping).

    Pages are **refcounted** so the block-granular prefix cache
    (``engine/page_prefix.py``) can map one immutable prompt-prefix page
    into many slots' tables at once — prefix sharing by indirection, no
    panel copies. A slot holds one ref on every page in its table
    (shared prefix pages included); the prefix index pins cached pages
    with a ref of its own. A page returns to the free list only when its
    last ref drops.
    """

    def __init__(self, num_pages: int, page_size: int, n_slots: int,
                 max_pages_per_slot: int) -> None:
        # Page num_pages - 1 is the device scratch page; never allocate it.
        self.num_pages = num_pages
        self.page_size = page_size
        self.sentinel = num_pages - 1
        self.free: List[int] = list(range(num_pages - 1))
        self.refs = np.zeros((num_pages,), np.int32)
        self.table = np.full((n_slots, max_pages_per_slot), self.sentinel,
                             np.int32)
        self._held: List[List[int]] = [[] for _ in range(n_slots)]

    def pages_needed(self, n_tokens: int) -> int:
        return -(-max(n_tokens, 1) // self.page_size)

    def can_allocate(self, n_tokens: int, n_prefix_pages: int = 0) -> bool:
        total = self.pages_needed(n_tokens)
        n_new = max(total - n_prefix_pages, 0)
        return n_new <= len(self.free) and total <= self.table.shape[1]

    def allocate(
        self, slot: int, n_tokens: int,
        prefix_pages: Sequence[int] = (),
    ) -> bool:
        """Reserve pages covering n_tokens for a fresh slot. Shared
        ``prefix_pages`` (already holding the prompt prefix's K/V) are
        mapped into the head of the slot's table with a ref each; fresh
        pages cover the rest. False (and no change) when the pool can't
        cover it — caller leaves the request pending."""
        total = self.pages_needed(n_tokens)
        n_new = max(total - len(prefix_pages), 0)
        if n_new > len(self.free) or total > self.table.shape[1]:
            return False
        assert not self._held[slot], f"slot {slot} still holds pages"
        got = [self.free.pop() for _ in range(n_new)]
        held = list(prefix_pages) + got
        for p in held:
            self.refs[p] += 1
        self._held[slot] = held
        self.table[slot, :] = self.sentinel
        self.table[slot, : len(held)] = held
        return True

    def holds(self, slot: int) -> bool:
        """Whether the slot currently holds any pages (release is a
        no-op otherwise — callers use this to count real releases)."""
        return bool(self._held[slot])

    def release(self, slot: int) -> None:
        for p in self._held[slot]:
            self.refs[p] -= 1
            if self.refs[p] == 0:
                self.free.append(p)
        self._held[slot] = []
        self.table[slot, :] = self.sentinel

    def take(self, n: int) -> Optional[List[int]]:
        """Pop ``n`` free pages with a transient ref each (the KV cache
        tier's restore path: the pages are filled from host RAM, then
        registered/pinned by the prefix index and the transient ref
        dropped via ``unpin``). None (and no change) when the pool can't
        cover it."""
        if n > len(self.free):
            return None
        pages = [self.free.pop() for _ in range(n)]
        for p in pages:
            self.refs[p] += 1
        return pages

    def pin(self, page: int) -> None:
        """Add a non-slot ref (prefix index). Caller must hold/know the
        page is live (refs > 0) — pinning a free page is a logic error."""
        assert self.refs[page] > 0, f"pin of unreferenced page {page}"
        self.refs[page] += 1

    def unpin(self, page: int) -> None:
        self.refs[page] -= 1
        if self.refs[page] == 0:
            self.free.append(page)

    @property
    def free_pages(self) -> int:
        return len(self.free)


def write_prompts_paged(
    cache: PagedKVCache,
    table: jax.Array,     # [A, max_pages] int32 — page rows of the admitted
                          # slots (sentinel where unallocated)
    ks: jax.Array,        # [L, A, T, K, H]
    vs: jax.Array,
    lengths: jax.Array,   # [A] int32; <= 0 marks a padding row
    pos_offset: Optional[jax.Array] = None,  # scalar int32 — absolute
                          # position of row 0 (page-ALIGNED; prefix-cached
                          # tail writes land after the shared pages)
) -> PagedKVCache:
    """Scatter freshly prefilled prompts into their slots' pages. T (the
    prefill bucket) need not be page-aligned; positions past ``lengths``
    land on allocated-but-masked space or on the sentinel scratch page."""
    L, A, T, K, H = ks.shape
    P = cache.page_size
    n_blocks = -(-T // P)
    Tp = n_blocks * P
    pos = jnp.arange(Tp)                                     # [Tp]
    live = pos[None, :] < lengths[:, None]                   # [A, Tp]
    if pos_offset is not None:
        pos = pos + pos_offset
    max_pos = table.shape[1] * P - 1
    blk = jnp.minimum(pos, max_pos) // P
    # Page id per (row, position); sentinel when the position is beyond
    # the row's valid length or its allocation.
    pages = jnp.take_along_axis(
        table, jnp.broadcast_to(blk[None, :], (A, Tp)), axis=1
    )                                                        # [A, Tp]
    pages = jnp.where(live, pages, cache.num_pages - 1)
    off = jnp.broadcast_to((pos % P)[None, :], (A, Tp))
    pages_f = pages.reshape(-1)                              # [A*Tp]
    off_f = off.reshape(-1)

    new_layers = []
    new_scales = [] if cache.scales is not None else None
    for li, (kp, vp) in enumerate(cache.layers):
        # [A, T, K, H] -> pad T to Tp -> [K, A*Tp, H]
        k_new = ks[li]
        v_new = vs[li]
        if Tp != T:
            pad = ((0, 0), (0, Tp - T), (0, 0), (0, 0))
            k_new = jnp.pad(k_new, pad)
            v_new = jnp.pad(v_new, pad)
        k_new = k_new.transpose(2, 0, 1, 3).reshape(K, A * Tp, H)
        v_new = v_new.transpose(2, 0, 1, 3).reshape(K, A * Tp, H)
        if cache.scales is not None:
            k_new, ksc = quantize_kv(k_new)                  # [K, A*Tp]
            v_new, vsc = quantize_kv(v_new)
            ks_p, vs_p = cache.scales[li]
            ks_p = ks_p.at[:, pages_f, off_f].set(ksc, mode="drop")
            vs_p = vs_p.at[:, pages_f, off_f].set(vsc, mode="drop")
            new_scales.append((ks_p, vs_p))
        kp = kp.at[:, pages_f, off_f].set(k_new.astype(kp.dtype), mode="drop")
        vp = vp.at[:, pages_f, off_f].set(v_new.astype(vp.dtype), mode="drop")
        new_layers.append((kp, vp))
    return cache._replace(
        layers=tuple(new_layers),
        scales=tuple(new_scales) if new_scales is not None else None,
    )


def install_lengths(
    cache: PagedKVCache,
    slots: jax.Array,    # [A] int32 (OOB rows dropped)
    lengths: jax.Array,  # [A]
) -> PagedKVCache:
    return cache._replace(
        lengths=cache.lengths.at[slots].set(
            jnp.maximum(lengths, 0), mode="drop"
        )
    )


def write_chunk_rows_paged(
    cache: PagedKVCache,
    table: jax.Array,     # [B, max_pages] int32 — full block table
    ring_ks: Sequence[jax.Array],  # per layer [B, K, n, H]
    ring_vs: Sequence[jax.Array],
    start: jax.Array,     # [B]
    accepted: jax.Array,  # [B]
) -> PagedKVCache:
    """Chunk-end scatter of the decode ring into pages (paged counterpart
    of ``ops/kvcache.py:write_chunk_rows``)."""
    B = cache.n_slots
    P = cache.page_size
    n = ring_ks[0].shape[2]
    j = jnp.arange(n)[None, :]
    pos = start[:, None] + j                                 # [B, n]
    max_pos = table.shape[1] * P - 1
    blk = jnp.minimum(pos, max_pos) // P
    pages = jnp.take_along_axis(table, blk, axis=1)          # [B, n]
    pages = jnp.where(j < accepted[:, None], pages, cache.num_pages - 1)
    pages_f = pages.reshape(-1)                              # [B*n]
    off_f = (pos % P).reshape(-1)

    new_layers = []
    new_scales = [] if cache.scales is not None else None
    for li, ((kp, vp), rk, rv) in enumerate(
        zip(cache.layers, ring_ks, ring_vs)
    ):
        k_new = rk.transpose(1, 0, 2, 3).reshape(
            cache.n_kv_heads, B * n, cache.head_dim
        )
        v_new = rv.transpose(1, 0, 2, 3).reshape(
            cache.n_kv_heads, B * n, cache.head_dim
        )
        if cache.scales is not None:
            k_new, ksc = quantize_kv(k_new)
            v_new, vsc = quantize_kv(v_new)
            ks_p, vs_p = cache.scales[li]
            ks_p = ks_p.at[:, pages_f, off_f].set(ksc, mode="drop")
            vs_p = vs_p.at[:, pages_f, off_f].set(vsc, mode="drop")
            new_scales.append((ks_p, vs_p))
        kp = kp.at[:, pages_f, off_f].set(k_new.astype(kp.dtype), mode="drop")
        vp = vp.at[:, pages_f, off_f].set(v_new.astype(vp.dtype), mode="drop")
        new_layers.append((kp, vp))
    # Clamp to allocated slot capacity (parity with the dense path's min
    # against S): decode's ctx_full/budget invariants should keep lengths
    # in range on their own, but a length past allocation would claim
    # tokens that were actually routed to the scratch page.
    new_lengths = jnp.minimum(
        cache.lengths + jnp.minimum(accepted, n), table.shape[1] * P
    )
    return cache._replace(
        layers=tuple(new_layers), lengths=new_lengths,
        scales=tuple(new_scales) if new_scales is not None else None,
    )


def gather_pages(
    pool: jax.Array,      # [K, num_pages, P, H] (or [K, num_pages, P]
                          # scale pools)
    table: jax.Array,     # [B, max_pages]
    n_blocks: int,        # static — bucketed ceil(bound / P)
) -> jax.Array:
    """XLA fallback read: materialize the first ``n_blocks`` pages of each
    slot as dense [B, K, n_blocks*P, H] panels (CPU tests / off-TPU) —
    or [B, K, n_blocks*P] for 3-d scale pools. Sentinel entries gather
    scratch-page garbage — masked by lengths at attention time exactly
    like the dense cache's stale bytes."""
    K, _, P = pool.shape[:3]
    B = table.shape[0]
    idx = table[:, :n_blocks]                                # [B, nb]
    g = pool[:, idx]                                         # [K, B, nb, P(, H)]
    if pool.ndim == 3:
        return g.transpose(1, 0, 2, 3).reshape(B, K, n_blocks * P)
    H = pool.shape[3]
    return g.transpose(1, 0, 2, 3, 4).reshape(B, K, n_blocks * P, H)


__all__ = [
    "PagedKVCache",
    "PageAllocator",
    "write_prompts_paged",
    "write_chunk_rows_paged",
    "install_lengths",
    "gather_pages",
]
