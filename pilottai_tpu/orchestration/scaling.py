"""DynamicScaling: agent-pool autoscaler driven by the obs metrics layer.

Reference parity for the *mechanics* (``pilott/orchestration/
orchestration.py``): 60s loop (``:73-83``), recency-weighted trend over
the last 5 samples (``:157-167``), scale-up via
``orchestrator.create_agent`` (``:169-191``), scale-down drains the
lowest-success-rate idle agent (wait → stop → remove, ``:193-231``),
cooldown gate (``:233-240``).

The *signals* are no longer ad-hoc reads of orchestrator internals (the
reference blended psutil CPU% into the decision): every input now flows
through the ``obs`` metrics registry — the same snapshot ``/metrics``
exports — so the autoscaler's view and the operator's dashboard can
never disagree. Orchestrator-side pressure is published as
``orchestrator.*`` gauges each cycle, engine-side pressure arrives as
the gauges the batcher/attribution layer already maintains
(``engine.queue_depth``, ``engine.device_busy_frac``) and SLO pressure
as the per-class ``slo.<class>.burn_rate`` gauges (obs/slo.py). The
decision itself is exported back as ``scaling.recommendation`` (+1 grow
/ −1 shrink / 0 hold) — the observability half of ROADMAP item 5's
autoscaling loop, consumable by an external operator (k8s HPA adapter,
capacity dashboards) even when the in-process actuator is disabled.

TPU grounding: "scaling" here resizes the *admission* side — more agents
means more concurrent reasoning loops feeding the shared engine batcher —
not OS threads. The engine's slot count stays fixed; agents are cheap.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Any, Dict, Optional

from pilottai_tpu.core.config import ScalingConfig
from pilottai_tpu.core.status import AgentStatus
from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import MetricsRegistry, global_metrics


class DynamicScaling:
    """Grows/drains the orchestrator's agent pool on observed load trend.

    ``registry`` defaults to the process-global metrics bus; tests (and
    multi-tenant deployments wanting isolated autoscalers) inject their
    own.
    """

    def __init__(
        self,
        orchestrator: Any,  # Serve
        config: Optional[ScalingConfig] = None,
        agent_type: str = "worker",
        registry: MetricsRegistry = global_metrics,
        slo_tracker: Optional[Any] = None,
        forecast: Optional[Any] = None,
    ) -> None:
        from pilottai_tpu.obs import global_forecast, global_slo

        self.orchestrator = orchestrator
        self.config = config or ScalingConfig()
        self.agent_type = agent_type
        self._registry = registry
        # Seasonal arrival forecaster (obs/forecast.py): the predictive
        # input. Injectable for tests; shares the profiler's global
        # instance by default (the flight recorder's start listener
        # feeds it). ``forecast_enabled`` in ScalingConfig gates use.
        self._forecast = forecast if forecast is not None else global_forecast
        # The burn-rate gauges are only WRITTEN when a flight finishes;
        # reading them raw after traffic stops would pin the last
        # (possibly alarming) value forever. When the scaler shares the
        # tracker's registry, it refreshes the gauges against the clock
        # before each read. Tests that inject an isolated registry (and
        # set gauges directly) get no tracker unless they pass one.
        self._slo = slo_tracker if slo_tracker is not None else (
            global_slo if registry is global_metrics else None
        )
        self._samples: deque = deque(maxlen=self.config.trend_window)
        # None = never acted; 0.0 would wrongly apply the cooldown to the
        # first action when time.monotonic() (system uptime) < cooldown.
        self._last_action: Optional[float] = None
        self._task: Optional[asyncio.Task] = None
        self._log = get_logger("orchestration.scaling")
        self.scale_ups = 0
        self.scale_downs = 0
        for name in (
            "scaling.system_load", "scaling.recommendation",
            "scaling.target_agents", "scaling.forecast_rps",
            "scaling.forecast_lead_s",
        ):
            registry.declare(name, "gauge")

    # ------------------------------------------------------------------ #

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._scaling_loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _scaling_loop(self) -> None:
        while True:
            await asyncio.sleep(self.config.check_interval)
            try:
                await self.scale_once()
            except Exception as exc:  # noqa: BLE001 - keep the loop alive
                self._log.error("scaling cycle failed: %s", exc, exc_info=True)

    # ------------------------------------------------------------------ #
    # Signals
    # ------------------------------------------------------------------ #

    def publish_orchestrator_gauges(self) -> None:
        """Publish the orchestrator's own pressure as ``orchestrator.*``
        gauges. The load computation reads them BACK from the registry
        snapshot — one surface for the decision, the dashboard and the
        Prometheus scrape, so "why did it scale?" is always answerable
        from exported data."""
        agents = self.orchestrator.agent_list()
        mean_queue = (
            sum(a.queue_utilization for a in agents) / len(agents)
            if agents else 1.0
        )
        backlog = len(self.orchestrator.task_queue) / max(
            self.orchestrator.config.max_queue_size, 1
        )
        running = len(self.orchestrator.running_tasks) / max(
            self.orchestrator.config.max_concurrent_tasks, 1
        )
        reg = self._registry
        reg.set_gauge("orchestrator.agent_queue_util", mean_queue)
        reg.set_gauge("orchestrator.queue_frac", min(backlog, 1.0))
        reg.set_gauge("orchestrator.running_frac", min(running, 1.0))
        reg.set_gauge("orchestrator.agents", float(len(agents)))

    def signals(self) -> Dict[str, float]:
        """The obs-snapshot inputs of one scaling decision."""
        if self._slo is not None:
            self._slo.refresh_gauges()  # decay burn on an idle system
        snap = self._registry.snapshot()
        gauges = snap["gauges"]
        burn = max(
            (
                v for k, v in gauges.items()
                if k.startswith("slo.") and k.endswith(".burn_rate")
            ),
            default=0.0,
        )
        depth = gauges.get("engine.queue_depth", 0.0)
        ref = gauges.get("engine.max_queue_depth") or float(
            self.config.queue_depth_ref
        )
        out = {
            "agent_queue_util": gauges.get(
                "orchestrator.agent_queue_util", 0.0
            ),
            "orch_queue_frac": gauges.get("orchestrator.queue_frac", 0.0),
            "running_frac": gauges.get("orchestrator.running_frac", 0.0),
            "engine_queue_depth": depth,
            "engine_queue_frac": min(depth / max(ref, 1.0), 1.0),
            "device_busy_frac": gauges.get("engine.device_busy_frac", 0.0),
            "slo_burn_rate": burn,
            "shed_rate": self._registry.rate("engine.shed", window=60.0),
        }
        out["forecast_boost"] = self._forecast_boost(out)
        return out

    def _forecast_boost(self, signals: Dict[str, float]) -> float:
        """Multiplier (≥ 1) the predicted arrival ramp applies to the
        load signal: forecast(now + lead) over the current smoothed
        rate, boost-only and capped. 1.0 (a no-op) when forecasting is
        disabled or the seasonal curve hasn't seen a full period yet —
        a cold forecaster must never move capacity. The inputs are
        exported as ``scaling.forecast_*`` gauges either way, so the
        dashboard can watch the forecaster warm up before trusting it."""
        cfg = self.config
        lead = float(cfg.forecast_lead_s)
        fc = self._forecast
        predicted = 0.0
        boost = 1.0
        if cfg.forecast_enabled and fc is not None:
            try:
                predicted = fc.forecast_rps(lead_s=lead)
                current = fc.current_rps()
                if fc.ready() and current > 1e-9:
                    boost = min(
                        max(predicted / current, 1.0), cfg.forecast_boost_cap
                    )
            except Exception:  # noqa: BLE001 — forecast is advisory
                predicted, boost = 0.0, 1.0
        self._registry.set_gauge("scaling.forecast_rps", predicted)
        self._registry.set_gauge("scaling.forecast_lead_s", lead)
        signals["forecast_rps"] = predicted
        return boost

    def system_load(
        self, signals: Optional[Dict[str, float]] = None
    ) -> float:
        """0..1 load from the published signal surface. Weighted blend of
        orchestrator pressure (agent queues, backlog, running tasks),
        engine pressure (admission queue, device busy fraction) and SLO
        pressure (error-budget burn), with two floors:

        * saturated agent queues alone must cross the scale-up threshold
          even when every other signal is calm (the pre-obs behavior);
        * burn rate ≥ 2x budget reads as full load — an SLO burning its
          budget twice as fast as provisioned is a capacity incident
          whatever the queues look like, and burn ≈ 1 floors the load
          mid-range so the scaler won't shrink while budget is burning.

        ``signals`` short-circuits the publish-and-snapshot walk when
        the caller (``metrics``) already has a fresh reading.
        """
        if signals is None:
            self.publish_orchestrator_gauges()
            signals = self.signals()
        s = signals
        weighted = (
            0.30 * s["agent_queue_util"]
            + 0.20 * s["orch_queue_frac"]
            + 0.15 * s["running_frac"]
            + 0.15 * s["engine_queue_frac"]
            + 0.10 * s["device_busy_frac"]
            + 0.10 * min(s["slo_burn_rate"] / 2.0, 1.0)
        )
        burn_floor = min(s["slo_burn_rate"] / 2.0, 1.0)
        load = max(s["agent_queue_util"], burn_floor, weighted)
        # Predictive term (ISSUE 18): scale the reactive load by the
        # forecast ratio so a predicted ramp crosses the scale-up
        # threshold BEFORE queues and burn do. Boost-only and capped
        # (see _forecast_boost); 1.0 whenever forecasting is off/cold.
        load *= s.get("forecast_boost", 1.0)
        return min(1.0, load)

    def trend(self) -> float:
        """Recency-weighted slope (reference ``:157-167``)."""
        if len(self._samples) < 2:
            return 0.0
        weights = range(1, len(self._samples))
        deltas = [
            (self._samples[i] - self._samples[i - 1]) * w
            for i, w in zip(range(1, len(self._samples)), weights)
        ]
        return sum(deltas) / sum(weights)

    def _cooled_down(self) -> bool:
        if self._last_action is None:
            return True
        return time.monotonic() - self._last_action >= self.config.cooldown

    # ------------------------------------------------------------------ #

    async def scale_once(self) -> Optional[str]:
        """One scaling decision; returns "up"/"down"/None. The decision
        (acted on or not) is exported as ``scaling.recommendation``."""
        load = self.system_load()
        self._samples.append(load)
        n_agents = len(self.orchestrator.agents)
        reg = self._registry
        reg.set_gauge("scaling.system_load", load)

        decision: Optional[str] = None
        recommendation = 0.0
        target = float(n_agents)
        if load > self.config.scale_up_threshold:
            recommendation = 1.0
            target = float(min(n_agents + 1, self.config.max_agents))
            if n_agents < self.config.max_agents and self._cooled_down():
                await self._scale_up()
                decision = "up"
        elif load < self.config.scale_down_threshold and self.trend() <= 0:
            recommendation = -1.0
            target = float(max(n_agents - 1, self.config.min_agents))
            if n_agents > self.config.min_agents and self._cooled_down():
                if await self._scale_down():
                    decision = "down"
                else:
                    recommendation = 0.0  # nothing drainable right now
        reg.set_gauge("scaling.recommendation", recommendation)
        reg.set_gauge("scaling.target_agents", target)
        return decision

    async def _scale_up(self) -> None:
        agent = await self.orchestrator.create_agent(self.agent_type)
        self._last_action = time.monotonic()
        self.scale_ups += 1
        self._registry.inc("scaling.scale_ups")
        self._log.info("scaled up: new agent %s (pool=%d)",
                       agent.id[:8], len(self.orchestrator.agents))

    async def _scale_down(self) -> bool:
        """Drain the lowest-success-rate idle agent (reference ``:193-231``)."""
        idle = [
            a for a in self.orchestrator.agent_list()
            if a.status == AgentStatus.IDLE
            and not a.current_tasks
            and a.task_queue.qsize() == 0
        ]
        if not idle:
            return False
        victim = min(idle, key=lambda a: a.success_rate)
        await self.orchestrator.remove_agent(victim.id)
        self._last_action = time.monotonic()
        self.scale_downs += 1
        self._registry.inc("scaling.scale_downs")
        self._log.info("scaled down: removed agent %s (pool=%d)",
                       victim.id[:8], len(self.orchestrator.agents))
        return True

    # ------------------------------------------------------------------ #

    def get_metrics(self) -> Dict[str, Any]:
        # One publish + one snapshot walk feeds both the load and the
        # reported signal surface (system_load would otherwise redo it).
        self.publish_orchestrator_gauges()
        signals = self.signals()
        return {
            "system_load": self.system_load(signals=signals),
            "trend": self.trend(),
            "signals": signals,
            "recommendation": self._registry.get("scaling.recommendation"),
            "agents": len(self.orchestrator.agents),
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "min_agents": self.config.min_agents,
            "max_agents": self.config.max_agents,
        }
