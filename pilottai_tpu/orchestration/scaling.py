"""DynamicScaling: agent-pool autoscaler.

Reference parity: ``pilott/orchestration/orchestration.py`` (the exported
copy; its dead duplicate in ``scaling.py:425-666`` has no counterpart
here, §2.12-d) — 60s loop (``:73-83``), system load = weighted queue
utilization + queue size (``:129-134``), recency-weighted trend over the
last 5 samples (``:157-167``), scale-up via ``orchestrator.create_agent``
(``:169-191``), scale-down drains the lowest-success-rate idle agent
(wait → stop → remove, ``:193-231``), cooldown gate (``:233-240``),
metrics (``:265-281``).

TPU grounding: "scaling" here resizes the *admission* side — more agents
means more concurrent reasoning loops feeding the shared engine batcher —
not OS threads. The engine's slot count stays fixed; agents are cheap.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Any, Dict, Optional

from pilottai_tpu.core.config import ScalingConfig
from pilottai_tpu.core.status import AgentStatus
from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import global_metrics


class DynamicScaling:
    """Grows/drains the orchestrator's agent pool on load trend."""

    def __init__(
        self,
        orchestrator: Any,  # Serve
        config: Optional[ScalingConfig] = None,
        agent_type: str = "worker",
    ) -> None:
        self.orchestrator = orchestrator
        self.config = config or ScalingConfig()
        self.agent_type = agent_type
        self._samples: deque = deque(maxlen=self.config.trend_window)
        # None = never acted; 0.0 would wrongly apply the cooldown to the
        # first action when time.monotonic() (system uptime) < cooldown.
        self._last_action: Optional[float] = None
        self._task: Optional[asyncio.Task] = None
        self._log = get_logger("orchestration.scaling")
        self.scale_ups = 0
        self.scale_downs = 0

    # ------------------------------------------------------------------ #

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._scaling_loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _scaling_loop(self) -> None:
        while True:
            await asyncio.sleep(self.config.check_interval)
            try:
                await self.scale_once()
            except Exception as exc:  # noqa: BLE001 - keep the loop alive
                self._log.error("scaling cycle failed: %s", exc, exc_info=True)

    # ------------------------------------------------------------------ #

    def system_load(self) -> float:
        """0.45 mean agent queue-util + 0.30 orchestrator queue fill +
        0.25 running-task saturation (reference weights ``:129-134``,
        psutil terms replaced with engine-side signals)."""
        agents = self.orchestrator.agent_list()
        mean_queue = (
            sum(a.queue_utilization for a in agents) / len(agents) if agents else 1.0
        )
        backlog = len(self.orchestrator.task_queue) / max(
            self.orchestrator.config.max_queue_size, 1
        )
        running = len(self.orchestrator.running_tasks) / max(
            self.orchestrator.config.max_concurrent_tasks, 1
        )
        weighted = 0.45 * mean_queue + 0.30 * backlog + 0.25 * min(running, 1.0)
        # Floor by mean queue utilization: saturated agent queues alone must
        # cross the scale-up threshold even with an empty orchestrator queue.
        return min(1.0, max(mean_queue, weighted))

    def trend(self) -> float:
        """Recency-weighted slope (reference ``:157-167``)."""
        if len(self._samples) < 2:
            return 0.0
        weights = range(1, len(self._samples))
        deltas = [
            (self._samples[i] - self._samples[i - 1]) * w
            for i, w in zip(range(1, len(self._samples)), weights)
        ]
        return sum(deltas) / sum(weights)

    def _cooled_down(self) -> bool:
        if self._last_action is None:
            return True
        return time.monotonic() - self._last_action >= self.config.cooldown

    async def scale_once(self) -> Optional[str]:
        """One scaling decision; returns "up"/"down"/None."""
        load = self.system_load()
        self._samples.append(load)
        n_agents = len(self.orchestrator.agents)
        global_metrics.set_gauge("scaling.system_load", load)

        if (
            load > self.config.scale_up_threshold
            and n_agents < self.config.max_agents
            and self._cooled_down()
        ):
            await self._scale_up()
            return "up"
        if (
            load < self.config.scale_down_threshold
            and self.trend() <= 0
            and n_agents > self.config.min_agents
            and self._cooled_down()
        ):
            if await self._scale_down():
                return "down"
        return None

    async def _scale_up(self) -> None:
        agent = await self.orchestrator.create_agent(self.agent_type)
        self._last_action = time.monotonic()
        self.scale_ups += 1
        global_metrics.inc("scaling.scale_ups")
        self._log.info("scaled up: new agent %s (pool=%d)",
                       agent.id[:8], len(self.orchestrator.agents))

    async def _scale_down(self) -> bool:
        """Drain the lowest-success-rate idle agent (reference ``:193-231``)."""
        idle = [
            a for a in self.orchestrator.agent_list()
            if a.status == AgentStatus.IDLE
            and not a.current_tasks
            and a.task_queue.qsize() == 0
        ]
        if not idle:
            return False
        victim = min(idle, key=lambda a: a.success_rate)
        await self.orchestrator.remove_agent(victim.id)
        self._last_action = time.monotonic()
        self.scale_downs += 1
        global_metrics.inc("scaling.scale_downs")
        self._log.info("scaled down: removed agent %s (pool=%d)",
                       victim.id[:8], len(self.orchestrator.agents))
        return True

    # ------------------------------------------------------------------ #

    def get_metrics(self) -> Dict[str, Any]:
        return {
            "system_load": self.system_load(),
            "trend": self.trend(),
            "agents": len(self.orchestrator.agents),
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "min_agents": self.config.min_agents,
            "max_agents": self.config.max_agents,
        }
