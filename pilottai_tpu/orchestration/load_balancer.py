"""LoadBalancer: periodic queue rebalancing across the agent pool.

Reference parity: ``pilott/orchestration/load_balancer.py`` (391 LoC) —
30s balancing loop (``:73-83``), metric collection + pausing agents over
the overload threshold (``:96-127``), composite load + trend over the last
5 samples (``:161-178``), over/underload classification (``:143-159``),
bounded task moves with best-target selection and safe-mode rollback
(``:180-336``), metrics export (``:338-354``).

TPU grounding: load here is queue pressure feeding the shared engine
batcher — moving a task changes which agent's queue drains it. The
composite replaces the reference's host cpu/mem (taken with a BLOCKING
psutil call inside the async loop, §2.12-h) with non-blocking queue and
error-rate signals.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from pilottai_tpu.core.agent import BaseAgent
from pilottai_tpu.core.config import LoadBalancerConfig
from pilottai_tpu.core.status import AgentStatus
from pilottai_tpu.core.task import Task
from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import global_metrics


class LoadBalancer:
    """Moves queued (not yet running) tasks from hot agents to cold ones."""

    def __init__(
        self,
        orchestrator: Any,  # Serve
        config: Optional[LoadBalancerConfig] = None,
    ) -> None:
        self.orchestrator = orchestrator
        self.config = config or LoadBalancerConfig()
        self._history: Dict[str, deque] = {}  # agent -> recent load samples
        self._paused: set = set()
        self._task: Optional[asyncio.Task] = None
        self._log = get_logger("orchestration.balancer")
        self.moves = 0
        self.failed_moves = 0

    # ------------------------------------------------------------------ #

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._balancing_loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _balancing_loop(self) -> None:
        while True:
            await asyncio.sleep(self.config.check_interval)
            try:
                await self.balance_once()
            except Exception as exc:  # noqa: BLE001 - keep the loop alive
                self._log.error("balancing cycle failed: %s", exc, exc_info=True)

    # ------------------------------------------------------------------ #

    def composite_load(self, agent: BaseAgent) -> float:
        """Queue 0.4 / in-flight 0.3 / error-rate 0.3, floored by raw queue
        utilization so a full queue alone counts as overload (the reference
        mixes cpu 0.3 / mem 0.3 / queue 0.2 / errors 0.2 at ``:172-178``,
        where cpu/mem could saturate independently)."""
        weighted = (
            0.4 * agent.queue_utilization
            + 0.3 * agent.load
            + 0.3 * (1.0 - agent.success_rate)
        )
        return min(1.0, max(agent.queue_utilization, weighted))

    def _record_sample(self, agent: BaseAgent, load: float) -> None:
        window = self._history.setdefault(
            agent.id, deque(maxlen=self.config.trend_window)
        )
        window.append(load)

    def trend(self, agent_id: str) -> float:
        """Positive = rising load (reference ``:161-170``)."""
        window = self._history.get(agent_id)
        if not window or len(window) < 2:
            return 0.0
        return (window[-1] - window[0]) / max(len(window) - 1, 1)

    def classify(self) -> Tuple[List[BaseAgent], List[BaseAgent]]:
        overloaded, underloaded = [], []
        for agent in self.orchestrator.agent_list():
            if not agent.status.is_available and agent.status != AgentStatus.PAUSED:
                continue
            load = self.composite_load(agent)
            self._record_sample(agent, load)
            if load > self.config.overload_threshold:
                overloaded.append(agent)
            elif load < self.config.underload_threshold:
                underloaded.append(agent)
        overloaded.sort(key=self.composite_load, reverse=True)
        underloaded.sort(key=self.composite_load)
        return overloaded, underloaded

    async def balance_once(self) -> int:
        """One rebalancing cycle; returns number of tasks moved."""
        overloaded, underloaded = self.classify()
        await self._manage_pauses(overloaded)
        if not overloaded or not underloaded:
            return 0
        moved = 0
        for hot in overloaded:
            if moved >= self.config.max_tasks_per_cycle:
                break
            moveable = self._moveable_tasks(hot)
            for task in moveable:
                if moved >= self.config.max_tasks_per_cycle:
                    break
                target = self._best_target(task, underloaded)
                if target is None:
                    continue
                if await self._move_task(task, hot, target):
                    moved += 1
        if moved:
            self._log.info("rebalanced %d task(s)", moved)
            global_metrics.inc("balancer.moves", moved)
        return moved

    async def _manage_pauses(self, overloaded: List[BaseAgent]) -> None:
        """Pause agents breaching overload; resume when they cool off
        (reference ``:96-127``)."""
        hot_ids = {a.id for a in overloaded}
        for agent in self.orchestrator.agent_list():
            if agent.id in hot_ids and agent.status == AgentStatus.BUSY:
                continue  # busy agents drain naturally; don't pause mid-task
            if (
                agent.id in hot_ids
                and self.composite_load(agent) > self.config.overload_threshold
                and self.trend(agent.id) > 0
                and agent.status == AgentStatus.IDLE
            ):
                await agent.pause()
                self._paused.add(agent.id)
            elif agent.id in self._paused and agent.id not in hot_ids:
                await agent.resume()
                self._paused.discard(agent.id)

    def _moveable_tasks(self, agent: BaseAgent) -> List[Task]:
        """Pending/queued ∧ not locked ∧ not pinned (reference ``:261-266``)."""
        return [
            t for t in agent.queued_tasks()
            if not t.metadata.get("unmoveable") and not t.status.is_active
        ]

    def _best_target(
        self, task: Task, candidates: List[BaseAgent]
    ) -> Optional[BaseAgent]:
        """Suitability/load/error composite (reference ``:268-336``)."""
        scored = [
            (
                0.5 * c.evaluate_task_suitability(task)
                + 0.3 * (1.0 - self.composite_load(c))
                + 0.2 * c.success_rate,
                c,
            )
            for c in candidates
            if c.status.is_available
        ]
        if not scored:
            return None
        best_score, best = max(scored, key=lambda pair: pair[0])
        return best if best_score > 0.3 else None

    async def _move_task(
        self, task: Task, source: BaseAgent, target: BaseAgent
    ) -> bool:
        """Detach → re-attach with rollback on failure (reference
        ``:220-251`` "safe mode")."""
        detached = source.remove_task(task.id)
        if detached is None:
            return False
        try:
            await target.add_task(detached)
            self.moves += 1
            return True
        except Exception as exc:  # noqa: BLE001 - rollback boundary
            self.failed_moves += 1
            self._log.warning(
                "move %s -> %s failed (%s); rolling back",
                task.id[:8], target.id[:8], exc,
            )
            try:
                await source.add_task(detached)
            except Exception:  # noqa: BLE001 - last resort: orchestrator queue
                # Never orphan work: hand it back to the orchestrator's own
                # queue for fresh routing.
                try:
                    await self.orchestrator.requeue_task(detached)
                    self._log.info("task %s requeued at orchestrator", task.id[:8])
                except Exception:  # noqa: BLE001
                    self._log.error("task %s is orphaned", task.id[:8])
            return False

    # ------------------------------------------------------------------ #

    def get_metrics(self) -> Dict[str, Any]:
        return {
            "moves": self.moves,
            "failed_moves": self.failed_moves,
            "paused_agents": len(self._paused),
            "loads": {
                a.id[:8]: round(self.composite_load(a), 3)
                for a in self.orchestrator.agent_list()
            },
        }
