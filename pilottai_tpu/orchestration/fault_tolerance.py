"""FaultTolerance: health monitoring, bounded recovery, agent replacement
with task migration.

Reference parity: ``pilott/orchestration/scaling.py:34-423`` (the richest
auxiliary subsystem, SURVEY §5.3) — ``AgentHealth`` (``:40-47``), 30s
monitoring loop (``:134-144``), health = f(heartbeat ≤ timeout, stuck
tasks, error count) → 4-level status (``:209-228``), bounded in-place
recovery (stop→reset→start→verify) with attempt cap + cooldown
(``:263-311``), replacement with recoverable-task transfer (``:323-378``),
recovery audit history (``:313-321``), metrics (``:380-389``).

TPU grounding: heartbeats map to per-host liveness (multi-host: over DCN
via ``parallel.mesh.initialize_distributed`` process groups); replacement
maps to re-spawning an agent after TPU-VM preemption, with its queued work
requeued — BASELINE config #5's recovery story.
"""

from __future__ import annotations

import asyncio
import inspect
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from pilottai_tpu.core.agent import BaseAgent
from pilottai_tpu.core.config import FaultToleranceConfig
from pilottai_tpu.core.status import AgentStatus, HealthStatus
from pilottai_tpu.obs.dag import global_dag
from pilottai_tpu.reliability import global_injector
from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import global_metrics


@dataclass
class AgentHealth:
    """Tracked health state per agent (reference ``:40-47``)."""

    agent_id: str
    status: HealthStatus = HealthStatus.HEALTHY
    last_heartbeat: float = field(default_factory=time.time)
    error_count: int = 0
    stuck_tasks: int = 0
    recovery_attempts: int = 0
    last_recovery: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "agent_id": self.agent_id,
            "status": self.status.value,
            "heartbeat_age": time.time() - self.last_heartbeat,
            "error_count": self.error_count,
            "stuck_tasks": self.stuck_tasks,
            "recovery_attempts": self.recovery_attempts,
        }


class FaultTolerance:
    """Watches agents, recovers the sick, replaces the dead."""

    def __init__(
        self,
        orchestrator: Any,  # Serve
        config: Optional[FaultToleranceConfig] = None,
    ) -> None:
        self.orchestrator = orchestrator
        self.config = config or FaultToleranceConfig()
        self.health: Dict[str, AgentHealth] = {}
        self.recovery_history: List[Dict[str, Any]] = []
        # Last observed heartbeat staleness per agent (seconds) — when a
        # stale heartbeat triggers recovery, the affected tasks' DAG
        # retry nodes carry the stall so the lost time is attributed.
        self._last_stall: Dict[str, float] = {}
        self._task: Optional[asyncio.Task] = None
        self._log = get_logger("orchestration.fault")

    # ------------------------------------------------------------------ #

    async def start(self) -> None:
        for agent in self.orchestrator.agent_list():
            self.register_agent(agent)
        if self._task is None:
            self._task = asyncio.create_task(self._monitoring_loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def register_agent(self, agent: BaseAgent) -> None:
        self.health.setdefault(agent.id, AgentHealth(agent_id=agent.id))

    def unregister_agent(self, agent_id: str) -> None:
        self.health.pop(agent_id, None)
        self._last_stall.pop(agent_id, None)
        # Drop the health gauge with the record: a stale gauge for a
        # removed agent reads as a live health report forever.
        global_metrics.remove_gauge(f"fault.health.{agent_id}")

    async def _monitoring_loop(self) -> None:
        while True:
            await asyncio.sleep(self.config.check_interval)
            try:
                await self.check_once()
            except Exception as exc:  # noqa: BLE001 - keep the loop alive
                self._log.error("monitoring cycle failed: %s", exc, exc_info=True)

    # ------------------------------------------------------------------ #

    def _assess(self, agent: BaseAgent) -> AgentHealth:
        """Classify health (reference ``:209-252``)."""
        self.register_agent(agent)
        health = self.health[agent.id]
        info = agent.get_health()
        health.last_heartbeat = info["last_heartbeat"]
        # Chaos point: an injected heartbeat stall of ``value=`` seconds —
        # the agent looks silent without actually wedging anything, so the
        # monitor's stale-heartbeat → recover/replace path is testable.
        stall = global_injector.fire("agent.heartbeat.stall", agent_id=agent.id)
        if stall:
            health.last_heartbeat = min(
                health.last_heartbeat, time.time() - float(stall)
            )
        health.error_count = info["error_count"]
        self._last_stall[agent.id] = max(
            time.time() - health.last_heartbeat, 0.0
        )
        health.stuck_tasks = sum(
            1
            for t in agent.current_tasks.values()
            if t.started_at is not None
            and time.time() - t.started_at > self.config.stuck_task_timeout
        )
        heartbeat_age = time.time() - health.last_heartbeat
        problems = 0
        if heartbeat_age > self.config.heartbeat_timeout:
            problems += 2
        if health.error_count >= self.config.error_threshold:
            problems += 1
        if health.stuck_tasks > 0:
            problems += 1
        if agent.status == AgentStatus.ERROR:
            problems += 2
        if problems == 0:
            health.status = HealthStatus.HEALTHY
        elif problems == 1:
            health.status = HealthStatus.DEGRADED
        elif problems == 2:
            health.status = HealthStatus.UNHEALTHY
        else:
            health.status = HealthStatus.CRITICAL
        return health

    async def check_once(self) -> Dict[str, HealthStatus]:
        """One monitoring pass; recover/replace as needed."""
        statuses: Dict[str, HealthStatus] = {}
        for agent in self.orchestrator.agent_list():
            health = self._assess(agent)
            statuses[agent.id] = health.status
            # Key by FULL id: 8-char prefixes can collide across agents,
            # silently merging two agents' health into one gauge.
            global_metrics.set_gauge(
                f"fault.health.{agent.id}",
                list(HealthStatus).index(health.status),
            )
            if health.status == HealthStatus.UNHEALTHY:
                await self._try_recover(agent, health)
            elif health.status == HealthStatus.CRITICAL:
                if not await self._try_recover(agent, health):
                    await self._replace_agent(agent, health)
        # Reap health records (and their gauges) of agents no longer in
        # the pool.
        live = {a.id for a in self.orchestrator.agent_list()}
        for agent_id in list(self.health):
            if agent_id not in live:
                del self.health[agent_id]
                self._last_stall.pop(agent_id, None)
                global_metrics.remove_gauge(f"fault.health.{agent_id}")
        return statuses

    # ------------------------------------------------------------------ #

    def _recovery_allowed(self, health: AgentHealth) -> bool:
        """Attempt cap + cooldown (reference ``:263-277``)."""
        if health.recovery_attempts >= self.config.max_recovery_attempts:
            return False
        return time.time() - health.last_recovery >= self.config.recovery_cooldown or \
            health.recovery_attempts == 0

    async def _try_recover(self, agent: BaseAgent, health: AgentHealth) -> bool:
        """In-place recovery: stop → reset → start → verify (reference
        ``:279-311``)."""
        if not self._recovery_allowed(health):
            return False
        health.recovery_attempts += 1
        health.last_recovery = time.time()
        self._log.info(
            "recovering agent %s (attempt %d)",
            agent.id[:8], health.recovery_attempts,
        )
        # Detach the backlog first: reset() cancels whatever is still
        # queued, and a stale heartbeat must not cost the agent its work.
        preserved = [
            t for t in (
                agent.remove_task(task.id)
                for task in self._recoverable_tasks(agent)
            ) if t is not None
        ]
        try:
            await agent.stop()
            await agent.reset()
            await agent.start()
            ok = agent.status.is_available
        except Exception as exc:  # noqa: BLE001 - recovery boundary
            self._log.warning("recovery of %s failed: %s", agent.id[:8], exc)
            ok = False
        stall_s = round(self._last_stall.get(agent.id, 0.0), 3)
        now = time.perf_counter()
        for task in preserved:
            # The recovery interruption lands in the task's DAG as a
            # retry node carrying the observed heartbeat stall — a
            # chaos-injected stall is attributable, not silent dead time
            # (the chaos CI lane pins exactly this).
            global_dag.record(
                task.id, "retry", "agent_recovery",
                start=now, end=now,
                agent_id=agent.id[:8], stall_s=stall_s,
            )
            if ok:
                try:
                    await agent.add_task(task)
                    continue
                except Exception:  # noqa: BLE001 - fall through to requeue
                    pass
            await self._requeue(task, stall_s=stall_s)
        self._audit("recover", agent.id, ok)
        if ok:
            health.status = HealthStatus.HEALTHY
            agent.send_heartbeat()
            health.error_count = 0
            global_metrics.inc("fault.recoveries")
        return ok

    async def _replace_agent(self, agent: BaseAgent, health: AgentHealth) -> Optional[BaseAgent]:
        """Spawn a replacement, transfer recoverable work, retire the old
        agent (reference ``:323-378``)."""
        self._log.warning("replacing critical agent %s", agent.id[:8])
        recoverable = self._recoverable_tasks(agent)
        from pilottai_tpu.core.factory import AgentFactory

        # Same registered type when possible; "worker" as the fallback.
        agent_type = agent.config.role_type.value
        if agent_type not in AgentFactory.list_agent_types():
            agent_type = "worker"
        try:
            replacement = await self.orchestrator.create_agent(
                agent_type=agent_type,
                config=agent.config.model_copy(),
            )
        except Exception as exc:  # noqa: BLE001 - replacement boundary
            self._log.error("replacement spawn failed: %s", exc)
            self._audit("replace", agent.id, False)
            return None
        transferred = 0
        had_worker = agent._worker_task is not None
        for task in recoverable:
            detached = agent.remove_task(task.id)
            if detached is None:
                continue
            try:
                await replacement.add_task(detached)
                transferred += 1
            except Exception:  # noqa: BLE001 - saturated queue etc.
                await self._requeue(detached)
        if had_worker:
            # Mirror the old agent's drive mode, or transferred work would
            # sit queued with nothing draining it.
            replacement.start_queue_worker()
        await self.orchestrator.remove_agent(agent.id)
        self.unregister_agent(agent.id)
        self.register_agent(replacement)
        self._audit(
            "replace", agent.id, True,
            extra={"replacement": replacement.id, "transferred": transferred},
        )
        global_metrics.inc("fault.replacements")
        self._log.info(
            "replaced %s -> %s (%d task(s) transferred)",
            agent.id[:8], replacement.id[:8], transferred,
        )
        return replacement

    async def _requeue(self, task: Any, **dag_attrs: Any) -> None:
        """Route a detached task back through orchestrator routing; a task
        must never be silently orphaned. The DAG attribution kwargs are
        passed only when the orchestrator's signature accepts them
        (custom/stub orchestrators may predate the DAG-aware requeue) —
        probed via inspection, NOT except TypeError, which would also
        swallow real TypeErrors raised inside the awaited call."""
        kwargs: Dict[str, Any] = {}
        try:
            params = inspect.signature(
                self.orchestrator.requeue_task
            ).parameters
            var_kw = any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values()
            )
            # Filter PER KWARG: an orchestrator accepting `reason` but
            # not **kwargs must not be handed stall_s and blow up.
            for key, value in (
                ("reason", "fault_recovery"), *dag_attrs.items()
            ):
                if var_kw or key in params:
                    kwargs[key] = value
        except (TypeError, ValueError):  # uninspectable callable
            pass
        try:
            await self.orchestrator.requeue_task(task, **kwargs)
        except Exception as exc:  # noqa: BLE001 - last resort: log loudly
            self._log.error("task %s lost: requeue failed: %s", task.id[:8], exc)

    def _recoverable_tasks(self, agent: BaseAgent) -> List[Any]:
        """Queued ∧ not marked non-recoverable (reference ``:354-378``)."""
        return [
            t for t in agent.queued_tasks()
            if not t.metadata.get("non_recoverable")
        ]

    def _audit(self, action: str, agent_id: str, ok: bool, extra: Optional[Dict] = None) -> None:
        self.recovery_history.append(
            {
                "action": action,
                "agent_id": agent_id,
                "success": ok,
                "ts": time.time(),
                **(extra or {}),
            }
        )
        if len(self.recovery_history) > 1000:
            del self.recovery_history[:500]

    # ------------------------------------------------------------------ #

    def get_metrics(self) -> Dict[str, Any]:
        counts: Dict[str, int] = {}
        for health in self.health.values():
            counts[health.status.value] = counts.get(health.status.value, 0) + 1
        return {
            "agents_tracked": len(self.health),
            "by_status": counts,
            "recoveries": int(global_metrics.get("fault.recoveries")),
            "replacements": int(global_metrics.get("fault.replacements")),
            "audit_entries": len(self.recovery_history),
        }
