"""Orchestration control plane: load balancing, autoscaling, fault
tolerance.

Reference parity: ``pilott/orchestration/`` — LoadBalancer
(``load_balancer.py``), DynamicScaling (``orchestration.py``; the
reference ships a dead duplicate in ``scaling.py:425-666``, §2.12-d — one
copy here), FaultTolerance (``scaling.py:34-423``). Unlike the reference,
these are wired into ``Serve``'s lifecycle (ServeConfig flags) instead of
floating unattached (§3.1), and their load signals come from agent queues
and engine metrics rather than blocking psutil probes (§2.12-h).
"""

from pilottai_tpu.orchestration.fault_tolerance import AgentHealth, FaultTolerance
from pilottai_tpu.orchestration.load_balancer import LoadBalancer
from pilottai_tpu.orchestration.scaling import DynamicScaling

__all__ = ["LoadBalancer", "DynamicScaling", "FaultTolerance", "AgentHealth"]
