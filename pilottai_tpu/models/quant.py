"""Weight-only quantization for serving: int8 and packed int4.

Decode is HBM-bound on the weight stream (the whole model is read every
token); storing matmul weights below compute precision shrinks that
traffic proportionally. XLA fuses the in-jit dequant
(``q.astype(bf16) * s``) into the matmul's operand read — measured on
v5e for int8: 26 µs vs 47 µs per [2048, 8192] layer matmul (647 GB/s
effective on half the bytes), a 1.8× step-time win with zero custom
kernels. int4 (ISSUE 14) halves the stream again: two nibbles per int8
byte along the contraction axis, unpacked with two shifts in-jit so the
HBM read stays the packed buffer.

Schemes:

* **int8** (``QTensor``): symmetric per-output-channel over the
  contraction axis (``axis=-2`` of the stacked ``[L, in, out]`` layer
  weights), the standard weight-only recipe (~negligible quality delta
  at 8 bits).
* **int4** (``Q4Tensor``): symmetric per-**group** scales over the
  contraction axis (``engine_quant_group`` rows per scale, default
  128) — at 4 bits a single whole-column scale visibly hurts quality;
  group scales bound the error to the group's own dynamic range at a
  cost of ``4/group`` extra bits per weight. Quantization-sensitive
  leaves fall back: ``lm_head`` stays int8 (logit argmax decides the
  token) and the MoE router stays dense (its logits pick *which*
  experts run).

Norms, embeds and rope tables stay in the compute dtype — they are <1%
of bytes.

Serving-only: the trainer keeps full-precision weights; the engine
quantizes once at load (``NativeEngine.start``), which also shrinks the
params' HBM footprint.

No reference counterpart (the reference computes no attention at all —
SURVEY.md §2.13); this is TPU-first engineering for the ≤500 ms p50
agent-step target (BASELINE.md) and the ≥0.15 MFU 8B decode target
(ROADMAP item 3).
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class QTensor(NamedTuple):
    """int8 weight + broadcastable scale. A pytree node, so stacked-layer
    slicing (``jax.tree.map(lambda a: a[l], layers)``) and ``lax.scan``
    carry it transparently."""

    q: jax.Array  # int8, same shape as the original weight
    s: jax.Array  # compute dtype, shape [..., 1, out]


@jax.tree_util.register_pytree_node_class
class Q4Tensor:
    """Packed int4 weight: two nibbles per int8 byte along the
    contraction axis (``axis=-2``), per-group scales.

    ``q`` is int8 ``[..., ceil(in/2), out]`` — byte ``b`` holds the
    nibble for row ``2b`` in its low bits and row ``2b+1`` in its high
    bits (an odd trailing row pads with a zero nibble). ``s`` is the
    compute-dtype scale ``[..., n_groups, out]`` with
    ``n_groups = ceil(in/group)``. The true contraction length and the
    group width ride as static pytree aux data, so stacked-layer
    slicing and ``lax.scan`` carry the tensor exactly like ``QTensor``
    (aux is layer-invariant — slicing the leading layer axis never
    changes the contraction length)."""

    def __init__(self, q: jax.Array, s: jax.Array, in_dim: int, group: int):
        self.q = q
        self.s = s
        self.in_dim = int(in_dim)
        self.group = int(group)

    def tree_flatten(self):
        return (self.q, self.s), (self.in_dim, self.group)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0], aux[1])

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"Q4Tensor(q={getattr(self.q, 'shape', None)}, "
            f"s={getattr(self.s, 'shape', None)}, in_dim={self.in_dim}, "
            f"group={self.group})"
        )


def pack_int4(q8: jax.Array) -> jax.Array:
    """Pack int8 values in [-8, 7] into nibbles along ``axis=-2``:
    ``[..., in, out]`` → ``[..., ceil(in/2), out]``. Row ``2b`` lands in
    the byte's low nibble, ``2b+1`` in the high nibble; an odd trailing
    row is padded with zero. All arithmetic stays in int8 (left shifts
    wrap, which is exactly two's-complement nibble packing)."""
    if q8.shape[-2] % 2:
        pad = [(0, 0)] * q8.ndim
        pad[-2] = (0, 1)
        q8 = jnp.pad(q8, pad)
    lo = q8[..., 0::2, :]
    hi = q8[..., 1::2, :]
    return ((hi << 4) | (lo & 0xF)).astype(jnp.int8)


def unpack_int4(packed: jax.Array, in_dim: int) -> jax.Array:
    """Inverse of ``pack_int4``: int8 nibbles back to int8 values in
    [-8, 7], trimmed to the true contraction length. Sign recovery is
    two arithmetic shifts (``<<4 >>4`` for the low nibble, ``>>4`` for
    the high one) — in-jit these fuse into the consumer, so the HBM
    read of a packed weight stays the packed buffer."""
    lo = ((packed << 4) >> 4).astype(jnp.int8)
    hi = (packed >> 4).astype(jnp.int8)
    both = jnp.stack([lo, hi], axis=-2)            # [..., P, 2, out]
    shape = packed.shape[:-2] + (2 * packed.shape[-2], packed.shape[-1])
    return both.reshape(shape)[..., :in_dim, :]


def dequant(w: Any) -> jax.Array:
    """QTensor/Q4Tensor -> dense weight in the scale's dtype;
    pass-through for plain arrays. Call at the matmul site — inside jit
    XLA fuses the convert+mul (and the int4 nibble shifts) into the
    operand read, so no dense copy lands in HBM."""
    if isinstance(w, QTensor):
        return w.q.astype(w.s.dtype) * w.s
    if isinstance(w, Q4Tensor):
        q = unpack_int4(w.q, w.in_dim)
        # Per-group scales broadcast back over the contraction axis
        # (the last group may be a remainder — trim after the repeat).
        s = jnp.repeat(w.s, w.group, axis=-2)[..., : w.in_dim, :]
        return q.astype(w.s.dtype) * s
    return w


def quantize_array(
    w: jax.Array, dtype=jnp.bfloat16, bits: int = 8, group: int = 128
) -> Any:
    """Symmetric weight-only quantization over the contraction axis
    (axis=-2). ``w`` is [..., in, out].

    * ``bits=8``: per-output-channel scales → ``QTensor``.
    * ``bits=4``: per-(group × output-channel) scales → packed
      ``Q4Tensor``. Groups of ``group`` contraction rows share one
      scale; a non-dividing trailing group is simply smaller (its amax
      runs over the real rows only — zero padding never inflates it).
    """
    if bits == 8:
        amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True)
        scale = jnp.maximum(amax, 1e-8) / 127.0
        q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
        return QTensor(q=q.astype(jnp.int8), s=scale.astype(dtype))
    if bits != 4:
        raise ValueError(f"unsupported weight quantization bits={bits}")
    group = max(1, int(group))
    in_dim = w.shape[-2]
    n_groups = -(-in_dim // group)
    wf = w.astype(jnp.float32)
    if n_groups * group != in_dim:
        pad = [(0, 0)] * wf.ndim
        pad[-2] = (0, n_groups * group - in_dim)
        wf = jnp.pad(wf, pad)
    grouped = wf.reshape(wf.shape[:-2] + (n_groups, group, wf.shape[-1]))
    amax = jnp.max(jnp.abs(grouped), axis=-2, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 7.0          # [..., G, 1, out]
    q = jnp.clip(jnp.round(grouped / scale), -8, 7).astype(jnp.int8)
    q = q.reshape(wf.shape[:-2] + (n_groups * group, wf.shape[-1]))
    q = q[..., :in_dim, :]
    return Q4Tensor(
        q=pack_int4(q), s=scale[..., 0, :].astype(dtype),
        in_dim=in_dim, group=group,
    )


def _is_quantized(x: Any) -> bool:
    return isinstance(x, (QTensor, Q4Tensor))


def quantize_params(
    params: Any,
    dtype=jnp.bfloat16,
    donate: bool = False,
    bits: int = 8,
    group: int = 128,
) -> Any:
    """Quantize every stacked matmul weight (ndim >= 3 under ``layers``,
    plus an untied ``lm_head``). Embeds/norms stay dense. Runs under jit
    so the quantized tensors are produced on device and the
    full-precision originals can be freed.

    ``bits=4`` packs layer matmuls as ``Q4Tensor`` with the
    quantization-sensitive fallbacks: ``lm_head`` stays **int8** (its
    argmax picks the emitted token — the one matmul where 4-bit noise
    changes outputs rather than just values, and it is a small share of
    the per-token bytes) and the MoE router stays **dense** for the
    same selection-sensitivity reason int8 already left it dense.
    Already-int8 ``QTensor`` leaves (the eager-init / checkpoint path)
    re-quantize from their dequantized values — deterministic, and the
    dequant fuses into the group-amax/round consumers so the dense fp32
    stack never materializes whole.

    ``donate=True`` consumes the input tree: untouched leaves (norms,
    embeds, already-quantized tensors) alias through instead of being
    copied — without this the pass-through copy of an 8B tree doubles
    HBM and OOMs a v5e. The caller's reference becomes invalid."""

    from jax.tree_util import tree_map_with_path

    def _quant_leaf(path, a):
        keys = {getattr(k, "key", None) for k in path}
        if bits == 8:
            if _is_quantized(a):   # already quantized (init-time path)
                return a
            # Norm scales are 2D-stacked (skip by ndim); the MoE router
            # stays dense — its logits drive top-k expert selection, the
            # one matmul where 8-bit error changes *which* weights run,
            # not just their values. It is also a tiny fraction of the
            # bytes.
            if "router" in keys or a.ndim < 3:
                return a
            return quantize_array(a, dtype)
        # bits == 4
        if isinstance(a, Q4Tensor):
            return a
        if "router" in keys:
            return dequant(a) if isinstance(a, QTensor) else a
        if isinstance(a, QTensor):
            return quantize_array(dequant(a), dtype, bits=4, group=group)
        if a.ndim < 3:
            return a
        return quantize_array(a, dtype, bits=4, group=group)

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def _quant(p):
        out = dict(p)
        out["layers"] = tree_map_with_path(
            _quant_leaf, p["layers"], is_leaf=_is_quantized,
        )
        if "lm_head" in p:
            head = p["lm_head"]
            # int4 fallback: the head quantizes to int8 in BOTH modes.
            if isinstance(head, Q4Tensor):
                head = quantize_array(dequant(head), dtype)
            elif isinstance(head, QTensor):
                pass
            else:
                head = quantize_array(head, dtype)
            out["lm_head"] = head
        return out

    return _quant(params)


def weight_stream_bytes(params: Any) -> dict:
    """Measured byte accounting for the decode weight stream (the
    ``engine.weight_bytes*`` gauges — ISSUE 14 makes the bytes-halved
    claim a measured series, not a docstring).

    * ``total``: resident bytes of the whole parameter tree (global
      logical bytes — divide by the TP shard count for per-chip).
    * ``per_token``: bytes streamed from HBM per decode token — every
      layer weight, the final norm, and the unembedding head (the tied
      ``embed`` matrix streams whole through the logits projection;
      an untied head counts ``lm_head`` and the embed table drops out,
      as decode's embedding lookup gathers a single row).

    MoE note: this repo's MoE uses dense dispatch (every expert
    computes every token — models/moe.py), so *all* expert bytes
    stream per token and are counted as such.
    """
    def _tree_bytes(tree) -> int:
        total = 0
        for leaf in jax.tree.leaves(tree, is_leaf=_is_quantized):
            if _is_quantized(leaf):
                total += int(leaf.q.size)  # int8 storage, 1 byte each
                total += int(leaf.s.size) * jnp.dtype(leaf.s.dtype).itemsize
            else:
                total += int(leaf.size) * jnp.dtype(leaf.dtype).itemsize
        return total

    total = _tree_bytes(params)
    per_token = _tree_bytes(
        {k: v for k, v in params.items() if k != "embed"}
    )
    if "lm_head" not in params and "embed" in params:
        per_token += _tree_bytes(params["embed"])
    return {"total": int(total), "per_token": int(per_token)}


__all__ = [
    "QTensor",
    "Q4Tensor",
    "dequant",
    "pack_int4",
    "unpack_int4",
    "quantize_array",
    "quantize_params",
    "weight_stream_bytes",
]
