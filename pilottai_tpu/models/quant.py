"""Weight-only int8 quantization for serving.

Decode is HBM-bound on the weight stream (the whole model is read every
token); storing matmul weights as int8 with per-output-channel bf16
scales halves that traffic. XLA fuses the in-jit dequant
(``q.astype(bf16) * s``) into the matmul's operand read — measured on
v5e: 26 µs vs 47 µs per [2048, 8192] layer matmul (647 GB/s effective on
half the bytes), a 1.8× step-time win with zero custom kernels.

Scheme: symmetric per-output-channel over the contraction axis
(``axis=-2`` of the stacked ``[L, in, out]`` layer weights), the standard
weight-only recipe (~negligible quality delta at 8 bits). Norms, embeds
and rope tables stay in the compute dtype — they are <1% of bytes.

Serving-only: the trainer keeps full-precision weights; the engine
quantizes once at load (``NativeEngine.start``), which also halves the
params' HBM footprint.

No reference counterpart (the reference computes no attention at all —
SURVEY.md §2.13); this is TPU-first engineering for the ≤500 ms p50
agent-step target (BASELINE.md).
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class QTensor(NamedTuple):
    """int8 weight + broadcastable scale. A pytree node, so stacked-layer
    slicing (``jax.tree.map(lambda a: a[l], layers)``) and ``lax.scan``
    carry it transparently."""

    q: jax.Array  # int8, same shape as the original weight
    s: jax.Array  # compute dtype, shape [..., 1, out]


def dequant(w: Any) -> jax.Array:
    """QTensor -> dense weight in the scale's dtype; pass-through for
    plain arrays. Call at the matmul site — inside jit XLA fuses the
    convert+mul into the operand read, so no dense copy lands in HBM."""
    if isinstance(w, QTensor):
        return w.q.astype(w.s.dtype) * w.s
    return w


def quantize_array(w: jax.Array, dtype=jnp.bfloat16) -> QTensor:
    """Symmetric per-output-channel int8 over the contraction axis
    (axis=-2). ``w`` is [..., in, out]."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return QTensor(q=q.astype(jnp.int8), s=scale.astype(dtype))


def quantize_params(params: Any, dtype=jnp.bfloat16, donate: bool = False) -> Any:
    """Quantize every stacked matmul weight (ndim >= 3 under ``layers``,
    plus an untied ``lm_head``). Embeds/norms stay dense. Runs under jit
    so the int8 tensors are produced on device and the full-precision
    originals can be freed.

    ``donate=True`` consumes the input tree: untouched leaves (norms,
    embeds, already-quantized QTensors) alias through instead of being
    copied — without this the pass-through copy of an 8B tree doubles
    HBM and OOMs a v5e. The caller's reference becomes invalid."""

    from jax.tree_util import tree_map_with_path

    def _quant_leaf(path, a):
        if isinstance(a, QTensor):  # already quantized (init-time path)
            return a
        keys = {getattr(k, "key", None) for k in path}
        # Norm scales are 2D-stacked (skip by ndim); the MoE router stays
        # dense — its logits drive top-k expert selection, the one matmul
        # where 8-bit error changes *which* weights run, not just their
        # values. It is also a tiny fraction of the bytes.
        if "router" in keys or a.ndim < 3:
            return a
        return quantize_array(a, dtype)

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def _quant(p):
        out = dict(p)
        out["layers"] = tree_map_with_path(
            _quant_leaf, p["layers"],
            is_leaf=lambda x: isinstance(x, QTensor),
        )
        if "lm_head" in p and not isinstance(p["lm_head"], QTensor):
            out["lm_head"] = quantize_array(p["lm_head"], dtype)
        return out

    return _quant(params)


__all__ = ["QTensor", "dequant", "quantize_array", "quantize_params"]
