"""Model zoo: Llama-3 and Gemma families in pure-functional JAX.

New TPU-native surface (the reference delegates all inference to remote
APIs, ``pilott/engine/llm.py:59``). Params are plain pytrees with stacked
layers (``lax.scan`` over depth → O(1) compile in layer count); sharding is
declared once via logical axes (``pilottai_tpu/parallel/sharding.py``).
"""

from pilottai_tpu.models.common import ModelConfig, init_params, param_logical_axes
from pilottai_tpu.models.registry import get_model_config, list_models, register_model
from pilottai_tpu.models.transformer import forward_decode, forward_prefill

__all__ = [
    "ModelConfig",
    "init_params",
    "param_logical_axes",
    "forward_prefill",
    "forward_decode",
    "get_model_config",
    "list_models",
    "register_model",
]
