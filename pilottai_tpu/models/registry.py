"""Model registry: name → ModelConfig."""

from __future__ import annotations

from typing import Dict, List

from pilottai_tpu.models import gemma, llama
from pilottai_tpu.models.common import ModelConfig

_REGISTRY: Dict[str, ModelConfig] = {}


def register_model(config: ModelConfig) -> None:
    _REGISTRY[config.name] = config


for _cfg in (
    llama.LLAMA3_8B,
    llama.LLAMA3_1B,
    llama.LLAMA3_8B_BYTE,
    llama.LLAMA3_1B_BYTE,
    llama.LLAMA_TINY,
    llama.PROTOCOL_S,
    llama.PROTOCOL_XS,
    llama.MIXTRAL_8X7B,
    llama.MIXTRAL_8X7B_BYTE,
    llama.MOE_TINY,
    gemma.GEMMA_2B,
    gemma.GEMMA2_2B,
    gemma.GEMMA_2B_BYTE,
    gemma.GEMMA_TINY,
):
    register_model(_cfg)


def get_model_config(name: str) -> ModelConfig:
    if name not in _REGISTRY:
        raise KeyError(
            f"unknown model {name!r}; available: {sorted(_REGISTRY)}"
        )
    return _REGISTRY[name]


def list_models() -> List[str]:
    return sorted(_REGISTRY)
