"""Gemma family configurations.

Gemma-1: GeGLU MLP, embedding scaled by sqrt(hidden), RMSNorm with +1
offset, tied embeddings. Gemma-2 adds logit/attention soft-caps,
post-layer norms and alternating sliding-window/global attention.
The 2B encoder also backs semantic memory (BASELINE.json config #2).
"""

from pilottai_tpu.models.common import ModelConfig

GEMMA_2B = ModelConfig(
    name="gemma-2b",
    family="gemma",
    vocab_size=256_128,
    hidden_size=2048,
    n_layers=18,
    n_heads=8,
    n_kv_heads=1,
    head_dim=256,
    intermediate_size=16_384,
    max_seq_len=8192,
    rope_theta=10_000.0,
    rms_eps=1e-6,
    tie_embeddings=True,
    act="gelu_tanh",
    scale_embed=True,
    rms_offset=True,
)

GEMMA2_2B = ModelConfig(
    name="gemma2-2b",
    family="gemma2",
    vocab_size=256_128,
    hidden_size=2304,
    n_layers=26,
    n_heads=8,
    n_kv_heads=4,
    head_dim=256,
    intermediate_size=9216,
    max_seq_len=8192,
    rope_theta=10_000.0,
    rms_eps=1e-6,
    tie_embeddings=True,
    act="gelu_tanh",
    scale_embed=True,
    rms_offset=True,
    post_norms=True,
    logit_softcap=30.0,
    attn_softcap=50.0,
    sliding_window=4096,
    sliding_pattern=2,
    query_scale=256.0**-0.5,
)

GEMMA_2B_BYTE = GEMMA_2B.replace(name="gemma-2b-byte", vocab_size=512)

GEMMA_TINY = ModelConfig(
    name="gemma-tiny",
    family="gemma2",
    vocab_size=512,
    hidden_size=128,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    head_dim=32,
    intermediate_size=256,
    max_seq_len=512,
    act="gelu_tanh",
    scale_embed=True,
    rms_offset=True,
    post_norms=True,
    logit_softcap=30.0,
    attn_softcap=50.0,
    sliding_window=128,
    sliding_pattern=2,
)
