"""Checkpoint loading: HF safetensors → stacked-layer pytree, plus
orbax-style native save/restore.

Weights are loaded layer-by-layer on host then device_put with their
sharding (so an 8B model never needs 2x host RAM), and stacked along the
leading layer axis to match the scan layout. No downloads — paths must be
local (zero-egress environment).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pilottai_tpu.models.common import ModelConfig, param_logical_axes
from pilottai_tpu.parallel.sharding import named_sharding

# HF parameter name templates per family (same for llama/gemma trunks).
_HF_LAYER_MAP = {
    ("ln1", "scale"): "model.layers.{i}.input_layernorm.weight",
    ("ln2", "scale"): "model.layers.{i}.post_attention_layernorm.weight",
    ("ln1_post", "scale"): "model.layers.{i}.post_attention_layernorm.weight",  # gemma2 naming handled below
    ("ln2_post", "scale"): "model.layers.{i}.post_feedforward_layernorm.weight",
    ("attn", "wq"): "model.layers.{i}.self_attn.q_proj.weight",
    ("attn", "wk"): "model.layers.{i}.self_attn.k_proj.weight",
    ("attn", "wv"): "model.layers.{i}.self_attn.v_proj.weight",
    ("attn", "wo"): "model.layers.{i}.self_attn.o_proj.weight",
    ("mlp", "wg"): "model.layers.{i}.mlp.gate_proj.weight",
    ("mlp", "wu"): "model.layers.{i}.mlp.up_proj.weight",
    ("mlp", "wd"): "model.layers.{i}.mlp.down_proj.weight",
}

_GEMMA2_OVERRIDES = {
    ("ln1_post", "scale"): "model.layers.{i}.post_attention_layernorm.weight",
    ("ln2", "scale"): "model.layers.{i}.pre_feedforward_layernorm.weight",
    ("ln2_post", "scale"): "model.layers.{i}.post_feedforward_layernorm.weight",
}


def _open_safetensors(path: Path):
    """Index all *.safetensors shards under ``path`` → {tensor_name: (file, reader)}."""
    from safetensors import safe_open  # ships with transformers

    index: Dict[str, Path] = {}
    index_file = path / "model.safetensors.index.json"
    if index_file.exists():
        weight_map = json.loads(index_file.read_text())["weight_map"]
        for name, fname in weight_map.items():
            index[name] = path / fname
    else:
        for f in sorted(path.glob("*.safetensors")):
            with safe_open(str(f), framework="np") as reader:
                for name in reader.keys():
                    index[name] = f
    return index


def load_hf_checkpoint(
    cfg: ModelConfig,
    path: str | Path,
    mesh: Optional[Any] = None,
    dtype=jnp.bfloat16,
) -> Dict[str, Any]:
    """Load a HF-layout safetensors checkpoint into the stacked pytree.

    HF linear weights are [out, in]; ours are [in, out] → transpose.
    """
    from safetensors import safe_open

    path = Path(path)
    index = _open_safetensors(path)
    axes = param_logical_axes(cfg)

    _readers: Dict[Path, Any] = {}

    def read(name: str) -> np.ndarray:
        f = index[name]
        if f not in _readers:
            _readers[f] = safe_open(str(f), framework="np")
        return _readers[f].get_tensor(name)

    def place(arr: np.ndarray, logical) -> jax.Array:
        arr = jnp.asarray(arr, dtype=dtype)
        if mesh is not None:
            return jax.device_put(arr, named_sharding(mesh, logical))
        return arr

    layer_map = dict(_HF_LAYER_MAP)
    if cfg.family == "gemma2":
        layer_map.update(_GEMMA2_OVERRIDES)

    # Stack per-layer tensors along the leading axis.
    layers: Dict[str, Dict[str, Any]] = {}
    for (group, leaf), template in layer_map.items():
        if group not in axes["layers"]:
            continue
        stack = []
        for i in range(cfg.n_layers):
            t = read(template.format(i=i))
            if leaf.startswith("w"):
                t = t.T  # HF [out,in] -> [in,out]
            stack.append(np.asarray(t))
        layers.setdefault(group, {})[leaf] = place(
            np.stack(stack), axes["layers"][group][leaf]
        )

    params: Dict[str, Any] = {
        "embed": place(read("model.embed_tokens.weight"), axes["embed"]),
        "layers": layers,
        "final_norm": {
            "scale": place(read("model.norm.weight"), axes["final_norm"]["scale"])
        },
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = place(read("lm_head.weight").T, axes["lm_head"])
    for reader in _readers.values():
        del reader
    return params


# ------------------------- native checkpointing ------------------------- #

def is_hf_checkpoint(path: str | Path) -> bool:
    """True when ``path`` holds HF-layout safetensors (vs an orbax tree
    written by ``save_params``)."""
    path = Path(path)
    return (
        (path / "model.safetensors.index.json").exists()
        or any(path.glob("*.safetensors"))
    )


def load_checkpoint(
    cfg: ModelConfig,
    path: str | Path,
    mesh: Optional[Any] = None,
    dtype=jnp.bfloat16,
) -> Dict[str, Any]:
    """Format-dispatching load: HF safetensors or native orbax."""
    if is_hf_checkpoint(path):
        return load_hf_checkpoint(cfg, path, mesh=mesh, dtype=dtype)
    return load_native_checkpoint(cfg, path, mesh=mesh, dtype=dtype)


def load_native_checkpoint(
    cfg: ModelConfig,
    path: str | Path,
    mesh: Optional[Any] = None,
    dtype=jnp.bfloat16,
) -> Dict[str, Any]:
    """Load an orbax params tree written by ``save_params`` (e.g. the
    protocol model, ``train/protocol.py``): cast floating leaves to the
    serving dtype and place on the mesh by logical axes."""
    from pilottai_tpu.parallel.sharding import shard_params

    raw = restore_params(path)

    def _cast(a):
        a = jnp.asarray(a)
        return a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) else a

    params = jax.tree.map(_cast, raw)
    if mesh is not None:
        params = shard_params(params, param_logical_axes(cfg), mesh)
    return params


def save_params(params: Dict[str, Any], path: str | Path) -> None:
    """Orbax save (durable model checkpoint; reference has no checkpointing
    at all, SURVEY.md §5.4)."""
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    ckpt = ocp.PyTreeCheckpointer()
    ckpt.save(path, params, force=True)


def restore_params(path: str | Path, target: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    import orbax.checkpoint as ocp

    ckpt = ocp.PyTreeCheckpointer()
    return ckpt.restore(Path(path).absolute(), item=target)
