"""Llama-3 family configurations.

Architectures per the public Llama-3 papers/configs: SwiGLU MLP, GQA,
RoPE theta 500k, RMSNorm, untied lm_head on 8B+. The ``*-byte`` variants
pair the architecture with the in-tree byte tokenizer (512-vocab) for
checkpoint-free serving and benchmarking — same compute graph per token,
so steps/sec numbers transfer.
"""

from pilottai_tpu.models.common import ModelConfig

LLAMA3_8B = ModelConfig(
    name="llama3-8b",
    family="llama",
    vocab_size=128_256,
    hidden_size=4096,
    n_layers=32,
    n_heads=32,
    n_kv_heads=8,
    head_dim=128,
    intermediate_size=14336,
    max_seq_len=8192,
    rope_theta=500_000.0,
    rms_eps=1e-5,
    tie_embeddings=False,
)

LLAMA3_1B = ModelConfig(
    name="llama3-1b",
    family="llama",
    vocab_size=128_256,
    hidden_size=2048,
    n_layers=16,
    n_heads=32,
    n_kv_heads=8,
    head_dim=64,
    intermediate_size=8192,
    max_seq_len=8192,
    rope_theta=500_000.0,
    rms_eps=1e-5,
    tie_embeddings=True,
)

# Byte-vocab variants: identical trunk, 512-token byte vocab — runnable with
# random init (no checkpoint, no downloads) for benches and smoke tests.
LLAMA3_8B_BYTE = LLAMA3_8B.replace(name="llama3-8b-byte", vocab_size=512, tie_embeddings=True)
LLAMA3_1B_BYTE = LLAMA3_1B.replace(name="llama3-1b-byte", vocab_size=512)

# Small configs for tests / CI (CPU-jax).
LLAMA_TINY = ModelConfig(
    name="llama-tiny",
    family="llama",
    vocab_size=512,
    hidden_size=128,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    head_dim=32,
    intermediate_size=256,
    max_seq_len=512,
)

# Mixtral-style sparse MoE on the Llama trunk (public Mixtral-8x7B shape:
# 8 experts, top-2 routing, RoPE theta 1e6).
MIXTRAL_8X7B = ModelConfig(
    name="mixtral-8x7b",
    family="llama",
    vocab_size=32_000,
    hidden_size=4096,
    n_layers=32,
    n_heads=32,
    n_kv_heads=8,
    head_dim=128,
    intermediate_size=14336,
    max_seq_len=32_768,
    rope_theta=1_000_000.0,
    rms_eps=1e-5,
    tie_embeddings=False,
    n_experts=8,
    n_active_experts=2,
)
MIXTRAL_8X7B_BYTE = MIXTRAL_8X7B.replace(
    name="mixtral-8x7b-byte", vocab_size=512, tie_embeddings=True
)

# Tiny MoE for tests / the multichip dry run (exercises expert parallelism).
MOE_TINY = LLAMA_TINY.replace(name="moe-tiny", n_experts=4, n_active_experts=2)

# The agent-protocol model: a small byte-vocab Llama trunk sized to learn
# the rules.yaml JSON wire protocol (train/protocol.py) and serve it fast —
# ~4M params, so one decode step is microseconds of device time and a
# 32-agent swarm shares one chip trivially. vocab 384 == ByteTokenizer's
# padded vocab, so the trained checkpoint needs no vocab shim at serve time.
PROTOCOL_S = ModelConfig(
    name="protocol-s",
    family="llama",
    vocab_size=384,
    hidden_size=256,
    n_layers=4,
    n_heads=8,
    n_kv_heads=4,
    head_dim=32,
    intermediate_size=1024,
    max_seq_len=1024,
    tie_embeddings=True,
)

# Micro variant for CPU tests of the training recipe (fast convergence
# checks, not a servable artifact).
PROTOCOL_XS = PROTOCOL_S.replace(
    name="protocol-xs", hidden_size=128, n_layers=2, n_heads=4, n_kv_heads=2,
    intermediate_size=384, max_seq_len=512,
)
