"""Mixture-of-experts MLP with top-k routing and expert parallelism.

TPU-first design choice: *dense dispatch*. Every expert computes every
token (static shapes, pure einsums onto the MXU, no ragged gather or
host round-trips) and the top-k gate zeroes non-selected contributions
at combine time. Costs n_experts/k more MLP FLOPs than sparse dispatch,
in exchange for zero dynamic shapes and a trivially shardable expert
axis: with experts sharded over the ``expert`` logical axis (mesh
``model`` by default), each device runs only its local experts and the
combine's sum over experts becomes one XLA psum over ICI — expert
parallelism without an all-to-all. A grouped-GEMM Pallas kernel is the
planned upgrade path for large expert counts.

No reference counterpart (the reference has no model execution,
SURVEY.md §2.13).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from pilottai_tpu.models.qmatmul import qmatmul

from pilottai_tpu.parallel.sharding import with_logical_constraint


def moe_mlp(
    cfg: Any,                 # ModelConfig (n_experts, n_active_experts, act)
    p: Dict[str, Any],        # layer slice: router [E,X], wg/wu [X,E,F], wd [X,F,E]
    x: jax.Array,             # [B, T, E]
    activation,               # callable matching the dense MLP's activation
) -> Tuple[jax.Array, jax.Array]:
    """Top-k routed MoE feed-forward.

    Returns (out [B, T, E], aux_loss scalar). aux_loss is the Switch-style
    load-balancing term (mean fraction routed × mean router probability ×
    n_experts, = 1.0 at perfect balance); the trainer weights and adds it.
    """
    X = cfg.n_experts
    k = min(cfg.n_active_experts, X)

    router_logits = jnp.einsum("bte,ex->btx", x, p["router"]).astype(jnp.float32)
    probs = jax.nn.softmax(router_logits, axis=-1)            # [B, T, X]
    top_w, top_idx = jax.lax.top_k(probs, k)                  # [B, T, k]
    top_w = top_w / jnp.maximum(
        jnp.sum(top_w, axis=-1, keepdims=True), 1e-9
    )
    # Dense combine weights: scatter top-k back to [B, T, X] via one-hot.
    one_hot = jax.nn.one_hot(top_idx, X, dtype=top_w.dtype)   # [B, T, k, X]
    combine = jnp.einsum("btk,btkx->btx", top_w, one_hot)     # [B, T, X]

    frac_routed = jnp.mean(one_hot[..., 0, :].reshape(-1, X), axis=0)
    mean_prob = jnp.mean(probs.reshape(-1, X), axis=0)
    aux_loss = X * jnp.sum(frac_routed * mean_prob)

    # All experts, all tokens; expert axis sharded -> each device computes
    # its local experts only. Expert matmuls go through the qmatmul
    # dispatch point with their einsum specs — the batched expert axis
    # keeps them on the fused-dequant arm for now (models/qmatmul.py).
    gate = activation(qmatmul(x, p["wg"], spec="bte,xef->btxf"))
    up = qmatmul(x, p["wu"], spec="bte,xef->btxf")
    h = gate * up
    h = with_logical_constraint(h, ("batch", "seq", "expert", None))
    y = qmatmul(h, p["wd"], spec="btxf,xfe->btxe")              # [B, T, X, E]
    out = jnp.einsum("btxe,btx->bte", y, combine.astype(y.dtype))
    return out, aux_loss
