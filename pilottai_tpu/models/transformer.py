"""Shared transformer forward: prefill and single-token decode.

Both paths ``lax.scan`` over stacked layer params (static shapes, O(1)
compile in depth) and express GQA/RoPE/soft-caps per ``ModelConfig``.
Activation shardings are declared with logical axes; under a mesh, XLA
inserts the TP all-reduces over ICI on its own.

No reference counterpart — this replaces the remote API call at
``pilott/engine/llm.py:59`` with on-device compute.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from pilottai_tpu.models.common import (
    ModelConfig,
    apply_rope,
    rms_norm,
    rope_tables,
)
from pilottai_tpu.ops.attention import (
    dot_product_attention,
    flash_enabled,
    flash_shapes_ok,
)
from pilottai_tpu.ops.pallas.flash_attention import flash_sharding_ok
from pilottai_tpu.models.qmatmul import qmatmul
from pilottai_tpu.ops.kvcache import KVCache
from pilottai_tpu.parallel.sharding import with_logical_constraint


def _activation(cfg: ModelConfig, x: jax.Array) -> jax.Array:
    if cfg.act == "gelu_tanh":
        return jax.nn.gelu(x, approximate=True)
    return jax.nn.silu(x)


def _mlp(
    cfg: ModelConfig, lp: Dict[str, Any], x: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """Feed-forward: dense SwiGLU (``lp["mlp"]``), or top-k MoE when the
    layer carries a ``moe`` sub-tree (cfg.n_experts > 0).

    Returns (out, aux_loss) — aux_loss is 0.0 for dense layers and the
    load-balancing term for MoE (collected by forward_train's scan)."""
    if "moe" in lp:
        from pilottai_tpu.models.moe import moe_mlp

        return moe_mlp(cfg, lp["moe"], x, lambda h: _activation(cfg, h))
    p = lp["mlp"]
    gate = _activation(cfg, qmatmul(x, p["wg"]))
    up = qmatmul(x, p["wu"])
    return qmatmul(gate * up, p["wd"]), jnp.zeros((), jnp.float32)


def _qkv(
    cfg: ModelConfig,
    p: Dict[str, Any],
    x: jax.Array,
    sin: jax.Array,
    cos: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    B, T, _ = x.shape
    q = qmatmul(x, p["wq"]).reshape(B, T, cfg.n_heads, cfg.head_dim)
    k = qmatmul(x, p["wk"]).reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
    v = qmatmul(x, p["wv"]).reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
    q = apply_rope(q, sin, cos)
    k = apply_rope(k, sin, cos)
    return q, k, v


def _attn_out(cfg: ModelConfig, p: Dict[str, Any], attn: jax.Array) -> jax.Array:
    B, T = attn.shape[:2]
    return qmatmul(attn.reshape(B, T, cfg.q_dim), p["wo"])


def _embed(cfg: ModelConfig, params: Dict[str, Any], tokens: jax.Array) -> jax.Array:
    x = params["embed"][tokens]
    if cfg.scale_embed:
        x = x * jnp.asarray(cfg.hidden_size**0.5, x.dtype)
    return x


def _unembed(cfg: ModelConfig, params: Dict[str, Any], x: jax.Array) -> jax.Array:
    head = params["lm_head"] if "lm_head" in params else params["embed"].T
    # No spec: the logits projection is the plain last-axis contraction,
    # so a quantized head keeps the native integer-operand lowering.
    logits = qmatmul(x, head, preferred_element_type=jnp.float32)
    if cfg.logit_softcap > 0.0:
        logits = jnp.tanh(logits / cfg.logit_softcap) * cfg.logit_softcap
    return logits


def _full_seq_block(
    cfg: ModelConfig,
    qscale: float,
    x: jax.Array,
    lp: Dict[str, Any],
    window: jax.Array,
    sin: jax.Array,
    cos: jax.Array,
    ipos: jax.Array,
    jpos: jax.Array,
    base_mask: jax.Array,
    positions: Optional[jax.Array] = None,  # [B, T]; enables flash dispatch
    valid: Optional[jax.Array] = None,      # [B]
    ring_mesh: Any = None,                  # Mesh → ring attention over 'seq'
    allow_flash: bool = True,               # False when running off-TPU
    flash_mesh: Any = None,                 # Mesh → shard_map'd flash (TP/DP)
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One transformer block over a full sequence (shared by prefill and
    the training forward). Returns (x, k, v)."""
    h = rms_norm(x, lp["ln1"]["scale"], cfg.rms_eps, cfg.rms_offset)
    q, k, v = _qkv(cfg, lp["attn"], h, sin, cos)
    T = q.shape[1]
    use_flash = (
        positions is not None
        and valid is not None
        and allow_flash
        and flash_enabled()
        and flash_shapes_ok(T, T, head_dim=cfg.head_dim, itemsize=q.dtype.itemsize)
    )
    if ring_mesh is not None and positions is not None and valid is not None:
        # Context parallelism: K/V rotate around the 'seq' ring (ICI);
        # differentiable, so the training path uses it directly.
        from pilottai_tpu.parallel.ring_attention import ring_attention

        attn = ring_attention(
            q, k, v, positions, valid, window,
            scale=qscale, softcap=cfg.attn_softcap, mesh=ring_mesh,
        )
    # Pallas flash kernel (fwd + custom-VJP bwd). Single chip calls it
    # directly; on a mesh it runs per-shard under shard_map (batch over
    # data/fsdp, heads over model) when the shapes divide.
    elif use_flash and len(jax.devices()) == 1:
        from pilottai_tpu.ops.pallas.flash_attention import flash_attention

        attn = flash_attention(
            q, k, v, positions, positions, valid, window,
            scale=qscale, softcap=cfg.attn_softcap,
        )
    elif use_flash and flash_mesh is not None and flash_sharding_ok(
        flash_mesh, q.shape[0], cfg.n_heads, cfg.n_kv_heads
    ):
        from pilottai_tpu.ops.pallas.flash_attention import (
            flash_attention_sharded,
        )

        attn = flash_attention_sharded(
            flash_mesh, q, k, v, positions, positions, valid, window,
            scale=qscale, softcap=cfg.attn_softcap,
        )
    else:
        win_mask = jnp.where(
            window > 0, (ipos - jpos) < jnp.maximum(window, 1), True
        )
        mask = base_mask & win_mask
        attn = dot_product_attention(
            q, k, v, mask=mask, scale=qscale, logit_softcap=cfg.attn_softcap
        )
    out = _attn_out(cfg, lp["attn"], attn)
    if cfg.post_norms:
        out = rms_norm(out, lp["ln1_post"]["scale"], cfg.rms_eps, cfg.rms_offset)
    x = x + out
    h = rms_norm(x, lp["ln2"]["scale"], cfg.rms_eps, cfg.rms_offset)
    out, aux = _mlp(cfg, lp, h)
    if cfg.post_norms:
        out = rms_norm(out, lp["ln2_post"]["scale"], cfg.rms_eps, cfg.rms_offset)
    x = x + out
    x = with_logical_constraint(x, ("batch", "seq", None))
    return x, k, v, aux


# --------------------------------------------------------------------- #
# Prefill
# --------------------------------------------------------------------- #

@partial(jax.jit, static_argnames=("cfg", "use_flash", "flash_mesh"))
def forward_prefill(
    params: Dict[str, Any],
    cfg: ModelConfig,
    tokens: jax.Array,      # [B, T] (right-padded)
    positions: jax.Array,   # [B, T] absolute positions (pad slots arbitrary)
    valid: jax.Array,       # [B] true prompt lengths
    use_flash: bool = True,  # callers running off-TPU (e.g. the cpu
                             # provider on a machine whose DEFAULT backend
                             # is a TPU) must pass False — flash_enabled()
                             # only sees the default backend
    flash_mesh: Any = None,  # static Mesh → shard_map'd flash on multi-chip
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Full-prompt forward. Returns (logits [B, T, V] fp32, k, v) where
    k/v are [L, B, T, K, H] ready to insert into a KVCache."""
    x = _embed(cfg, params, tokens)
    x = with_logical_constraint(x, ("batch", "seq", None))
    sin, cos = rope_tables(positions, cfg.head_dim, cfg.rope_theta)
    windows = jnp.asarray(cfg.window_sizes())
    qscale = cfg.query_scale if cfg.query_scale is not None else cfg.head_dim**-0.5

    # Causal mask within the prompt, from the *absolute* positions argument
    # (not arange), restricted to valid tokens — so prefill at a nonzero
    # offset masks consistently with its RoPE.
    T = tokens.shape[1]
    jpos = positions[:, None, :]          # [B, 1, T] key positions
    ipos = positions[:, :, None]          # [B, T, 1] query positions
    base_mask = (jpos <= ipos) & (
        jnp.arange(T)[None, None, :] < valid[:, None, None]
    )

    def layer_fn(carry, scanned):
        x = carry
        lp, window = scanned
        x, k, v, _ = _full_seq_block(
            cfg, qscale, x, lp, window, sin, cos, ipos, jpos, base_mask,
            positions=positions, valid=valid, allow_flash=use_flash,
            flash_mesh=flash_mesh,
        )
        return x, (k, v)

    x, (ks, vs) = jax.lax.scan(
        layer_fn, x, (params["layers"], windows)
    )
    x = rms_norm(x, params["final_norm"]["scale"], cfg.rms_eps, cfg.rms_offset)
    logits = _unembed(cfg, params, x)
    return logits, ks, vs


# --------------------------------------------------------------------- #
# Decode
# --------------------------------------------------------------------- #

@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def forward_decode(
    params: Dict[str, Any],
    cfg: ModelConfig,
    tokens: jax.Array,     # [B] current token per slot
    cache: KVCache,        # donated; positions written at cache.lengths
    active: jax.Array,     # [B] bool — which slots hold live sequences
) -> Tuple[jax.Array, KVCache]:
    """One decode step for every slot. Returns (logits [B, V] fp32, cache).

    This is the dense single-step *reference* path (pure XLA, per-layer
    K-major panels); production serving runs the fused multi-step
    ``engine/decode.py:decode_chunk`` which is parity-tested against it.

    Inactive slots still flow through the matmuls (static shapes — one
    compilation serves the whole serving lifetime) but their cache writes
    are routed out-of-bounds (dropped by XLA scatter semantics) and their
    lengths stay frozen, so a freed slot is bit-identical until readmission.
    """
    B = tokens.shape[0]
    S = cache.max_len
    # Write index == current length; inactive slots write at S (dropped).
    positions = jnp.where(active, cache.lengths, S)
    x = _embed(cfg, params, tokens[:, None])  # [B, 1, E]
    sin, cos = rope_tables(positions[:, None], cfg.head_dim, cfg.rope_theta)
    windows = cfg.window_sizes()
    qscale = cfg.query_scale if cfg.query_scale is not None else cfg.head_dim**-0.5
    bidx = jnp.arange(B)
    G = cfg.n_heads // cfg.n_kv_heads
    col = jnp.arange(S)[None, None, None, :]              # [1, 1, 1, S]
    pos_b = positions[:, None, None, None]                # [B, 1, 1, 1]

    new_layers = []
    for l in range(cfg.n_layers):
        lp = jax.tree.map(lambda a: a[l], params["layers"])
        window = int(windows[l])
        layer_k, layer_v = cache.layers[l]
        h = rms_norm(x, lp["ln1"]["scale"], cfg.rms_eps, cfg.rms_offset)
        q, k_new, v_new = _qkv(cfg, lp["attn"], h, sin, cos)
        # K-major panels: write [B, K, H] at each slot's position.
        layer_k = layer_k.at[bidx, :, positions].set(k_new[:, 0], mode="drop")
        layer_v = layer_v.at[bidx, :, positions].set(v_new[:, 0], mode="drop")

        qg = q[:, 0].reshape(B, cfg.n_kv_heads, G, cfg.head_dim)
        s = jnp.einsum(
            "bkgh,bksh->bkgs", qg, layer_k, preferred_element_type=jnp.float32
        ) * qscale
        if cfg.attn_softcap > 0.0:
            s = jnp.tanh(s / cfg.attn_softcap) * cfg.attn_softcap
        mask = col <= pos_b
        if window > 0:
            mask &= (pos_b - col) < window
        s = jnp.where(mask, s, -2.0**30)
        w = jax.nn.softmax(s, axis=-1).astype(layer_v.dtype)
        attn = jnp.einsum(
            "bkgs,bksh->bkgh", w, layer_v, preferred_element_type=jnp.float32
        ).astype(x.dtype)

        out = _attn_out(cfg, lp["attn"], attn.reshape(B, 1, cfg.n_heads, cfg.head_dim))
        if cfg.post_norms:
            out = rms_norm(out, lp["ln1_post"]["scale"], cfg.rms_eps, cfg.rms_offset)
        x = x + out
        h = rms_norm(x, lp["ln2"]["scale"], cfg.rms_eps, cfg.rms_offset)
        out, _ = _mlp(cfg, lp, h)
        if cfg.post_norms:
            out = rms_norm(out, lp["ln2_post"]["scale"], cfg.rms_eps, cfg.rms_offset)
        x = x + out
        new_layers.append((layer_k, layer_v))

    x = rms_norm(x, params["final_norm"]["scale"], cfg.rms_eps, cfg.rms_offset)
    logits = _unembed(cfg, params, x)[:, 0]  # [B, V]
    new_lengths = jnp.where(active, cache.lengths + 1, cache.lengths)
    return logits, KVCache(layers=tuple(new_layers), lengths=new_lengths)


# --------------------------------------------------------------------- #
# Training forward
# --------------------------------------------------------------------- #

@partial(jax.jit, static_argnames=("cfg", "remat", "ring_mesh", "flash_mesh"))
def forward_train(
    params: Dict[str, Any],
    cfg: ModelConfig,
    tokens: jax.Array,      # [B, T] (right-padded)
    positions: jax.Array,   # [B, T]
    valid: jax.Array,       # [B] true lengths
    remat: bool = True,
    ring_mesh: Any = None,  # static Mesh → ring attention over the seq axis
    flash_mesh: Any = None,  # static Mesh → shard_map'd flash (no seq shard)
) -> Tuple[jax.Array, jax.Array]:
    """Full-sequence forward for training: (logits, moe_aux_loss), no KV
    outputs. moe_aux_loss is the mean load-balancing term over layers
    (0.0 for dense models).

    With ``remat=True`` each layer body is wrapped in ``jax.checkpoint``
    so the backward pass recomputes activations instead of storing T×L of
    them — the HBM-for-FLOPs trade that makes long-sequence training fit.
    No reference counterpart (the reference has no training at all,
    SURVEY.md §1 "What the reference is NOT").
    """
    x = _embed(cfg, params, tokens)
    x = with_logical_constraint(x, ("batch", "seq", None))
    sin, cos = rope_tables(positions, cfg.head_dim, cfg.rope_theta)
    windows = jnp.asarray(cfg.window_sizes())
    qscale = cfg.query_scale if cfg.query_scale is not None else cfg.head_dim**-0.5

    T = tokens.shape[1]
    jpos = positions[:, None, :]
    ipos = positions[:, :, None]
    base_mask = (jpos <= ipos) & (
        jnp.arange(T)[None, None, :] < valid[:, None, None]
    )

    def block(x, lp, window):
        # positions/valid always flow in; _full_seq_block's dispatch picks
        # ring (seq-sharded mesh) > flash kernel (TPU, shapes fit; direct
        # on one chip, shard_map'd via flash_mesh on many) > XLA dense.
        x, _, _, aux = _full_seq_block(
            cfg, qscale, x, lp, window, sin, cos, ipos, jpos, base_mask,
            positions=positions, valid=valid,
            ring_mesh=ring_mesh,
            flash_mesh=flash_mesh if ring_mesh is None else None,
        )
        return x, aux

    if remat:
        block = jax.checkpoint(
            block, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        )

    def layer_fn(carry, scanned):
        lp, window = scanned
        x, aux = block(carry, lp, window)
        return x, aux

    x, aux_per_layer = jax.lax.scan(layer_fn, x, (params["layers"], windows))
    x = rms_norm(x, params["final_norm"]["scale"], cfg.rms_eps, cfg.rms_offset)
    # Mean MoE load-balance loss over layers (0.0 for dense models).
    return _unembed(cfg, params, x), jnp.mean(aux_per_layer)
