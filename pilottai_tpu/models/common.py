"""Model configuration, parameter init and sharding declarations.

One ``ModelConfig`` covers the Llama and Gemma families; family-specific
behaviors (activation, embed scaling, RMSNorm offset, logit soft-caps,
alternating sliding windows, post-norms) are explicit fields rather than
subclasses, so the single ``transformer.py`` forward stays scan-friendly.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    name: str = "tiny-test"
    family: str = "llama"  # "llama" | "gemma" | "gemma2"
    vocab_size: int = 512
    hidden_size: int = 256
    n_layers: int = 4
    n_heads: int = 4
    n_kv_heads: int = 2
    head_dim: int = 64
    intermediate_size: int = 512
    max_seq_len: int = 2048
    rope_theta: float = 500_000.0
    rms_eps: float = 1e-5
    tie_embeddings: bool = True

    # Family behaviors
    act: str = "silu"              # "silu" (llama) | "gelu_tanh" (gemma)
    scale_embed: bool = False      # gemma: x *= sqrt(hidden)
    rms_offset: bool = False       # gemma: scale = (1 + w)
    post_norms: bool = False       # gemma2: post-attn / post-mlp norms
    logit_softcap: float = 0.0     # gemma2: 30.0
    attn_softcap: float = 0.0      # gemma2: 50.0
    sliding_window: int = 0        # gemma2: 4096 on alternating layers
    sliding_pattern: int = 0       # every Nth layer is global (gemma2: 2)
    query_scale: Optional[float] = None  # default head_dim**-0.5

    # Mixture-of-experts (0 = dense MLP). Experts shard over the 'expert'
    # logical axis (mesh 'model' by default) — expert parallelism.
    n_experts: int = 0
    n_active_experts: int = 2      # top-k routing

    dtype: Any = jnp.bfloat16

    @property
    def q_dim(self) -> int:
        return self.n_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.n_kv_heads * self.head_dim

    def window_sizes(self) -> np.ndarray:
        """Per-layer sliding-window sizes; 0 = global attention."""
        if self.sliding_window <= 0 or self.sliding_pattern <= 0:
            return np.zeros((self.n_layers,), dtype=np.int32)
        out = np.full((self.n_layers,), self.sliding_window, dtype=np.int32)
        out[self.sliding_pattern - 1 :: self.sliding_pattern] = 0
        return out

    def replace(self, **kwargs: Any) -> "ModelConfig":
        return dataclasses.replace(self, **kwargs)

    def param_count(self) -> int:
        E, F, V, L = self.hidden_size, self.intermediate_size, self.vocab_size, self.n_layers
        mlp = 2 * E * F + F * E
        if self.n_experts > 0:
            mlp = self.n_experts * mlp + E * self.n_experts  # experts + router
        per_layer = (
            E * self.q_dim + 2 * E * self.kv_dim + self.q_dim * E  # attn
            + mlp
            + 2 * E + (2 * E if self.post_norms else 0)             # norms
        )
        head = 0 if self.tie_embeddings else E * V
        return V * E + L * per_layer + E + head

    def active_param_count(self) -> int:
        """Parameters touched per token on the forward pass: equals
        ``param_count`` for dense models; for MoE, only the router plus
        the top-k routed experts' MLPs count — the inactive experts'
        weights never stream from HBM for that token."""
        if self.n_experts <= 0:
            return self.param_count()
        E, F = self.hidden_size, self.intermediate_size
        mlp_one = 2 * E * F + F * E
        inactive = max(self.n_experts - self.n_active_experts, 0)
        return self.param_count() - self.n_layers * inactive * mlp_one

    def flops_per_token(self) -> float:
        """Model FLOPs per generated/prefilled token: 2 (multiply +
        accumulate) per active parameter — the standard weight-bound
        approximation (attention-score FLOPs are context-dependent and
        a few percent at serving context lengths; MFU derived from this
        is therefore a slight *under*-estimate, consistently so).

        This is THE formula for every MFU the repo reports: the live
        ``engine.mfu`` gauge (obs/attribution.py) and the bench sections
        both call it, so the numbers reconcile by construction."""
        return 2.0 * self.active_param_count()


def init_params(
    cfg: ModelConfig, key: jax.Array, dtype: Optional[Any] = None,
    quantize: bool = False,
) -> Dict[str, Any]:
    """Random-init a parameter pytree with stacked layers.

    Layer params carry a leading [L] axis so the forward pass can
    ``lax.scan`` over depth — compile time stays O(1) in n_layers, which
    matters on TPU where the first jit is the slow step.

    ``quantize=True`` emits matmul weights directly as int8 ``QTensor``s
    (models/quant.py), quantizing each leaf eagerly as it is generated —
    the bf16 intermediate frees leaf by leaf, so an 8B model peaks at
    ~(int8 tree + one layer-stack leaf) instead of the full bf16 tree
    plus the int8 copy. That is what lets llama3-8b random-init fit a
    single 16 GB v5e. Norms, embeds, and the MoE router stay dense,
    matching ``quantize_params``.
    """
    dtype = dtype or cfg.dtype
    E, F, V, L = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size, cfg.n_layers
    keys = jax.random.split(key, 8)

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, dtype=jnp.float32) * fan_in**-0.5).astype(dtype)

    def normal(k, shape, fan_in):
        if not (quantize and len(shape) >= 3):
            return dense(k, shape, fan_in)
        # Generate + quantize one leading (layer) slice per fused dispatch:
        # eager whole-leaf generation keeps multiple fp32 intermediates of
        # the biggest MLP leaf alive at once (~15 GB for 8B) — per-slice,
        # the transient is a few hundred MB and the int8 result is all
        # that accumulates.
        from pilottai_tpu.models.quant import QTensor, quantize_array

        @functools.partial(jax.jit, static_argnames=("shp", "fi"))
        def gen_chunk(k, shp, fi):
            w = (
                jax.random.normal(k, shp, dtype=jnp.float32) * fi**-0.5
            ).astype(dtype)
            return quantize_array(w, dtype)

        chunks = [
            gen_chunk(kk, shape[1:], fan_in)
            for kk in jax.random.split(k, shape[0])
        ]
        return QTensor(
            q=jnp.stack([c.q for c in chunks]),
            s=jnp.stack([c.s for c in chunks]),
        )

    layers: Dict[str, Any] = {
        "ln1": {"scale": jnp.zeros((L, E), dtype) if cfg.rms_offset else jnp.ones((L, E), dtype)},
        "ln2": {"scale": jnp.zeros((L, E), dtype) if cfg.rms_offset else jnp.ones((L, E), dtype)},
        "attn": {
            "wq": normal(keys[0], (L, E, cfg.q_dim), E),
            "wk": normal(keys[1], (L, E, cfg.kv_dim), E),
            "wv": normal(keys[2], (L, E, cfg.kv_dim), E),
            "wo": normal(keys[3], (L, cfg.q_dim, E), cfg.q_dim),
        },
    }
    if cfg.n_experts > 0:
        X = cfg.n_experts
        layers["moe"] = {
            # Router stays dense even under quantize — its logits pick
            # which experts run (see quantize_params).
            "router": dense(jax.random.fold_in(keys[4], 7), (L, E, X), E),
            "wg": normal(keys[4], (L, X, E, F), E),
            "wu": normal(keys[5], (L, X, E, F), E),
            "wd": normal(keys[6], (L, X, F, E), F),
        }
    else:
        layers["mlp"] = {
            "wg": normal(keys[4], (L, E, F), E),
            "wu": normal(keys[5], (L, E, F), E),
            "wd": normal(keys[6], (L, F, E), F),
        }
    if cfg.post_norms:
        zero_or_one = jnp.zeros if cfg.rms_offset else jnp.ones
        layers["ln1_post"] = {"scale": zero_or_one((L, E), dtype)}
        layers["ln2_post"] = {"scale": zero_or_one((L, E), dtype)}

    params: Dict[str, Any] = {
        "embed": normal(keys[7], (V, E), 1.0),
        "layers": layers,
        "final_norm": {
            "scale": jnp.zeros((E,), dtype) if cfg.rms_offset else jnp.ones((E,), dtype)
        },
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = normal(jax.random.fold_in(keys[7], 1), (E, V), E)
    return params


def param_logical_axes(cfg: ModelConfig) -> Dict[str, Any]:
    """Parallel pytree of logical-axis tuples for ``shard_params``.

    Layer leaves have a leading "layers" axis (never sharded). TP shards
    heads/mlp/vocab over the ``model`` mesh axis; FSDP shards the embed
    axis; see ``parallel/sharding.DEFAULT_RULES``.
    """
    layers: Dict[str, Any] = {
        "ln1": {"scale": ("layers", None)},
        "ln2": {"scale": ("layers", None)},
        "attn": {
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
        },
    }
    if cfg.n_experts > 0:
        layers["moe"] = {
            "router": ("layers", "embed", None),
            "wg": ("layers", "expert", "embed", "mlp_expert"),
            "wu": ("layers", "expert", "embed", "mlp_expert"),
            "wd": ("layers", "expert", "mlp_expert", "embed"),
        }
    else:
        layers["mlp"] = {
            "wg": ("layers", "embed", "mlp"),
            "wu": ("layers", "embed", "mlp"),
            "wd": ("layers", "mlp", "embed"),
        }
    if cfg.post_norms:
        layers["ln1_post"] = {"scale": ("layers", None)}
        layers["ln2_post"] = {"scale": ("layers", None)}
    axes: Dict[str, Any] = {
        "embed": ("vocab", "embed"),
        "layers": layers,
        "final_norm": {"scale": (None,)},
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def rms_norm(x: jax.Array, scale: jax.Array, eps: float, offset: bool) -> jax.Array:
    """RMSNorm in fp32 statistics (Gemma adds 1 to the learned scale)."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    normed = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    s = scale.astype(jnp.float32)
    if offset:
        s = s + 1.0
    return (normed * s).astype(dtype)


def rope_tables(
    positions: jax.Array, head_dim: int, theta: float
) -> Tuple[jax.Array, jax.Array]:
    """sin/cos tables for rotate-half RoPE. positions [B, T] →
    sin/cos [B, T, head_dim/2] in fp32."""
    half = head_dim // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [B, T, half]
    return jnp.sin(angles), jnp.cos(angles)


def apply_rope(x: jax.Array, sin: jax.Array, cos: jax.Array) -> jax.Array:
    """Rotate-half RoPE: x [B, T, N, H], sin/cos [B, T, H/2]."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    sin = sin[:, :, None, :].astype(jnp.float32)
    cos = cos[:, :, None, :].astype(jnp.float32)
    x1f, x2f = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out = jnp.concatenate(
        [x1f * cos - x2f * sin, x2f * cos + x1f * sin], axis=-1
    )
    return out.astype(x.dtype)
