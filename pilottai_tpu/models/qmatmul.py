"""Quantized-operand matmul dispatch — the ONE site model code calls.

``transformer.py`` / ``moe.py`` route every weight matmul through
:func:`qmatmul` instead of spelling ``x @ dequant(w)`` at each site, so
*how* a quantized weight is consumed is a single platform decision
instead of eight copy-pasted ones (ISSUE 14):

* **Native quantized-operand path** (capable platforms — TPU by
  default, overridable via ``PILOTTAI_QMATMUL=native|dequant``): the
  activation quantizes dynamically to int8 with per-row symmetric
  scales and the contraction runs as an integer
  ``lax.dot_general(..., preferred_element_type=int32)`` against the
  stored int8 weights (int4 weights unpack to int8 nibble values
  first — the HBM read is still the packed buffer). Scales fold in
  after the dot: per-output-channel weight scales commute with the
  contraction exactly; int4's per-group scales are applied per group
  via a grouped dot (the contraction splits into scale groups, each
  accumulated in int32 and scaled before the cross-group sum). No
  full-precision copy of the weight ever exists.
* **Fused-dequant fallback** (everywhere else, and for the einsum-
  shaped MoE expert matmuls): ``x @ dequant(w)`` — XLA fuses the
  convert+mul (and int4 nibble shifts) into the matmul's operand read
  on fusing backends. The HLO-inspector test
  (tests/test_quant_parity.py) pins that the native lowering carries
  no dense fp32 weight buffer, PR 12's ``collective_ops`` pattern
  applied to operand dtypes.

The native path changes numerics (activations round to 8 bits); the
byte-identity contracts in tests run against the dequant lowering,
which is bit-exact with the pre-dispatch-point code. Quality under the
native path is covered by the checkpoint smoke in the same test file.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import jax.numpy as jnp

from pilottai_tpu.models.quant import Q4Tensor, QTensor, dequant, unpack_int4


def native_quant_matmul_ok(platform: Optional[str] = None) -> bool:
    """Should quantized weights feed the integer dot natively here?
    ``PILOTTAI_QMATMUL`` forces the answer (``native`` / ``dequant``);
    otherwise only TPU backends opt in — their MXU takes int8 operands
    at rate, while CPU XLA would just emulate the integer dot slower
    than the fused-dequant form."""
    mode = os.environ.get("PILOTTAI_QMATMUL", "").lower()
    if mode == "native":
        return True
    if mode == "dequant":
        return False
    return (platform or jax.default_backend()) == "tpu"


def _dense_matmul(
    x: jax.Array, w: jax.Array, spec: Optional[str],
    preferred_element_type: Optional[Any],
) -> jax.Array:
    if spec is not None:
        if preferred_element_type is not None:
            return jnp.einsum(
                spec, x, w, preferred_element_type=preferred_element_type
            )
        return jnp.einsum(spec, x, w)
    if preferred_element_type is not None:
        return jax.lax.dot_general(
            x, w, (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=preferred_element_type,
        )
    return x @ w


def _quantize_activation(x: jax.Array):
    """Dynamic symmetric per-row int8: returns (xq int8, sx fp32 with a
    keepdim contraction axis)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    sx = jnp.maximum(amax, 1e-8) / 127.0
    xq = jnp.clip(jnp.round(xf / sx), -127, 127).astype(jnp.int8)
    return xq, sx


def _native_int8_matmul(
    x: jax.Array, w: Any, preferred_element_type: Optional[Any]
) -> jax.Array:
    """Integer-operand contraction for a 2D quantized weight: int8
    activation × int8 weight → int32 accumulate, scales folded in after
    (per output channel, or per contraction group for int4)."""
    out_dtype = (
        preferred_element_type if preferred_element_type is not None
        else x.dtype
    )
    xq, sx = _quantize_activation(x)
    if isinstance(w, QTensor):
        acc = jax.lax.dot_general(
            xq, w.q, (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        out = acc.astype(jnp.float32) * sx * w.s[0].astype(jnp.float32)
        return out.astype(out_dtype)
    # Q4Tensor: per-group scales need per-group accumulation — split the
    # contraction into [G, group] and run ONE batched integer dot whose
    # batch axis is the scale group; each group's int32 partial scales
    # before the cross-group sum (algebraically exact: within a group
    # the scale is constant, so it commutes with that group's dot).
    in_dim, group = w.in_dim, w.group
    n_groups = w.s.shape[-2]
    wq = unpack_int4(w.q, in_dim)                     # [in, out] int8
    pad_rows = n_groups * group - in_dim
    if pad_rows:
        wq = jnp.pad(wq, ((0, pad_rows), (0, 0)))
        xq = jnp.pad(xq, [(0, 0)] * (xq.ndim - 1) + [(0, pad_rows)])
    wq_g = wq.reshape(n_groups, group, wq.shape[-1])  # [G, group, out]
    xq_g = xq.reshape(xq.shape[:-1] + (n_groups, group))
    acc = jnp.einsum(
        "...gi,gio->...go", xq_g, wq_g, preferred_element_type=jnp.int32
    )
    out = jnp.sum(
        acc.astype(jnp.float32) * w.s.astype(jnp.float32), axis=-2
    ) * sx
    return out.astype(out_dtype)


def qmatmul(
    x: jax.Array,
    w: Any,
    spec: Optional[str] = None,
    preferred_element_type: Optional[Any] = None,
) -> jax.Array:
    """The quantized-operand matmul dispatch point.

    ``w`` may be a plain array, a ``QTensor`` (int8) or a ``Q4Tensor``
    (packed int4). Without ``spec`` the contraction is ``x``'s last
    axis against ``w``'s first (the 2D layer-matmul shape after stacked
    slicing); einsum-shaped weights (MoE experts, the logits
    projection) pass their ``spec`` and always take the fused-dequant
    form — their batched-operand layouts have no native integer
    lowering yet (the grouped-GEMM Pallas kernel is the planned
    upgrade path, models/moe.py).

    ``preferred_element_type`` matches the einsum/dot kwarg: the
    logits projection asks for fp32 accumulation and gets it on every
    arm.
    """
    if isinstance(w, (QTensor, Q4Tensor)):
        if spec is None and w.q.ndim == 2 and native_quant_matmul_ok():
            return _native_int8_matmul(x, w, preferred_element_type)
        return _dense_matmul(x, dequant(w), spec, preferred_element_type)
    return _dense_matmul(x, w, spec, preferred_element_type)


__all__ = ["qmatmul", "native_quant_matmul_ok"]
