"""PilottAI-TPU: a TPU-native hierarchical multi-agent LLM framework.

Re-designed from scratch with the capability surface of PilottAI
(reference: /root/reference, see SURVEY.md) but with the whole inference
path in-tree: a JAX/XLA/Pallas LLM engine (``provider="tpu"``), a
jit-batched on-device embedding encoder backing semantic memory, and a
mesh-aware orchestration control plane.

Top-level API (reference parity: ``pilott/__init__.py`` exports ``Serve``;
here we export the full core surface as ``pilott/core/__init__.py:1-21``
implies):

    from pilottai_tpu import Serve, Task, AgentConfig, LLMConfig

Heavy submodules (engine/models, which import jax) are loaded lazily so
``import pilottai_tpu`` stays cheap for control-plane-only users.
"""

from pilottai_tpu.core.task import (
    Task,
    TaskPriority,
    TaskResult,
    TaskStatus,
)
from pilottai_tpu.core.status import AgentRole, AgentStatus
from pilottai_tpu.core.config import (
    AgentConfig,
    FaultToleranceConfig,
    LLMConfig,
    LoadBalancerConfig,
    LogConfig,
    ReliabilityConfig,
    RouterConfig,
    ScalingConfig,
    ServeConfig,
)

__version__ = "0.17.0"  # kept in lockstep with pyproject.toml

# Lazy top-level exports; entries are added as the corresponding modules
# land so the advertised API never points at missing modules.
_LAZY = {
    "Memory": ("pilottai_tpu.core.memory", "Memory"),
    "Serve": ("pilottai_tpu.serve", "Serve"),
    "BaseAgent": ("pilottai_tpu.core.agent", "BaseAgent"),
    "AgentFactory": ("pilottai_tpu.core.factory", "AgentFactory"),
    "TaskRouter": ("pilottai_tpu.core.router", "TaskRouter"),
    "Tool": ("pilottai_tpu.tools.tool", "Tool"),
    "ToolRegistry": ("pilottai_tpu.tools.tool", "ToolRegistry"),
    "LLMHandler": ("pilottai_tpu.engine.handler", "LLMHandler"),
    "APIServer": ("pilottai_tpu.server", "APIServer"),
    "EnhancedMemory": ("pilottai_tpu.memory.semantic", "EnhancedMemory"),
    "Embedder": ("pilottai_tpu.memory.embedder", "Embedder"),
    "KnowledgeManager": ("pilottai_tpu.knowledge.manager", "KnowledgeManager"),
    "TaskDelegator": ("pilottai_tpu.delegation.delegator", "TaskDelegator"),
    "TaskJournal": ("pilottai_tpu.checkpoint.journal", "TaskJournal"),
    "TrainCheckpointer": ("pilottai_tpu.checkpoint.train_io", "TrainCheckpointer"),
    "CircuitBreaker": ("pilottai_tpu.reliability", "CircuitBreaker"),
    "CircuitOpenError": ("pilottai_tpu.reliability", "CircuitOpenError"),
    "DeadlineExceeded": ("pilottai_tpu.reliability", "DeadlineExceeded"),
    "EngineOverloaded": ("pilottai_tpu.reliability", "EngineOverloaded"),
    "FaultInjector": ("pilottai_tpu.reliability", "FaultInjector"),
    "global_injector": ("pilottai_tpu.reliability", "global_injector"),
    # Observability surface (pilottai_tpu/obs — docs/OBSERVABILITY.md).
    "FlightRecorder": ("pilottai_tpu.obs", "FlightRecorder"),
    "global_flight": ("pilottai_tpu.obs", "global_flight"),
    "global_steps": ("pilottai_tpu.obs", "global_steps"),
    "global_blackbox": ("pilottai_tpu.obs", "global_blackbox"),
    "metrics_snapshot": ("pilottai_tpu.obs", "metrics_snapshot"),
    "prometheus_text": ("pilottai_tpu.obs", "prometheus_text"),
    "perfetto_trace": ("pilottai_tpu.obs", "perfetto_trace"),
    "MetricsDashboard": ("pilottai_tpu.utils.dashboard", "MetricsDashboard"),
}


def __getattr__(name):
    try:
        module_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}") from None
    import importlib

    module = importlib.import_module(module_name)
    value = getattr(module, attr)
    globals()[name] = value
    return value


__all__ = [
    "Task",
    "TaskPriority",
    "TaskResult",
    "TaskStatus",
    "AgentRole",
    "AgentStatus",
    "AgentConfig",
    "LLMConfig",
    "LogConfig",
    "ReliabilityConfig",
    "ServeConfig",
    "RouterConfig",
    "LoadBalancerConfig",
    "ScalingConfig",
    "FaultToleranceConfig",
    *_LAZY.keys(),
]
