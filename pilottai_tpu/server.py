"""HTTP serving endpoint: OpenAI-compatible chat completions over the
in-tree engine, plus orchestrator task submission.

The reference FRAMEWORK is an API *client* (litellm → remote providers,
``pilott/engine/llm.py:59``) and its only networked surface is a
declared-but-unimplemented websocket config (``pilott/core/config.py:
153-156``, SURVEY §2.12-i). This framework owns the inference path, so
it can BE the provider: any OpenAI-SDK client (or plain HTTP) points at
this endpoint and gets the native engine — continuous batching,
speculation, prefix caching, grammar-masked JSON and SSE streaming
included.

Routes
------
* ``POST /v1/chat/completions`` — OpenAI wire format. ``stream: true``
  returns Server-Sent Events chunks (``chat.completion.chunk`` deltas,
  terminated by ``data: [DONE]``) fed by ``LLMHandler.astream``;
  ``response_format: {"type": "json_object"}`` maps to the engine's
  grammar-constrained ``json_mode``; ``tools`` (function specs) map to
  ``ToolSpec`` and structured ``tool_calls`` come back in the message.
* ``GET /v1/models`` — the registry's model list.
* ``POST /v1/tasks`` — framework-specific: submit a task description to
  an attached ``Serve`` orchestrator and wait for its ``TaskResult``
  (503 when the server wraps a bare handler).
* ``GET /healthz`` — liveness; ``GET /metrics`` — the unified metrics
  snapshot (JSON; same shape as the dashboard's ``/metrics.json``), or
  Prometheus text exposition with ``?format=prometheus``.
* ``GET /slo.json`` — per-class SLO attainment, burn rate and latency
  percentiles (obs/slo.py).

Every request accepts (and every completion/task response echoes) an
``x-request-id`` header: the flight-recorder trace id correlating spans,
structured logs, phase metrics and black-box dumps across the server →
handler → batcher boundary (docs/OBSERVABILITY.md). A ``slo_class``
body field (or ``x-slo-class`` header) assigns the request to an SLO
service class ("interactive"/"batch"); unknown classes are a 400. A
``session_id`` body field (or ``x-session-id`` header) names the
client's conversation for the engine's KV cache tier
(engine/kvcache/): turns sending the same id pin their prefix lineage
so a resume restores spilled KV from host RAM instead of re-prefilling
the transcript; malformed ids are a 400.

Implementation is stdlib-asyncio only (``asyncio.start_server`` + a
minimal HTTP/1.1 parser): SSE needs the event loop the engine's futures
resolve on, which rules out the threaded ``http.server`` the metrics
dashboard uses. One request per connection (``Connection: close``) —
agent/SDK traffic reconnects per call and it keeps the parser honest.

Auth mirrors the control plane's posture (``distributed/control_plane``):
optional shared bearer token for private-network deployments; terminate
TLS in front for anything else (documented in docs/SERVING.md).
"""

from __future__ import annotations

import asyncio
import hmac
import json
import re
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs

from pilottai_tpu.engine.types import GenerationParams, ToolSpec
from pilottai_tpu.obs import metrics_snapshot, prometheus_text
from pilottai_tpu.reliability import (
    CircuitOpenError,
    DeadlineExceeded,
    EngineOverloaded,
)
from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import global_metrics
from pilottai_tpu.utils.tracing import global_tracer

# Client-supplied x-request-id values become trace ids threaded through
# logs, span trees and black-box dumps — constrain the alphabet so a
# hostile header can't inject into JSONL journals or log greps.
_REQUEST_ID_RE = re.compile(r"[A-Za-z0-9._\-]{1,64}")

_MAX_HEADER = 32 * 1024
_MAX_BODY = 10 * 1024 * 1024

_STATUS_TEXT = {
    200: "OK", 400: "Bad Request", 401: "Unauthorized", 404: "Not Found",
    405: "Method Not Allowed", 408: "Request Timeout",
    413: "Payload Too Large", 429: "Too Many Requests",
    500: "Internal Server Error", 503: "Service Unavailable",
}


class _HttpError(Exception):
    def __init__(
        self,
        status: int,
        message: str,
        kind: str = "invalid_request_error",
        extra: Optional[Dict[str, Any]] = None,
    ):
        super().__init__(message)
        self.status = status
        self.message = message
        self.kind = kind
        self.extra = extra or {}


def _overload_error(exc: Exception) -> _HttpError:
    """Reliability exceptions → structured HTTP errors (documented in
    docs/SERVING.md "Overload & failure semantics"): deadline exceeded →
    408 timeout_error; breaker open → 503 overloaded_error (with a
    retry_after hint); queue shed → 429 overloaded_error."""
    if isinstance(exc, DeadlineExceeded):
        return _HttpError(
            408, str(exc) or "request deadline exceeded", "timeout_error"
        )
    if isinstance(exc, CircuitOpenError):
        return _HttpError(
            503, str(exc), "overloaded_error",
            extra={"retry_after": round(exc.retry_after, 3)},
        )
    return _HttpError(
        429, str(exc) or "engine overloaded; request shed", "overloaded_error"
    )


class APIServer:
    """Serve an ``LLMHandler`` (and optionally a ``Serve``) over HTTP."""

    def __init__(
        self,
        handler: Any,                    # LLMHandler, or {model_name: LLMHandler}
        serve: Optional[Any] = None,     # Serve orchestrator for /v1/tasks
        embedder: Optional[Any] = None,  # memory.Embedder for /v1/embeddings
        host: str = "127.0.0.1",
        port: int = 0,
        auth_token: Optional[str] = None,
    ) -> None:
        # Multi-model serving: a dict maps the request's ``model`` field
        # to a handler (unknown names 404, OpenAI ``model_not_found``).
        # A single handler serves every request regardless of ``model``
        # — the common one-model deployment.
        if isinstance(handler, dict):
            if not handler:
                raise ValueError("handler dict must not be empty")
            self.handlers: Dict[str, Any] = dict(handler)
            self.handler = next(iter(handler.values()))  # default
        else:
            self.handlers = {}
            self.handler = handler
        self.serve = serve
        self.embedder = embedder
        self.host = host
        self.port = port
        self.auth_token = auth_token
        self._server: Optional[asyncio.AbstractServer] = None
        self._log = get_logger("server")

    def _pick_handler(self, model: Optional[str]) -> Any:
        if not self.handlers or model is None:
            return self.handler
        try:
            return self.handlers[model]
        except KeyError:
            raise _HttpError(
                404, f"model {model!r} not found; available: "
                f"{sorted(self.handlers)}", "model_not_found",
            ) from None

    # ------------------------------------------------------------------ #

    async def start(self) -> "APIServer":
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        self._log.info("API server on http://%s:%d", self.host, self.port)
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    # ------------------------------------------------------------------ #
    # HTTP plumbing
    # ------------------------------------------------------------------ #

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            try:
                method, path, query, headers, body = await self._read_request(
                    reader
                )
            except _HttpError as exc:
                await self._send_error(writer, exc)
                return
            try:
                self._check_auth(path, headers)
                await self._route(method, path, query, headers, body, writer)
            except _HttpError as exc:
                await self._send_error(writer, exc)
            except (DeadlineExceeded, EngineOverloaded, CircuitOpenError) as exc:
                # Overload/deadline shedding is routine under load — a
                # structured client error, not a 500 with a stack trace.
                global_metrics.inc("server.shed_responses")
                await self._send_error(writer, _overload_error(exc))
            except (ConnectionError, asyncio.IncompleteReadError):
                # Routine client drop (usually mid-SSE): no error log, and
                # never write a 500 body into an already-started response.
                raise
            except Exception as exc:  # noqa: BLE001 — request boundary
                self._log.error("request failed: %s", exc, exc_info=True)
                await self._send_error(
                    writer, _HttpError(500, "internal error", "server_error")
                )
        except (ConnectionError, asyncio.IncompleteReadError):
            pass  # client went away
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass

    async def _read_request(
        self, reader: asyncio.StreamReader
    ) -> Tuple[str, str, str, Dict[str, str], bytes]:
        try:
            head = await asyncio.wait_for(
                reader.readuntil(b"\r\n\r\n"), timeout=30.0
            )
        except (asyncio.LimitOverrunError, ValueError) as exc:
            raise _HttpError(413, "headers too large") from exc
        except asyncio.TimeoutError as exc:
            raise _HttpError(400, "timed out reading request") from exc
        if len(head) > _MAX_HEADER:
            raise _HttpError(413, "headers too large")
        lines = head.decode("latin-1").split("\r\n")
        try:
            method, path, _version = lines[0].split(" ", 2)
        except ValueError as exc:
            raise _HttpError(400, "malformed request line") from exc
        headers: Dict[str, str] = {}
        for line in lines[1:]:
            if not line:
                continue
            k, _, v = line.partition(":")
            headers[k.strip().lower()] = v.strip()
        try:
            length = int(headers.get("content-length", "0") or "0")
        except ValueError as exc:
            raise _HttpError(400, "invalid Content-Length") from exc
        if length > _MAX_BODY:
            raise _HttpError(413, "body too large")
        if length:
            # Same bound as the header read: a client that sends headers
            # then withholds the body must not pin this connection task
            # (slowloris).
            try:
                body = await asyncio.wait_for(
                    reader.readexactly(length), timeout=30.0
                )
            except asyncio.TimeoutError as exc:
                raise _HttpError(400, "timed out reading body") from exc
        else:
            body = b""
        path, _, query = path.partition("?")
        return method, path, query, headers, body

    def _check_auth(self, path: str, headers: Dict[str, str]) -> None:
        if self.auth_token is None or path == "/healthz":
            return
        got = headers.get("authorization", "")
        if not hmac.compare_digest(got, f"Bearer {self.auth_token}"):
            raise _HttpError(401, "missing or invalid bearer token",
                             "authentication_error")

    async def _send(
        self,
        writer: asyncio.StreamWriter,
        status: int,
        payload: Dict[str, Any],
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> None:
        await self._send_raw(
            writer, status, json.dumps(payload).encode(),
            "application/json", extra_headers,
        )

    async def _send_raw(
        self,
        writer: asyncio.StreamWriter,
        status: int,
        data: bytes,
        ctype: str,
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> None:
        head = (
            f"HTTP/1.1 {status} {_STATUS_TEXT.get(status, '')}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(data)}\r\n"
        )
        for key, value in (extra_headers or {}).items():
            head += f"{key}: {value}\r\n"
        writer.write(head.encode() + b"Connection: close\r\n\r\n" + data)
        await writer.drain()

    async def _send_error(self, writer: asyncio.StreamWriter, exc: _HttpError) -> None:
        await self._send(
            writer, exc.status,
            {"error": {"message": exc.message, "type": exc.kind, **exc.extra}},
        )

    # Shared SSE scaffolding — one definition for every streaming route
    # (chat completions AND task streams), so status line, event shape
    # and terminator can't drift apart.

    @staticmethod
    async def _sse_start(
        writer: asyncio.StreamWriter,
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> None:
        head = (
            "HTTP/1.1 200 OK\r\n"
            "Content-Type: text/event-stream\r\n"
            "Cache-Control: no-cache\r\n"
        )
        for key, value in (extra_headers or {}).items():
            head += f"{key}: {value}\r\n"
        writer.write(head.encode() + b"Connection: close\r\n\r\n")
        await writer.drain()

    @staticmethod
    def _sse_event(writer: asyncio.StreamWriter, payload: Dict[str, Any]) -> None:
        writer.write(("data: " + json.dumps(payload) + "\n\n").encode())

    def _sse_error(self, writer: asyncio.StreamWriter, exc: Exception) -> None:
        """In-band error event: the 200 + SSE status line is already on
        the wire, so errors can't change it anymore. Reliability errors
        keep their structured type (timeout_error / overloaded_error) so
        SSE clients can tell a shed from a crash."""
        if isinstance(exc, (DeadlineExceeded, EngineOverloaded, CircuitOpenError)):
            err = _overload_error(exc)
            self._log.warning("stream shed: %s", exc)
            self._sse_event(
                writer,
                {"error": {"message": err.message, "type": err.kind, **err.extra}},
            )
            return
        self._log.error("stream failed: %s", exc, exc_info=True)
        self._sse_event(
            writer, {"error": {"message": str(exc), "type": "server_error"}}
        )

    @staticmethod
    async def _sse_done(writer: asyncio.StreamWriter) -> None:
        writer.write(b"data: [DONE]\n\n")
        await writer.drain()

    # ------------------------------------------------------------------ #
    # Routing
    # ------------------------------------------------------------------ #

    async def _route(
        self,
        method: str,
        path: str,
        query: str,
        headers: Dict[str, str],
        body: bytes,
        writer: asyncio.StreamWriter,
    ) -> None:
        if path == "/healthz" and method == "GET":
            # Liveness AND engine liveness: a watchdog-declared stall (a
            # hung dispatch — reliability/watchdog.py) flips this to 503
            # with a retry_after hint, so load balancers stop routing to
            # a process whose device can't serve, long before clients'
            # own timeouts would reveal it.
            from pilottai_tpu.reliability import global_engine_health

            cell_health = getattr(self.handler, "health_snapshot", None)
            if callable(cell_health):
                # Serving cell (distributed/cell.py): health aggregates
                # across replicas — the cell is up while ANY replica is
                # routable; one stalled replica degrades, not grounds.
                snap = cell_health()
                status = 200 if snap.get("ok") else 503
                await self._send(writer, status, {
                    "status": "ok" if snap.get("ok") else "unhealthy",
                    **{k: v for k, v in snap.items() if k != "ok"},
                })
            elif global_engine_health.healthy():
                await self._send(writer, 200, {"status": "ok"})
            else:
                snap = global_engine_health.snapshot()
                await self._send(writer, 503, {
                    "status": "stalled",
                    "reason": snap.get("reason"),
                    "stalled_for_s": snap.get("stalled_for_s"),
                    "retry_after": snap.get("retry_after"),
                })
        elif path == "/metrics" and method == "GET":
            handler_metrics = (
                {n: _jsonable(h.get_metrics()) for n, h in self.handlers.items()}
                if self.handlers else _jsonable(self.handler.get_metrics())
            )
            # ONE snapshot shape shared with the dashboard
            # (obs.metrics_snapshot); ?format=prometheus serves the text
            # exposition a scraper consumes directly.
            snap = metrics_snapshot(component=handler_metrics)
            if parse_qs(query).get("format") == ["prometheus"]:
                await self._send_raw(
                    writer, 200, prometheus_text(snap).encode(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            else:
                # Back-compat aliases: pre-unification clients read the
                # handler block under "handler" and the registry
                # snapshot under "global".
                snap_j = _jsonable(snap)
                await self._send(
                    writer, 200,
                    {
                        **snap_j,
                        "handler": handler_metrics,
                        "global": {
                            k: snap_j[k]
                            for k in ("uptime_s", "counters", "gauges",
                                      "histograms")
                        },
                    },
                )
        elif path == "/slo.json" and method == "GET":
            # Per-class SLO attainment / burn rate (obs/slo.py) — the
            # page an operator (or the autoscaler's dashboard) watches
            # during an incident. A serving cell aggregates per-replica
            # trackers (request-weighted attainment/burn, worst-replica
            # p99) and attaches each replica's own snapshot.
            cell_slo = getattr(self.handler, "slo_snapshot", None)
            if callable(cell_slo):
                await self._send(writer, 200, _jsonable(cell_slo()))
            else:
                from pilottai_tpu.obs import global_slo

                await self._send(writer, 200, global_slo.snapshot())
        elif path == "/topology.json" and method == "GET":
            # Disaggregated-serving topology (ISSUE 19): per-replica
            # tier roles plus the handoff counters — the page the drain
            # runbook reads before draining a prefill-tier replica
            # (docs/SERVING.md). A single engine reports itself as one
            # "mixed" replica so the shape is stable across deployments.
            from pilottai_tpu.utils.metrics import global_metrics as _gm

            cell_health = getattr(self.handler, "health_snapshot", None)
            tiers = (
                cell_health().get("tiers", {}) if callable(cell_health)
                else {"engine": "mixed"}
            )
            await self._send(writer, 200, {
                "tiers": tiers,
                "disaggregated": any(t != "mixed" for t in tiers.values()),
                "handoffs": _gm.get("cell.handoffs"),
                "handoff_fallbacks": _gm.get("cell.handoff_fallbacks"),
                "handoff_rejected": _gm.get("cell.handoff_rejected"),
                "handoff_tokens": _gm.get("cell.handoff_tokens"),
                "prefix_bypass": _gm.get("cell.tier.bypass"),
            })
        elif path == "/profile.json" and method == "GET":
            # Workload fingerprint (obs/profile.py): the rolling
            # length/arrival/class-mix shape of this deployment's
            # traffic, plus the seasonal forecast state — the input
            # `scripts/recommend.py` replays through the cost model.
            from pilottai_tpu.obs import global_profile

            await self._send(writer, 200, _jsonable(global_profile.fingerprint()))
        elif path == "/dag.json" and method == "GET":
            # Task-DAG attribution (obs/dag.py): active task summaries +
            # recent finished breakdowns with critical paths; ?task_id=
            # returns one task's full node-level ledger.
            from pilottai_tpu.obs import global_dag

            task_id = (parse_qs(query).get("task_id") or [None])[0]
            if task_id:
                described = global_dag.describe(task_id)
                if described is None:
                    raise _HttpError(404, f"no dag for task {task_id!r}")
                await self._send(writer, 200, _jsonable(described))
            else:
                await self._send(writer, 200, _jsonable(global_dag.snapshot()))
        elif path == "/v1/models" and method == "GET":
            await self._send(writer, 200, self._models())
        elif path == "/v1/chat/completions":
            if method != "POST":
                raise _HttpError(405, "POST required")
            await self._chat_completions(_parse_json(body), writer, headers)
        elif path == "/v1/embeddings":
            if method != "POST":
                raise _HttpError(405, "POST required")
            await self._embeddings(_parse_json(body), writer)
        elif path == "/v1/tasks":
            if method != "POST":
                raise _HttpError(405, "POST required")
            await self._submit_task(_parse_json(body), writer, headers)
        else:
            raise _HttpError(404, f"no route for {method} {path}")

    def _models(self) -> Dict[str, Any]:
        if self.handlers:
            # Multi-model mode: the servable set IS the route map.
            names = sorted(self.handlers)
        else:
            try:
                from pilottai_tpu.models.registry import list_models

                names = list_models()
            except Exception:  # noqa: BLE001 — registry is engine-optional
                names = []
            configured = getattr(
                getattr(self.handler, "config", None), "model_name", None
            )
            if configured and configured not in names:
                names = [configured] + names
        return {
            "object": "list",
            "data": [{"id": n, "object": "model", "owned_by": "pilottai-tpu"}
                     for n in names],
        }

    # ------------------------------------------------------------------ #
    # /v1/chat/completions
    # ------------------------------------------------------------------ #

    def _gen_params(self, req: Dict[str, Any]) -> Tuple[
        List[Dict[str, Any]], Optional[List[ToolSpec]], GenerationParams, bool
    ]:
        messages = req.get("messages")
        if not isinstance(messages, list) or not messages:
            raise _HttpError(400, "'messages' must be a non-empty list")
        normed = []
        for m in messages:
            if not isinstance(m, dict) or "content" not in m:
                raise _HttpError(400, "each message needs 'role' and 'content'")
            # OpenAI's own wire shape uses content: null on assistant
            # tool-call turns — normalize rather than 500 downstream.
            normed.append({
                "role": str(m.get("role") or "user"),
                "content": "" if m["content"] is None else str(m["content"]),
            })
        messages = normed
        tools = None
        if req.get("tools"):
            tools = []
            for t in req["tools"]:
                fn = t.get("function", t) if isinstance(t, dict) else {}
                if not isinstance(fn, dict) or not fn.get("name"):
                    raise _HttpError(400, "each tool needs function.name")
                params_schema = fn.get("parameters") or {}
                if not isinstance(params_schema, dict):
                    raise _HttpError(400, "tool parameters must be an object")
                tools.append(ToolSpec(
                    name=str(fn["name"]),
                    description=str(fn.get("description", "")),
                    parameters=params_schema,
                ))
        stop = req.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        if not isinstance(stop, list):
            raise _HttpError(400, "'stop' must be a string or list")
        rf = req.get("response_format") or {}
        if not isinstance(rf, dict):
            raise _HttpError(400, "'response_format' must be an object")
        json_schema = None
        strict = False
        if rf.get("type") == "json_schema":
            # OpenAI nests {name, schema, strict} under json_schema.
            spec = rf.get("json_schema")
            if not isinstance(spec, dict) or not isinstance(
                spec.get("schema"), dict
            ):
                raise _HttpError(
                    400, "response_format json_schema needs "
                    "{'json_schema': {'schema': {...}}}"
                )
            json_schema = spec["schema"]
            strict = bool(spec.get("strict"))
        # Absent vs present-but-invalid: a client's explicit
        # "max_tokens": 0 is a 400, not silently the 256 default
        # (`or` would swallow any falsy value).
        max_tokens = req.get("max_tokens")
        if max_tokens is None:
            max_tokens = req.get("max_completion_tokens")
        if max_tokens is None:
            max_tokens = 256
        if isinstance(max_tokens, bool) or not isinstance(max_tokens, int):
            # No coercion: 2.7 truncating to 2 (or true to 1) would run a
            # different budget than the client sent.
            raise _HttpError(400, "'max_tokens' must be an integer")
        if max_tokens < 1:
            raise _HttpError(400, "'max_tokens' must be >= 1")
        try:
            # Client values are untrusted: a non-numeric temperature or
            # seed is a 400 invalid_request_error (OpenAI parity), not a
            # 500 from int()/pydantic deep in the handler.
            params = GenerationParams(
                max_new_tokens=max_tokens,
                temperature=float(req.get("temperature", 0.7)),
                top_k=int(req.get("top_k", 0)),
                top_p=float(req.get("top_p", 1.0)),
                seed=int(req["seed"]) if req.get("seed") is not None else None,
                stop=[str(s) for s in stop],
                json_mode=rf.get("type") in ("json_object", "json_schema"),
                json_schema=json_schema,
            )
        except (TypeError, ValueError) as exc:
            # (pydantic's ValidationError subclasses ValueError)
            raise _HttpError(400, f"invalid sampling parameter: {exc}") from exc
        return messages, tools, params, strict

    def _request_deadline(
        self, req: Dict[str, Any], headers: Dict[str, str], handler: Any
    ) -> Optional[float]:
        """Derive the request's absolute monotonic deadline: body
        ``timeout`` beats the ``x-request-timeout`` header beats the
        deployment's ``ReliabilityConfig.default_timeout``; whatever wins
        is capped at ``max_timeout``. None = no deadline."""
        raw = req.get("timeout")
        if raw is None:
            raw = headers.get("x-request-timeout")
        rel = getattr(
            getattr(handler, "config", None), "reliability", None
        )
        if raw is None and rel is not None:
            raw = rel.default_timeout
        if raw is None:
            return None
        if isinstance(raw, bool) or not isinstance(raw, (int, float, str)):
            raise _HttpError(400, "'timeout' must be a number of seconds")
        try:
            t = float(raw)
        except ValueError as exc:
            raise _HttpError(
                400, "'timeout' must be a number of seconds"
            ) from exc
        if t <= 0:
            raise _HttpError(400, "'timeout' must be > 0")
        if rel is not None:
            t = min(t, rel.max_timeout)
        return time.monotonic() + t

    @staticmethod
    def _slo_class(
        req: Dict[str, Any], headers: Optional[Dict[str, str]]
    ) -> Optional[str]:
        """The request's SLO service class: body ``slo_class`` beats the
        ``x-slo-class`` header. Unknown classes are a 400 — a typo'd
        class would otherwise silently fall into the default class and
        exempt that traffic from the objective the client asked for."""
        raw = req.get("slo_class")
        if raw is None:
            raw = (headers or {}).get("x-slo-class")
        if raw is None:
            return None
        from pilottai_tpu.obs import global_slo

        if not isinstance(raw, str) or raw not in global_slo.classes:
            raise _HttpError(
                400, f"unknown slo_class {raw!r}; available: "
                f"{sorted(global_slo.classes)}"
            )
        return raw

    @staticmethod
    def _priority(
        req: Dict[str, Any], headers: Optional[Dict[str, str]]
    ) -> Optional[int]:
        """The request's scheduling priority (pilottai_tpu/sched/):
        body ``priority`` beats the ``x-priority`` header; accepts the
        rung number (0-3) or its name (low/normal/high/critical).
        Out-of-lattice values are a 400 — a typo'd priority silently
        falling to NORMAL would exempt the request from the ordering
        the client asked for."""
        raw = req.get("priority")
        if raw is None:
            raw = (headers or {}).get("x-priority")
        if raw is None:
            return None
        names = {"low": 0, "normal": 1, "high": 2, "critical": 3}
        if isinstance(raw, str) and raw.strip().lower() in names:
            return names[raw.strip().lower()]
        try:
            if isinstance(raw, bool) or (
                isinstance(raw, float) and not raw.is_integer()
            ):
                # int(2.7) would silently truncate to HIGH — the same
                # reject-don't-coerce contract as everything else here.
                value = None
            else:
                value = int(raw)
        except (TypeError, ValueError):
            value = None
        if value is None or not 0 <= value <= 3:
            raise _HttpError(
                400, "'priority' must be 0-3 or one of "
                "low/normal/high/critical"
            )
        return value

    @staticmethod
    def _session_id(
        req: Dict[str, Any], headers: Optional[Dict[str, str]]
    ) -> Optional[str]:
        """The request's KV-cache session handle: body ``session_id``
        beats the ``x-session-id`` header. Sanitized with the same
        charset as request ids — a malformed id is a 400, not a silent
        anonymous request (the client asked for lineage pinning and
        would otherwise re-prefill every turn without any signal
        why)."""
        raw = req.get("session_id")
        if raw is None:
            raw = (headers or {}).get("x-session-id")
        if raw is None:
            return None
        if not isinstance(raw, str) or not _REQUEST_ID_RE.fullmatch(raw):
            raise _HttpError(
                400, "'session_id' must be 1-64 characters of "
                "[A-Za-z0-9._-]"
            )
        return raw

    @staticmethod
    def _trace_id(headers: Optional[Dict[str, str]]) -> str:
        """The request's flight-recorder id: accept the client's
        ``x-request-id`` (sanitized) or mint one. Echoed back as a
        response header and threaded through handler → batcher spans,
        logs and black-box dumps (docs/OBSERVABILITY.md)."""
        raw = (headers or {}).get("x-request-id", "")
        if raw and _REQUEST_ID_RE.fullmatch(raw):
            return raw
        return uuid.uuid4().hex[:16]

    async def _chat_completions(
        self,
        req: Dict[str, Any],
        writer: asyncio.StreamWriter,
        headers: Optional[Dict[str, str]] = None,
    ) -> None:
        trace_id = self._trace_id(headers)
        # Root span of the request's trace: the handler's engine.generate
        # span nests under it (same asyncio task), the batcher's emitted
        # span under that — one tree, server → handler → batcher.
        with global_tracer.span(
            "server.request", trace_id=trace_id,
            route="/v1/chat/completions",
        ):
            await self._chat_completions_traced(req, writer, headers, trace_id)

    async def _chat_completions_traced(
        self,
        req: Dict[str, Any],
        writer: asyncio.StreamWriter,
        headers: Optional[Dict[str, str]],
        trace_id: str,
    ) -> None:
        messages, tools, params, strict = self._gen_params(req)
        handler = self._pick_handler(req.get("model"))
        deadline = self._request_deadline(req, headers or {}, handler)
        params = params.model_copy(update={"trace_id": trace_id})
        if deadline is not None:
            params = params.model_copy(update={"deadline": deadline})
        slo_class = self._slo_class(req, headers)
        if slo_class is not None:
            params = params.model_copy(update={"slo_class": slo_class})
        session_id = self._session_id(req, headers or {})
        if session_id is not None:
            params = params.model_copy(update={"session_id": session_id})
        priority = self._priority(req, headers)
        if priority is not None:
            params = params.model_copy(update={"priority": priority})
        model = req.get("model") or getattr(
            getattr(handler, "config", None), "model_name", "default"
        )
        if params.json_schema is not None and strict:
            # OpenAI strict-mode parity: a schema the deployment cannot
            # enforce is a 400 up front, never a 200 whose body silently
            # degraded to the generic JSON grammar.
            support = getattr(
                getattr(handler, "backend", None), "schema_support", None
            )
            reason = (
                support(params.json_schema) if support is not None
                else "this model deployment cannot enforce json_schema"
            )
            if reason is not None:
                raise _HttpError(
                    400, f"response_format json_schema with strict=true "
                    f"is not enforceable here: {reason}"
                )
        rid = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        created = int(time.time())

        if req.get("stream"):
            await self._sse_start(writer, {"x-request-id": trace_id})

            def chunk(delta: Dict[str, Any], finish: Optional[str],
                      **extra: Any) -> None:
                self._sse_event(writer, {
                    "id": rid, "object": "chat.completion.chunk",
                    "created": created, "model": model,
                    "choices": [{
                        "index": 0, "delta": delta,
                        "finish_reason": finish,
                    }],
                    **extra,
                })

            try:
                chunk({"role": "assistant"}, None)
                text_parts: List[str] = []
                stream_info: Dict[str, Any] = {}
                async for delta in handler.astream(
                    messages, tools=tools, params=params, info=stream_info
                ):
                    text_parts.append(delta)
                    chunk({"content": delta}, None)
                    await writer.drain()
                # Streamed function calling: the engine's tool protocol
                # is JSON text, so calls are parseable only once the
                # stream ends — emit them as one final tool_calls delta
                # (clients that only read content still saw the text).
                finish = stream_info.get("finish_reason", "stop")
                if tools:
                    from pilottai_tpu.engine.base import parse_tool_calls

                    calls = parse_tool_calls(
                        "".join(text_parts), [t.name for t in tools]
                    )
                    if calls:
                        finish = "tool_calls"
                        chunk({"tool_calls": [{
                            "index": i, "id": tc.id, "type": "function",
                            "function": {
                                "name": tc.name,
                                "arguments": json.dumps(tc.arguments),
                            },
                        } for i, tc in enumerate(calls)]}, None)
                extra: Dict[str, Any] = {}
                if params.json_schema is not None:
                    # Non-stream parity: streamed clients must also be
                    # able to tell enforced from best-effort output.
                    extra["schema_enforced"] = bool(
                        stream_info.get("schema_enforced")
                    )
                if "completion_tokens" in stream_info:
                    extra["usage"] = {
                        "completion_tokens": stream_info["completion_tokens"],
                    }
                chunk({}, finish, **extra)
            except (ConnectionError, asyncio.CancelledError):
                raise  # client gone / shutdown: astream's finally cancels
            except Exception as exc:  # noqa: BLE001 — surface in-band
                self._sse_error(writer, exc)
            await self._sse_done(writer)
            return

        response = await handler.generate_response(
            messages, tools=tools, params=params
        )
        message: Dict[str, Any] = {
            "role": "assistant", "content": response.content,
        }
        if response.tool_calls:
            message["tool_calls"] = [{
                "id": tc.id, "type": "function",
                "function": {
                    "name": tc.name,
                    "arguments": json.dumps(tc.arguments),
                },
            } for tc in response.tool_calls]
        payload: Dict[str, Any] = {
            "id": rid, "object": "chat.completion",
            "created": created, "model": response.model or model,
            "choices": [{
                "index": 0, "message": message,
                "finish_reason": response.finish_reason or "stop",
            }],
            "usage": {
                "prompt_tokens": response.usage.prompt_tokens,
                "completion_tokens": response.usage.completion_tokens,
                "total_tokens": response.usage.total_tokens,
            },
        }
        if params.json_schema is not None:
            # Non-strict requests proceed on best effort; tell the client
            # whether the output was actually DFA-enforced (mock and
            # non-schema backends report not-enforced rather than None —
            # the field exists exactly so clients never have to guess).
            payload["schema_enforced"] = bool(response.schema_enforced)
        await self._send(
            writer, 200, payload, extra_headers={"x-request-id": trace_id}
        )

    # ------------------------------------------------------------------ #
    # /v1/embeddings
    # ------------------------------------------------------------------ #

    async def _embeddings(
        self, req: Dict[str, Any], writer: asyncio.StreamWriter
    ) -> None:
        if self.embedder is None:
            raise _HttpError(
                503, "no embedder attached to this endpoint", "server_error"
            )
        texts = req.get("input")
        if isinstance(texts, str):
            texts = [texts]
        if (
            not isinstance(texts, list) or not texts
            or not all(isinstance(t, str) for t in texts)
        ):
            raise _HttpError(400, "'input' must be a string or list of strings")
        # encode() is synchronous jit compute behind a thread lock — keep
        # the event loop responsive (SURVEY §7 hard part 5).
        loop = asyncio.get_running_loop()
        vecs = await loop.run_in_executor(
            None, self.embedder.encode, list(texts)
        )
        # Exact usage: what the encoder actually consumed (its own
        # tokenizer, its own max_len truncation) — clients metering on
        # the OpenAI usage field must not get a chars/4 guess.
        tok = getattr(self.embedder, "tokenizer", None)
        max_len = getattr(self.embedder, "max_len", None)
        if tok is not None:
            n_tokens = sum(
                len(tok.encode(t)[:max_len] if max_len else tok.encode(t))
                for t in texts
            )
        else:
            n_tokens = sum(len(t) // 4 for t in texts)
        await self._send(writer, 200, {
            "object": "list",
            "model": getattr(
                getattr(self.embedder, "cfg", None), "name", "embedder"
            ),
            "data": [
                {"object": "embedding", "index": i, "embedding": v.tolist()}
                for i, v in enumerate(vecs)
            ],
            "usage": {
                "prompt_tokens": n_tokens,
                "total_tokens": n_tokens,
            },
        })

    # ------------------------------------------------------------------ #
    # /v1/tasks
    # ------------------------------------------------------------------ #

    async def _submit_task(
        self,
        req: Dict[str, Any],
        writer: asyncio.StreamWriter,
        headers: Optional[Dict[str, str]] = None,
    ) -> None:
        trace_id = self._trace_id(headers)
        # Same trace posture as chat completions: serve.execute_task's
        # span (and every agent/engine span under it) joins this trace,
        # so one x-request-id greps an entire task execution.
        with global_tracer.span(
            "server.request", trace_id=trace_id, route="/v1/tasks"
        ):
            await self._submit_task_traced(req, writer, headers, trace_id)

    async def _submit_task_traced(
        self,
        req: Dict[str, Any],
        writer: asyncio.StreamWriter,
        headers: Optional[Dict[str, str]],
        trace_id: str,
    ) -> None:
        if self.serve is None:
            raise _HttpError(
                503, "no orchestrator attached to this endpoint",
                "server_error",
            )
        task = req.get("task") or req.get("description")
        if not task:
            raise _HttpError(400, "'task' (or 'description') is required")
        # Same precedence and caps as chat completions: body beats the
        # x-request-timeout header beats reliability.default_timeout, all
        # capped at max_timeout. Serve threads the budget into
        # ``task.timeout`` so agents honor it too.
        timeout = req.get("timeout")
        if timeout is None:
            timeout = (headers or {}).get("x-request-timeout")
        rel = getattr(
            getattr(self.handler, "config", None), "reliability", None
        )
        if timeout is None and rel is not None:
            timeout = rel.default_timeout
        try:
            timeout = float(timeout) if timeout is not None else None
        except (TypeError, ValueError) as exc:
            raise _HttpError(400, "'timeout' must be a number") from exc
        if timeout is not None and timeout <= 0:
            raise _HttpError(400, "'timeout' must be > 0")
        if timeout is not None and rel is not None:
            timeout = min(timeout, rel.max_timeout)

        def result_payload(result) -> Dict[str, Any]:
            return {
                "object": "task.result",
                "success": result.success,
                "output": _jsonable(result.output),
                "error": result.error,
                "execution_time": result.execution_time,
                "metadata": _jsonable(result.metadata),
            }

        if req.get("stream"):
            # Live lifecycle feed: subscribe BEFORE submitting so the
            # received/analyzed/queued events aren't missed, then SSE
            # every event (subtask events roll up) and close with the
            # final result + [DONE]. Subscription and header flush both
            # live INSIDE the try: a client that drops before the
            # headers drain must still unsubscribe (leak regression).
            task_obj = self.serve.prepare_task(task)
            q = self.serve.subscribe_events(task_obj.id)
            exec_task = None
            getter = None
            try:
                await self._sse_start(writer, {"x-request-id": trace_id})
                exec_task = asyncio.ensure_future(
                    self.serve.execute_task(task_obj, timeout=timeout)
                )
                while not exec_task.done():
                    getter = asyncio.ensure_future(q.get())
                    done, _ = await asyncio.wait(
                        {getter, exec_task},
                        return_when=asyncio.FIRST_COMPLETED,
                    )
                    if getter in done:
                        self._sse_event(writer, _jsonable(getter.result()))
                        getter = None
                        await writer.drain()
                    else:
                        getter.cancel()
                        getter = None
                while not q.empty():  # events emitted before completion
                    self._sse_event(writer, _jsonable(q.get_nowait()))
                result = await exec_task
                self._sse_event(writer, result_payload(result))
            except (ConnectionError, asyncio.CancelledError):
                raise
            except Exception as exc:  # noqa: BLE001 — surface in-band
                self._sse_error(writer, exc)
            finally:
                self.serve.unsubscribe_events(task_obj.id, q)
                # Handler cancellation mid-asyncio.wait leaves BOTH
                # futures pending — cancel whatever is still in flight.
                if getter is not None and not getter.done():
                    getter.cancel()
                if exec_task is not None and not exec_task.done():
                    exec_task.cancel()
            await self._sse_done(writer)
            return

        try:
            result = await self.serve.execute_task(task, timeout=timeout)
        except asyncio.TimeoutError:
            # The caller's budget elapsed before the orchestrator finished
            # (execute_task threaded the same budget into task.timeout, so
            # the execution side is winding the task down too).
            raise _HttpError(
                408, f"task did not complete within {timeout}s",
                "timeout_error",
            ) from None
        await self._send(
            writer, 200, result_payload(result),
            extra_headers={"x-request-id": trace_id},
        )


def _parse_json(body: bytes) -> Dict[str, Any]:
    try:
        data = json.loads(body or b"{}")
    except json.JSONDecodeError as exc:
        raise _HttpError(400, f"invalid JSON body: {exc}") from exc
    if not isinstance(data, dict):
        raise _HttpError(400, "request body must be a JSON object")
    return data


def _jsonable(value: Any) -> Any:
    """Best-effort conversion to JSON-serializable structures (task
    outputs and metrics may carry arbitrary objects)."""
    try:
        json.dumps(value)
        return value
    except (TypeError, ValueError):
        if isinstance(value, dict):
            return {str(k): _jsonable(v) for k, v in value.items()}
        if isinstance(value, (list, tuple)):
            return [_jsonable(v) for v in value]
        return repr(value)
