"""Knowledge sources: the single, coherent source protocol.

Reference parity: the *intended* union of the two reference classes
(``knowledge/knowledge_manager.py:16-26`` model with retries/timeout;
``tools/knowledge.py:5-62`` stub with connect/query/disconnect for
database/api/file types — all placeholder returns). Here the protocol is
one abstract class with three real implementations: files, callables, and
the semantic memory store (which turns EnhancedMemory into a queryable
source backed by on-device embedding search).
"""

from __future__ import annotations

import abc
import json
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional


class KnowledgeSource(abc.ABC):
    """A named, connectable, queryable knowledge backend."""

    def __init__(
        self,
        name: str,
        retries: int = 2,
        retry_delay: float = 0.5,
        timeout: float = 10.0,
    ) -> None:
        self.name = name
        self.retries = retries
        self.retry_delay = retry_delay
        self.timeout = timeout
        self.connected = False

    async def connect(self) -> bool:
        self.connected = True
        return True

    async def disconnect(self) -> None:
        self.connected = False

    @abc.abstractmethod
    async def query(self, query: str, **kwargs: Any) -> List[Dict[str, Any]]:
        """Return matching records for ``query``."""

    async def health_check(self) -> bool:
        return self.connected


class FileSource(KnowledgeSource):
    """Searches local text/JSON/JSONL files line-by-line (case-insensitive
    substring; the file analog of the reference's 'file' source type)."""

    def __init__(self, name: str, path: str | Path, **kwargs: Any) -> None:
        super().__init__(name, **kwargs)
        self.path = Path(path)

    async def connect(self) -> bool:
        self.connected = self.path.exists()
        return self.connected

    async def query(self, query: str, limit: int = 10, **kwargs: Any) -> List[Dict[str, Any]]:
        if not self.connected:
            raise ConnectionError(f"source {self.name!r} not connected")
        needle = query.lower()
        out: List[Dict[str, Any]] = []
        text = self.path.read_text(errors="replace")
        if self.path.suffix == ".json":
            data = json.loads(text)
            rows = data if isinstance(data, list) else [data]
            for row in rows:
                if needle in json.dumps(row).lower():
                    out.append({"source": self.name, "record": row})
                    if len(out) >= limit:
                        break
        else:
            for lineno, line in enumerate(text.splitlines(), 1):
                if needle in line.lower():
                    out.append(
                        {"source": self.name, "line": lineno, "text": line.strip()}
                    )
                    if len(out) >= limit:
                        break
        return out


class CallableSource(KnowledgeSource):
    """Wraps a user function (sync or async) as a source — the extension
    point the reference's 'api'/'database' stubs gestured at."""

    def __init__(
        self, name: str, fn: Callable[[str], Any], **kwargs: Any
    ) -> None:
        super().__init__(name, **kwargs)
        self.fn = fn

    async def query(self, query: str, **kwargs: Any) -> List[Dict[str, Any]]:
        if not self.connected:
            raise ConnectionError(f"source {self.name!r} not connected")
        import asyncio
        import inspect

        if inspect.iscoroutinefunction(self.fn):
            result = await self.fn(query, **kwargs)
        else:
            result = await asyncio.to_thread(self.fn, query, **kwargs)
        if isinstance(result, list):
            return [
                r if isinstance(r, dict) else {"source": self.name, "record": r}
                for r in result
            ]
        return [{"source": self.name, "record": result}]


class MemorySource(KnowledgeSource):
    """EnhancedMemory as a knowledge source: queries run through the
    on-device embedding search (ties the knowledge layer to BASELINE
    config #2's encoder path)."""

    def __init__(self, name: str, memory: Any, **kwargs: Any) -> None:
        super().__init__(name, **kwargs)
        self.memory = memory

    async def query(self, query: str, limit: int = 5, **kwargs: Any) -> List[Dict[str, Any]]:
        if not self.connected:
            raise ConnectionError(f"source {self.name!r} not connected")
        hits = await self.memory.semantic_search(query, limit=limit)
        return [{"source": self.name, **hit} for hit in hits]
