"""Knowledge layer: federated query over named sources with LRU+TTL cache.

Reference parity: ``pilott/knowledge/knowledge_manager.py`` +
``pilott/tools/knowledge.py`` — the reference ships two incompatible
``KnowledgeSource`` classes (SURVEY §2.12-e); there is exactly one here.
"""

from pilottai_tpu.knowledge.manager import KnowledgeManager
from pilottai_tpu.knowledge.source import (
    CallableSource,
    FileSource,
    KnowledgeSource,
    MemorySource,
)

__all__ = [
    "KnowledgeManager",
    "KnowledgeSource",
    "FileSource",
    "CallableSource",
    "MemorySource",
]
