"""KnowledgeManager: federated query over named sources with an LRU+TTL
cache.

Reference parity: ``pilott/knowledge/knowledge_manager.py`` —
``add_source`` with connection test (``:62-77``), ``query_knowledge``:
cache check → per-source lock → retry-with-delay-and-timeout → cache fill
(``:79-147``), OrderedDict LRU capped at 1000 with TTL 3600s
(``:157-197``), pattern/source invalidation (``:199-219``), hourly cleanup
with source-health reconnect (``:221-249``), stats (``:251-267``).
"""

from __future__ import annotations

import asyncio
import fnmatch
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from pilottai_tpu.knowledge.source import KnowledgeSource
from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import global_metrics


class KnowledgeManager:
    """Queries all (or selected) sources, caching merged results."""

    def __init__(
        self,
        cache_size: int = 1000,
        cache_ttl: float = 3600.0,
        cleanup_interval: float = 3600.0,
    ) -> None:
        self.sources: Dict[str, KnowledgeSource] = {}
        self._source_locks: Dict[str, asyncio.Lock] = {}
        self._cache: "OrderedDict[str, tuple]" = OrderedDict()  # key -> (ts, value)
        self.cache_size = cache_size
        self.cache_ttl = cache_ttl
        self.cleanup_interval = cleanup_interval
        self._stats = {"hits": 0, "misses": 0, "errors": 0, "queries": 0}
        self._cleanup_task: Optional[asyncio.Task] = None
        self._log = get_logger("knowledge")

    # ------------------------------------------------------------------ #

    async def start(self) -> None:
        if self._cleanup_task is None:
            self._cleanup_task = asyncio.create_task(self._cleanup_loop())

    async def stop(self) -> None:
        if self._cleanup_task is not None:
            self._cleanup_task.cancel()
            try:
                await self._cleanup_task
            except asyncio.CancelledError:
                pass
            self._cleanup_task = None
        for source in self.sources.values():
            await source.disconnect()

    # ------------------------------------------------------------------ #

    async def add_source(self, source: KnowledgeSource) -> None:
        """Register + connection-test a source (reference ``:62-77``)."""
        if source.name in self.sources:
            raise ValueError(f"source {source.name!r} already added")
        ok = await source.connect()
        if not ok:
            raise ConnectionError(f"source {source.name!r} failed connection test")
        self.sources[source.name] = source
        self._source_locks[source.name] = asyncio.Lock()

    async def remove_source(self, name: str) -> None:
        source = self.sources.pop(name, None)
        self._source_locks.pop(name, None)
        if source is not None:
            await source.disconnect()
        self.invalidate(f"*@{name}")

    # ------------------------------------------------------------------ #

    def _cache_get(self, key: str) -> Optional[Any]:
        hit = self._cache.get(key)
        if hit is None:
            return None
        ts, value = hit
        if time.time() - ts > self.cache_ttl:
            del self._cache[key]
            return None
        self._cache.move_to_end(key)
        return value

    def _cache_put(self, key: str, value: Any) -> None:
        self._cache[key] = (time.time(), value)
        self._cache.move_to_end(key)
        while len(self._cache) > self.cache_size:
            self._cache.popitem(last=False)

    def invalidate(self, pattern: str = "*") -> int:
        """Drop cache entries matching a glob (reference ``:199-219``)."""
        doomed = [k for k in self._cache if fnmatch.fnmatch(k, pattern)]
        for key in doomed:
            del self._cache[key]
        return len(doomed)

    # ------------------------------------------------------------------ #

    async def query_knowledge(
        self,
        query: str,
        sources: Optional[List[str]] = None,
        use_cache: bool = True,
        **kwargs: Any,
    ) -> List[Dict[str, Any]]:
        """Query selected (default: all) sources, merging results."""
        self._stats["queries"] += 1
        names = sources or list(self.sources)
        results: List[Dict[str, Any]] = []
        for name in names:
            if name not in self.sources:
                raise KeyError(f"unknown source {name!r}")
            key = f"{query}@{name}"
            if use_cache:
                cached = self._cache_get(key)
                if cached is not None:
                    self._stats["hits"] += 1
                    results.extend(cached)
                    continue
            self._stats["misses"] += 1
            rows = await self._query_source_with_retry(name, query, **kwargs)
            if rows is not None:
                self._cache_put(key, rows)
                results.extend(rows)
        global_metrics.inc("knowledge.queries")
        return results

    async def _query_source_with_retry(
        self, name: str, query: str, **kwargs: Any
    ) -> Optional[List[Dict[str, Any]]]:
        """Per-source lock + retries + timeout (reference ``:120-147``)."""
        source = self.sources[name]
        async with self._source_locks[name]:
            for attempt in range(source.retries + 1):
                try:
                    return await asyncio.wait_for(
                        source.query(query, **kwargs), timeout=source.timeout
                    )
                except Exception as exc:  # noqa: BLE001 - retry boundary
                    self._stats["errors"] += 1
                    self._log.warning(
                        "source %s query failed (attempt %d): %s",
                        name, attempt + 1, exc,
                    )
                    if attempt < source.retries:
                        await asyncio.sleep(source.retry_delay * (attempt + 1))
        return None

    # ------------------------------------------------------------------ #

    async def _cleanup_loop(self) -> None:
        while True:
            await asyncio.sleep(self.cleanup_interval)
            await self.cleanup()

    async def cleanup(self) -> None:
        """Expire stale cache entries; reconnect unhealthy sources
        (reference ``:221-249``)."""
        now = time.time()
        for key in [k for k, (ts, _) in self._cache.items() if now - ts > self.cache_ttl]:
            del self._cache[key]
        for name, source in self.sources.items():
            try:
                if not await source.health_check():
                    self._log.info("reconnecting unhealthy source %s", name)
                    await source.connect()
            except Exception as exc:  # noqa: BLE001
                self._log.warning("health check failed for %s: %s", name, exc)

    # ------------------------------------------------------------------ #

    def get_source_stats(self) -> Dict[str, Any]:
        return {
            name: {"connected": s.connected, "timeout": s.timeout}
            for name, s in self.sources.items()
        }

    def get_cache_stats(self) -> Dict[str, Any]:
        total = self._stats["hits"] + self._stats["misses"]
        return {
            **self._stats,
            "entries": len(self._cache),
            "hit_rate": self._stats["hits"] / total if total else 0.0,
        }
