"""Fault-injection registry: named failure points, scriptable from tests.

The failure paths this tree claims to handle (device failure → slot
recovery, heartbeat loss → agent replacement, journal loss → at-least-once
replay) were previously only reachable by monkeypatching internals. This
registry gives every such path a stable, named trigger that is a **no-op
in production** (one empty-dict check, no lock) and scriptable from chaos
tests: raise an exception, sleep to simulate a slow/hung dependency, or
hand the consuming site a value (e.g. seconds of heartbeat stall).

Canonical points wired in-tree (callers may add more; names are free-form):

===========================  =============================================
``engine.step``              decode-chunk dispatch (``batcher._dispatch_chunk``)
``engine.prefill``           admission prefill — ``delay=`` simulates a
                             slow/hung prefill, ``exc=`` a failed one
``engine.dispatch.hang``     a stuck dispatch — ``delay=`` pins the device
                             thread inside ``_dispatch_chunk`` without
                             raising, exactly what a hung XLA call or a
                             wedged collective looks like (the watchdog's
                             detection target)
``engine.fold.corrupt``      poisons one slot's folded tokens with
                             out-of-vocab ids at the fold boundary —
                             ``value=`` the slot index (or ``True`` for
                             the first live slot)
``engine.rebuild``           failure-path ``_rebuild_device_state`` —
                             ``exc=`` simulates a rebuild that itself
                             fails (retried next device-loop cycle)
``handler.timeout``          ``LLMHandler``'s backend call boundary
``agent.heartbeat.stall``    ``FaultTolerance._assess`` consumes ``value=``
                             seconds of injected heartbeat staleness
``checkpoint.write``         ``TaskJournal`` append (disk-full simulation)
``mesh.shard_loss``          a serving-mesh device fails mid-decode —
                             ``value=`` the boot-order device index (the
                             dispatch raises ``ShardLossError``); a dict
                             ``{"device": i, "hang": True}`` freezes that
                             shard's heartbeat instead (the watchdog-path
                             detector's target)
``kvcache.spill.corrupt``    flips a byte of a host-tier entry AFTER its
                             CRC sealed (host-RAM rot between spill and
                             restore) — restore must detect + re-prefill
``kvcache.restore.corrupt``  same rot, injected at the restore site
                             (``KVCacheIndex._entry_ok``)
``cell.migrate.corrupt``     flips a byte of a migration wire payload —
                             the import must reject it cleanly
===========================  =============================================

Triggering is count-based (``times=N`` fires, then auto-disarm; ``times=None``
fires until disarmed) and/or probability-based (``probability=p`` with a
seeded per-registry RNG, so chaos soaks are reproducible). Fires are
counted per point (``fired(name)``) and in ``global_metrics`` under
``fault.injected.<name>``.

Thread safety: ``fire()`` is called concurrently from the batcher's
prep, device and reader threads. Every counter transition — the
``skip=N`` countdown, the probability draw, the ``fired`` increment and
the ``times`` auto-disarm — happens under ONE registry lock, so an
``arm(times=1, skip=2)`` fires exactly once after exactly two passes no
matter how many threads race the point (pinned by
tests/test_kv_integrity.py's hammer). Only the not-armed fast path and
the post-decision effects (metrics, sleep, raise) run lock-free.
"""

from __future__ import annotations

import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, Optional, Type, Union

from pilottai_tpu.utils.metrics import global_metrics

ExcSpec = Union[BaseException, Type[BaseException]]


@dataclass
class Fault:
    """An armed failure point. ``exc``/``delay``/``value`` compose: a fire
    sleeps ``delay`` first, then raises ``exc`` (if set), else returns
    ``value`` to the consuming site.

    The mutable counters (``skip``, ``fired``) are transitioned ONLY
    under the owning registry's lock — test code may read them freely
    (torn reads of an int are impossible in CPython) but must never
    write them while the point is armed."""

    name: str
    exc: Optional[ExcSpec] = None
    delay: float = 0.0
    value: Any = None
    times: Optional[int] = 1    # fires before auto-disarm; None = unlimited
    probability: float = 1.0
    skip: int = 0               # let this many passes through first — e.g.
                                # land a fault mid-decode, after real
                                # tokens have already folded
    fired: int = field(default=0)

    def _materialize(self) -> BaseException:
        exc = self.exc
        if isinstance(exc, type):
            return exc(f"injected fault at {self.name!r}")
        assert exc is not None
        return exc


class FaultInjector:
    """Thread-safe fault registry with a near-free production fast path."""

    def __init__(self, seed: int = 0) -> None:
        self._faults: Dict[str, Fault] = {}
        self._fired: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._rng = random.Random(seed)

    # ------------------------------------------------------------------ #
    # Arming (test side)
    # ------------------------------------------------------------------ #

    def arm(
        self,
        name: str,
        exc: Optional[ExcSpec] = None,
        *,
        delay: float = 0.0,
        value: Any = None,
        times: Optional[int] = 1,
        probability: float = 1.0,
        skip: int = 0,
    ) -> Fault:
        fault = Fault(
            name=name, exc=exc, delay=delay, value=value,
            times=times, probability=probability, skip=skip,
        )
        with self._lock:
            self._faults[name] = fault
        return fault

    def disarm(self, name: str) -> None:
        with self._lock:
            self._faults.pop(name, None)

    def reset(self) -> None:
        """Disarm everything and clear fire counts (test teardown)."""
        with self._lock:
            self._faults.clear()
            self._fired.clear()

    def armed(self, name: str) -> bool:
        # Lock-free read (CPython dict membership is atomic) — same
        # contract as fire()'s fast path: a one-call-late answer is
        # fine, a lock on every probe is not.
        return name in self._faults

    def remaining(self, name: str) -> Optional[int]:
        """Fires left before auto-disarm (None = unlimited or not
        armed) — chaos-soak introspection."""
        with self._lock:
            fault = self._faults.get(name)
            if fault is None or fault.times is None:
                return None
            return max(0, fault.times - fault.fired)

    def fired(self, name: str) -> int:
        """Times ``name`` actually triggered (survives auto-disarm)."""
        with self._lock:
            return self._fired.get(name, 0)

    # ------------------------------------------------------------------ #
    # Firing (production side)
    # ------------------------------------------------------------------ #

    def fire(self, name: str, **context: Any) -> Any:
        """Trigger point ``name``. Returns the fault's ``value`` (or None
        when not armed / not triggered); sleeps ``delay``; raises ``exc``.

        Production fast path: when nothing is armed this is a single dict
        membership check — no lock, no allocation. ``context`` kwargs are
        informational (they ride into the metrics site labels only via
        the caller) and let call sites pass ids without formatting cost
        on the fast path.

        ``delay`` uses ``time.sleep`` — intended for thread-context points
        (the batcher's device thread); async sites should inject
        exceptions instead of delays.
        """
        if name not in self._faults:  # production fast path
            return None
        with self._lock:
            fault = self._faults.get(name)
            if fault is None:
                return None
            if fault.skip > 0:
                fault.skip -= 1
                return None
            if fault.probability < 1.0 and self._rng.random() >= fault.probability:
                return None
            fault.fired += 1
            self._fired[name] = self._fired.get(name, 0) + 1
            if fault.times is not None and fault.fired >= fault.times:
                self._faults.pop(name, None)
        global_metrics.inc(f"fault.injected.{name}")
        if fault.delay > 0:
            time.sleep(fault.delay)
        if fault.exc is not None:
            raise fault._materialize()
        return fault.value

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "armed": sorted(self._faults),
                "fired": dict(self._fired),
            }


global_injector = FaultInjector()


@contextmanager
def inject(
    name: str,
    exc: Optional[ExcSpec] = None,
    *,
    injector: Optional[FaultInjector] = None,
    **kwargs: Any,
) -> Iterator[Fault]:
    """Scoped arming for tests: the point is disarmed on exit no matter
    how the block ends (count-exhausted auto-disarm included)."""
    reg = injector if injector is not None else global_injector
    fault = reg.arm(name, exc, **kwargs)
    try:
        yield fault
    finally:
        reg.disarm(name)
