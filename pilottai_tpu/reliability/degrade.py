"""Degradation ladder: shed capability before shedding availability.

Repeated engine faults inside a short window mean the device (or the
workload hitting it) is unhealthy in a way one recovery cycle won't fix.
Instead of oscillating between full-speed serving and total failure, the
ladder steps *capability* down one rung per burst of faults — each rung
trades throughput for stability using only knobs the batcher can change
between dispatches (no new executables, no restarts):

====  ==================  =================================================
rung  name                effect (cumulative — each rung implies the ones
                          below it)
====  ==================  =================================================
0     ``full``            normal serving
1     ``no_draft``        speculative *model* drafting disabled (n-gram
                          drafts only — no extra shallow-layer weight
                          passes on a device that is already struggling)
2     ``min_chunk``       decode chunks clamped to the smallest compiled
                          bucket (short dispatches → short blast radius
                          and fast fold heartbeats)
3     ``half_slots``      admission capped at half the slots (less work
                          in flight per fault)
4     ``shed_batch``      batch-class requests shed outright; remaining
                          capacity defends the interactive SLO class
                          (obs/slo.py)
====  ==================  =================================================

Promotion is automatic: a clean soak of ``promote_s`` seconds without a
fault steps one rung back up (one rung per soak period, so a flapping
device climbs slowly). The current rung is exported as the
``engine.degrade_level`` gauge; every fault is counted under
``engine.faults.<reason>``.

Import cost: stdlib + utils only (control-plane safe).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict

from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import global_metrics

FULL = 0
NO_DRAFT = 1
MIN_CHUNK = 2
HALF_SLOTS = 3
SHED_BATCH = 4

LEVEL_NAMES = ("full", "no_draft", "min_chunk", "half_slots", "shed_batch")
MAX_LEVEL = len(LEVEL_NAMES) - 1


class DegradeLadder:
    """Rolling-window fault counter driving the capability rung.

    ``record_fault`` is called by the batcher's failure paths (device
    loop errors, reader errors, poisoned folds, watchdog stalls); the
    batcher consults ``level()`` between dispatches. Thread-safe; the
    clock is injectable for tests.
    """

    def __init__(
        self,
        fault_threshold: int = 3,
        window_s: float = 30.0,
        promote_s: float = 60.0,
        enabled: bool = True,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.fault_threshold = max(1, fault_threshold)
        self.window_s = window_s
        self.promote_s = promote_s
        self.enabled = enabled
        self._clock = clock
        self._lock = threading.Lock()
        self._level = FULL
        self._faults: Deque[float] = deque()
        self._last_change = clock()
        self._log = get_logger("reliability.degrade")
        global_metrics.set_gauge("engine.degrade_level", 0.0)

    # ------------------------------------------------------------------ #

    def record_fault(self, reason: str = "fault") -> int:
        """Count one fault event; step the rung down (level up) when the
        rolling window crosses the threshold. Returns the current level."""
        global_metrics.inc(f"engine.faults.{reason}")
        now = self._clock()
        with self._lock:
            self._promote_locked(now)
            if not self.enabled:
                return self._level
            self._faults.append(now)
            while self._faults and now - self._faults[0] > self.window_s:
                self._faults.popleft()
            if (
                len(self._faults) >= self.fault_threshold
                and self._level < MAX_LEVEL
            ):
                self._level += 1
                self._faults.clear()  # each rung needs a fresh burst
                self._last_change = now
                global_metrics.inc("engine.degrade_steps")
                self._set_gauge()
                self._log.warning(
                    "degrade ladder stepped to %d (%s) after fault %r",
                    self._level, LEVEL_NAMES[self._level], reason,
                )
            return self._level

    def level(self) -> int:
        """Current rung, with clock-driven auto-promotion applied: each
        clean ``promote_s`` soak since the last change steps one rung
        back toward full capability."""
        with self._lock:
            self._promote_locked(self._clock())
            return self._level

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            self._promote_locked(self._clock())
            return {
                "level": self._level,
                "name": LEVEL_NAMES[self._level],
                "faults_in_window": len(self._faults),
                "enabled": self.enabled,
            }

    # ------------------------------------------------------------------ #

    def _promote_locked(self, now: float) -> None:
        promoted = False
        while (
            self._level > FULL
            and now - self._last_change >= self.promote_s
        ):
            self._level -= 1
            self._last_change += self.promote_s
            promoted = True
        if promoted:
            self._faults.clear()
            self._set_gauge()
            self._log.info(
                "clean soak: degrade ladder promoted to %d (%s)",
                self._level, LEVEL_NAMES[self._level],
            )

    def _set_gauge(self) -> None:
        global_metrics.set_gauge("engine.degrade_level", float(self._level))
