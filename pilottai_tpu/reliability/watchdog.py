"""Device watchdog: detect a *hung* engine, not just a failed one.

A device-side exception reaches the batcher's except-arms and is handled
(recovery, rebuild). A *stuck* dispatch — a hung XLA call, a wedged
collective on a multichip mesh, a tunnel that silently stopped moving
bytes — never raises anywhere: the device thread blocks inside the
dispatch, folds stop arriving, and every client simply hangs until its
own timeout. The watchdog turns that silent state into an explicit one:

* the batcher ``beat()``s the watchdog on every fold / prefill /
  segment advance (progress heartbeats);
* a monitor thread declares the engine **stalled** when heartbeats go
  stale for ``stall_s`` seconds *while work is in flight* (an idle
  engine never beats and is healthy by definition);
* a stall fires the ``EngineHealth`` registry: the health endpoint
  flips to 503 (with a ``retry_after`` hint), subscribed circuit
  breakers force-open so new requests fast-fail instead of queueing
  onto a dead device, and the batcher's ``on_stall`` hook writes a
  black-box dump — a hung TPU dispatch becomes a 503-with-diagnostics
  instead of a pile of silent client hangs;
* a late heartbeat (the hang resolved) marks the engine recovered; the
  breaker re-closes through its own half-open probing.

Import cost: stdlib + utils only (the package's control-plane
constraint) — the black-box dump is wired by the batcher, which already
imports ``obs``.
"""

from __future__ import annotations

import inspect
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional

from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import global_metrics


class EngineHealth:
    """Process-level engine liveness registry.

    One place three consumers meet: the watchdog writes stall/recovery
    transitions, the HTTP edge reads ``healthy()`` for ``/healthz``, and
    circuit breakers ``subscribe()`` so a stall force-opens them without
    the batcher ever knowing a breaker exists (the handler owns the
    breaker, the engine backend owns the batcher — this registry is the
    only coupling point). Subscribers are held weakly (bound methods via
    ``WeakMethod``) so short-lived handlers in tests never accumulate.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # Per-SOURCE stall records: a process can host several engines
        # (APIServer's multi-model handler map), each with its own
        # watchdog — one engine recovering must not flip /healthz back
        # to 200 while a sibling is still hung. Healthy ⇔ no sources.
        self._stalls: Dict[str, Dict[str, Any]] = {}
        self._subs: List[Any] = []
        self._log = get_logger("reliability.health")

    def subscribe(self, callback: Callable[[Dict[str, Any]], None]) -> None:
        """Register ``callback(snapshot)`` to fire on every transition to
        stalled (not on recovery — a breaker re-closes by probing)."""
        ref = (
            weakref.WeakMethod(callback)
            if inspect.ismethod(callback) else (lambda cb=callback: cb)
        )
        with self._lock:
            self._subs.append(ref)

    def mark_stalled(
        self, reason: str = "engine stalled", retry_after: float = 0.0,
        source: str = "engine", **info: Any,
    ) -> None:
        with self._lock:
            self._stalls[source] = {
                "reason": reason,
                "since": time.monotonic(),
                "retry_after": retry_after,
            }
            live = []
            subs = []
            for ref in self._subs:
                cb = ref()
                if cb is not None:
                    live.append(ref)
                    subs.append(cb)
            self._subs = live
        global_metrics.set_gauge("engine.stalled", 1.0)
        # Subscribers (breakers) BEFORE the log line: the health flip is
        # already observable, and fast-fail should engage before we
        # spend time formatting diagnostics.
        snap = self.snapshot()
        for cb in subs:
            try:
                cb(snap)
            except Exception as exc:  # noqa: BLE001 — never break the marker
                self._log.warning("engine-stall subscriber failed: %s", exc)
        self._log.error("engine %r marked stalled: %s", source, reason)

    def mark_recovered(self, source: str = "engine") -> None:
        with self._lock:
            was = self._stalls.pop(source, None)
            still = bool(self._stalls)
        global_metrics.set_gauge("engine.stalled", 1.0 if still else 0.0)
        if was is not None:
            self._log.info(
                "engine %r marked recovered (%s)", source,
                "others still stalled" if still else "all healthy",
            )

    def healthy(self) -> bool:
        return not self._stalls

    def source_healthy(self, source: Optional[str]) -> bool:
        """Per-source verdict: a multi-replica process (serving cell)
        must keep routing to healthy replicas while a sibling is hung —
        the aggregate ``healthy()`` would ground the whole cell."""
        if source is None:
            return self.healthy()
        with self._lock:
            return source not in self._stalls

    def snapshot(self) -> Dict[str, Any]:
        """Aggregate view (the health endpoint's shape): oldest stall's
        age, every source's reason, the largest retry_after."""
        now = time.monotonic()
        with self._lock:
            if not self._stalls:
                return {
                    "stalled": False, "reason": None,
                    "stalled_for_s": None, "retry_after": 0.0,
                }
            return {
                "stalled": True,
                "reason": "; ".join(
                    s["reason"] for s in self._stalls.values()
                ),
                "stalled_for_s": round(
                    now - min(s["since"] for s in self._stalls.values()), 3
                ),
                "retry_after": max(
                    s["retry_after"] for s in self._stalls.values()
                ),
                "sources": sorted(self._stalls),
            }

    def reset(self) -> None:
        """Test teardown: clear state AND subscribers."""
        with self._lock:
            self._stalls.clear()
            self._subs = []
        global_metrics.set_gauge("engine.stalled", 0.0)


global_engine_health = EngineHealth()


class Watchdog:
    """Heartbeat-staleness monitor for one batcher's device loop.

    ``beat()`` is called by the progress paths (fold, prefill install,
    segment advance); ``has_work()`` is the batcher's cheap "anything in
    flight or queued?" probe. While ``has_work()`` is False the last-beat
    mark tracks the clock, so the stall timer starts at the moment work
    appears — an idle engine can never trip. Warmup compiles are excluded
    the same way (the batcher's probe returns False while warming).
    """

    def __init__(
        self,
        stall_s: float,
        has_work: Callable[[], bool],
        on_stall: Optional[Callable[[Dict[str, Any]], None]] = None,
        name: str = "engine",
        health: Optional[EngineHealth] = None,
        poll_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.stall_s = stall_s
        self.poll_s = poll_s if poll_s is not None else max(
            min(stall_s / 4.0, 0.25), 0.01
        )
        self.name = name
        self._has_work = has_work
        self._on_stall = on_stall
        self._health = health if health is not None else global_engine_health
        self._clock = clock
        self._last = clock()
        self._stalled = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._log = get_logger("reliability.watchdog")

    def beat(self) -> None:
        """Progress heartbeat (any thread; a plain float store)."""
        self._last = self._clock()

    @property
    def stalled(self) -> bool:
        return self._stalled

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._last = self._clock()
        self._thread = threading.Thread(
            target=self._run, name=f"pilottai-watchdog-{self.name}",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._stalled:
            # A deliberate engine stop while stalled must not leave the
            # process health endpoint pinned at 503 forever (only THIS
            # watchdog's stall clears — siblings stay stalled).
            self._stalled = False
            self._health.mark_recovered(self.name)

    # ------------------------------------------------------------------ #

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            now = self._clock()
            try:
                busy = bool(self._has_work())
            except Exception:  # noqa: BLE001 — probe must not kill the dog
                busy = False
            if not busy:
                if self._stalled:
                    self._recover()
                self._last = now
                continue
            stale = now - self._last
            if stale >= self.stall_s and not self._stalled:
                self._trip(stale)
            elif stale < self.stall_s and self._stalled:
                self._recover()

    def _trip(self, stale: float) -> None:
        self._stalled = True
        global_metrics.inc("engine.watchdog_stalls")
        info = {
            "stalled_for_s": round(stale, 3),
            "stall_s": self.stall_s,
            "watchdog": self.name,
        }
        self._log.error(
            "engine %s stalled: no fold/prefill heartbeat for %.2fs with "
            "work in flight (stall_s=%.2fs)", self.name, stale, self.stall_s,
        )
        self._health.mark_stalled(
            reason=(
                f"device loop heartbeat stale for {stale:.2f}s with work "
                f"in flight (watchdog_stall_s={self.stall_s})"
            ),
            retry_after=self.stall_s,
            source=self.name,
            **info,
        )
        if self._on_stall is not None:
            try:
                self._on_stall(info)
            except Exception as exc:  # noqa: BLE001 — diagnostics only
                self._log.warning("watchdog on_stall hook failed: %s", exc)

    def _recover(self) -> None:
        self._stalled = False
        global_metrics.inc("engine.watchdog_recoveries")
        self._health.mark_recovered(self.name)
