"""Deadline and overload error types + small helpers.

One vocabulary for the whole request path: the HTTP edge derives an
absolute deadline (``time.monotonic()`` based — wall-clock jumps must not
expire requests), threads it through ``GenerationParams.deadline`` /
``GenRequest.deadline``, and every layer that can spend time checks it.
The server maps these to structured JSON errors (docs/SERVING.md,
"Overload & failure semantics"): ``DeadlineExceeded`` → 408,
``EngineOverloaded`` → 429, ``CircuitOpenError`` (breaker.py) → 503.
"""

from __future__ import annotations

import time
from typing import Optional


class DeadlineExceeded(TimeoutError):
    """The request's end-to-end deadline passed before it completed.

    Subclasses ``TimeoutError`` so callers that already handle timeouts
    (orchestrator retry paths, asyncio.wait_for users) treat it the same
    way without knowing about this module.
    """


class EngineOverloaded(RuntimeError):
    """Admission refused: the engine's queue is beyond its configured
    depth. Raised synchronously at submit — no slot, no queue entry, no
    partial work exists for the request."""


class PoisonedOutput(RuntimeError):
    """The device returned tokens that fail validation at the fold
    boundary (out-of-vocab ids — the host-visible symptom of NaN logits
    or corrupted device memory). Contained per request: only the
    affected slot fails; the engine and its other occupants keep
    serving. Not replayed by in-flight recovery (re-decoding corrupted
    state would reproduce the poison); the handler's normal retry loop
    gives the request a fresh attempt instead."""


def deadline_from_timeout(
    timeout: Optional[float], now: Optional[float] = None
) -> Optional[float]:
    """Relative budget → absolute monotonic deadline (None passes through)."""
    if timeout is None:
        return None
    return (now if now is not None else time.monotonic()) + timeout


def remaining(
    deadline: Optional[float], now: Optional[float] = None
) -> Optional[float]:
    """Seconds left before ``deadline`` (may be negative); None = no deadline."""
    if deadline is None:
        return None
    return deadline - (now if now is not None else time.monotonic())


def expired(deadline: Optional[float], now: Optional[float] = None) -> bool:
    if deadline is None:
        return False
    return (now if now is not None else time.monotonic()) >= deadline
