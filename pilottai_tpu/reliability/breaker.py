"""Circuit breaker: fast-fail when the backend is demonstrably dead.

Without one, every request against a failed device pays the full
retry-with-backoff budget before erroring — under heavy traffic that
pins the concurrency semaphore, piles timed-out work onto a backend that
cannot serve it, and turns one device failure into minutes of 500s.
LLM-Pilot (arxiv 2410.02425) frames this as admission control for
predictable tails; the breaker is the failure-side half.

States (classic three-state machine, monotonic-clock based):

* **closed** — normal; consecutive failures are counted, any success
  resets the count. ``failure_threshold`` consecutive failures open it.
* **open** — ``allow()`` is False (callers raise ``CircuitOpenError``
  without touching the backend) until ``recovery_timeout`` elapses.
* **half-open** — up to ``half_open_max`` probe calls pass through; a
  probe success closes the breaker, a probe failure re-opens it (and
  re-arms the full recovery timeout).

Thread-safe: the engine handler calls from the event loop, chaos tests
and metrics scrapes from other threads. State transitions are counted in
``global_metrics`` (``reliability.breaker_opened`` / ``_closed``) and the
current state exposed as gauge ``reliability.breaker_state.<name>``
(0=closed, 1=half-open, 2=open).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from pilottai_tpu.utils.metrics import global_metrics

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitOpenError(RuntimeError):
    """Fast-fail: the breaker is open and the call was not attempted.

    ``retry_after`` is the seconds until the next half-open probe window
    (servers surface it as a Retry-After hint)."""

    def __init__(self, message: str, retry_after: float = 0.0) -> None:
        super().__init__(message)
        self.retry_after = max(0.0, retry_after)


class CircuitBreaker:
    """Consecutive-failure breaker with half-open probing."""

    def __init__(
        self,
        failure_threshold: int = 5,
        recovery_timeout: float = 30.0,
        half_open_max: int = 1,
        name: str = "engine",
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.failure_threshold = max(1, failure_threshold)
        self.recovery_timeout = recovery_timeout
        self.half_open_max = max(1, half_open_max)
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0          # consecutive, while closed
        self._opened_at = 0.0
        self._probes = 0            # in-flight half-open probes
        # Observability hook: fired (outside the lock) with the breaker's
        # name each time it transitions closed/half-open → open. The
        # handler wires this to the black-box dumper so the engine state
        # surrounding the open is captured. Must be cheap-ish and never
        # raise back into the breaker.
        self.on_open: Optional[Callable[[str], None]] = None
        self._set_gauge()

    # ------------------------------------------------------------------ #

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def retry_after(self) -> float:
        """Seconds until the next call could pass (0 when not open)."""
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(
                0.0, self.recovery_timeout - (self._clock() - self._opened_at)
            )

    def allow(self) -> bool:
        """True when a call may proceed. In half-open this RESERVES a
        probe slot — pair every ``allow() == True`` with exactly one
        ``record_success``/``record_failure``."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and self._probes < self.half_open_max:
                self._probes += 1
                return True
            return False

    def release_probe(self) -> None:
        """Un-reserve a half-open probe whose call ended with NO verdict
        (e.g. cancelled mid-flight). Without this the reserved slot would
        leak — ``_probes`` only resets on state transitions — and with
        every slot leaked ``allow()`` would return False forever while
        ``retry_after()`` reads 0: a permanently wedged breaker."""
        with self._lock:
            if self._state == HALF_OPEN and self._probes > 0:
                self._probes -= 1

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state != CLOSED:
                self._state = CLOSED
                self._probes = 0
                global_metrics.inc("reliability.breaker_closed")
            self._set_gauge()

    def record_failure(self) -> None:
        with self._lock:
            prev = self._state
            if self._state == HALF_OPEN:
                # The probe failed: the backend is still dead — re-open
                # and re-arm the full recovery window.
                self._open()
            elif self._state == CLOSED:
                self._failures += 1
                if self._failures >= self.failure_threshold:
                    self._open()
            self._set_gauge()
            opened = self._state == OPEN and prev != OPEN
        hook = self.on_open
        if opened and hook is not None:
            # Outside the lock: the hook may take its own locks / do IO
            # (black-box dump) and must not be able to deadlock callers.
            try:
                hook(self.name)
            except Exception:  # noqa: BLE001 — hook must not break the breaker
                pass

    def force_open(self, reason: str = "forced") -> None:
        """Open immediately regardless of the failure count — the device
        watchdog's path: a *hung* engine produces no failures to count
        (calls never return), so the stall itself is the verdict. The
        normal half-open probing recovers it once ``recovery_timeout``
        elapses and the engine answers again."""
        with self._lock:
            prev = self._state
            if prev != OPEN:
                self._open()
            self._set_gauge()
            opened = self._state == OPEN and prev != OPEN
        hook = self.on_open
        if opened and hook is not None:
            try:
                hook(self.name)
            except Exception:  # noqa: BLE001 — hook must not break the breaker
                pass

    def on_engine_stall(self, snapshot: Optional[Dict[str, Any]] = None) -> None:
        """``EngineHealth`` subscriber form (reliability/watchdog.py):
        a bound method, so the health registry can hold it weakly.

        ``health_sources`` (set by the owner — the serving cell scopes
        each replica's breaker to its own engine's watchdog source)
        filters the process-wide stall fan-out: in a multi-replica
        process, replica A hanging must fast-fail A's handler, not
        ground every sibling. None (the default, single-engine
        processes) keeps the original any-stall-opens behavior."""
        sources = getattr(self, "health_sources", None)
        if sources is not None and snapshot is not None:
            stalled = set(snapshot.get("sources") or ())
            if not (stalled & set(sources)):
                return
        self.force_open("engine watchdog stall")

    # ------------------------------------------------------------------ #

    def _open(self) -> None:
        # lock held
        self._state = OPEN
        self._opened_at = self._clock()
        self._probes = 0
        self._failures = 0
        global_metrics.inc("reliability.breaker_opened")

    def _maybe_half_open(self) -> None:
        # lock held
        if (
            self._state == OPEN
            and self._clock() - self._opened_at >= self.recovery_timeout
        ):
            self._state = HALF_OPEN
            self._probes = 0
            self._set_gauge()

    def _set_gauge(self) -> None:
        global_metrics.set_gauge(
            f"reliability.breaker_state.{self.name}", _STATE_GAUGE[self._state]
        )

    def open_error(self) -> CircuitOpenError:
        return CircuitOpenError(
            f"engine circuit breaker {self.name!r} is open "
            f"(backend failing; retry in {self.retry_after():.1f}s)",
            retry_after=self.retry_after(),
        )

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._failures,
                "retry_after": (
                    max(
                        0.0,
                        self.recovery_timeout
                        - (self._clock() - self._opened_at),
                    )
                    if self._state == OPEN else 0.0
                ),
            }
