"""Reliability layer: end-to-end deadlines, overload shedding, circuit
breaking and a fault-injection (chaos) harness.

The serving north star is heavy traffic against finite hardware; this
package holds the pieces that keep overload and failure *bounded*:

* ``deadline`` — one deadline/overload error vocabulary plus monotonic
  deadline helpers, threaded HTTP edge → handler → batcher.
* ``inject`` — named failure points (no-ops in production) that chaos
  tests script to provoke the failure paths the tree claims to handle.
* ``breaker`` — a circuit breaker wrapping engine calls so repeated
  device failures flip to fast-fail 503s with half-open probing.
* ``watchdog`` — a heartbeat-staleness monitor that turns a *hung*
  dispatch (which never raises anywhere) into an explicit stalled
  state: health endpoint 503s, subscribed breakers force-open, and a
  black-box dump captures the engine's last steps.
* ``degrade`` — a capability ladder: repeated faults inside a rolling
  window step serving capability down (drafting → chunk size → slots →
  batch-class shed) instead of oscillating between full speed and
  total failure; a clean soak promotes back up.

Import cost: utils-only dependencies, no jax — safe for control-plane
processes.
"""

from pilottai_tpu.reliability.breaker import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
    CircuitOpenError,
)
from pilottai_tpu.reliability.deadline import (
    DeadlineExceeded,
    EngineOverloaded,
    PoisonedOutput,
    deadline_from_timeout,
    expired,
    remaining,
)
from pilottai_tpu.reliability.degrade import DegradeLadder
from pilottai_tpu.reliability.watchdog import (
    EngineHealth,
    Watchdog,
    global_engine_health,
)
from pilottai_tpu.reliability.inject import (
    Fault,
    FaultInjector,
    global_injector,
    inject,
)

__all__ = [
    "CLOSED",
    "HALF_OPEN",
    "OPEN",
    "CircuitBreaker",
    "CircuitOpenError",
    "DeadlineExceeded",
    "DegradeLadder",
    "EngineHealth",
    "EngineOverloaded",
    "Fault",
    "FaultInjector",
    "PoisonedOutput",
    "Watchdog",
    "deadline_from_timeout",
    "expired",
    "global_engine_health",
    "global_injector",
    "inject",
    "remaining",
]
