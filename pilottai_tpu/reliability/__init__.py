"""Reliability layer: end-to-end deadlines, overload shedding, circuit
breaking and a fault-injection (chaos) harness.

The serving north star is heavy traffic against finite hardware; this
package holds the pieces that keep overload and failure *bounded*:

* ``deadline`` — one deadline/overload error vocabulary plus monotonic
  deadline helpers, threaded HTTP edge → handler → batcher.
* ``inject`` — named failure points (no-ops in production) that chaos
  tests script to provoke the failure paths the tree claims to handle.
* ``breaker`` — a circuit breaker wrapping engine calls so repeated
  device failures flip to fast-fail 503s with half-open probing.

Import cost: utils-only dependencies, no jax — safe for control-plane
processes.
"""

from pilottai_tpu.reliability.breaker import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
    CircuitOpenError,
)
from pilottai_tpu.reliability.deadline import (
    DeadlineExceeded,
    EngineOverloaded,
    deadline_from_timeout,
    expired,
    remaining,
)
from pilottai_tpu.reliability.inject import (
    Fault,
    FaultInjector,
    global_injector,
    inject,
)

__all__ = [
    "CLOSED",
    "HALF_OPEN",
    "OPEN",
    "CircuitBreaker",
    "CircuitOpenError",
    "DeadlineExceeded",
    "EngineOverloaded",
    "Fault",
    "FaultInjector",
    "deadline_from_timeout",
    "expired",
    "global_injector",
    "inject",
    "remaining",
]
