"""Serving cell: N engine replicas behind one KV-affinity front door.

ISSUE 11 / ROADMAP item 2 — the million-user shape is many engine
replicas behind one admission point, not one bigger engine. A
:class:`ServingCell` hosts N replicas in one process (each its own
``LLMHandler`` + batcher + per-replica SLO registry, so tests and bench
run a realistic cell without N processes) and routes every request with
:class:`~pilottai_tpu.distributed.router.ReplicaRouter`:

* **KV affinity** — a cell-level radix routing table (prompt byte
  prefixes → last-serving replica) plus sticky session pins, so a
  session's next turn lands where its KV already lives (a restore or a
  hot prefix hit instead of a full re-prefill).
* **SLO headroom** — each replica carries its own
  :class:`~pilottai_tpu.obs.SLOTracker` (own ``MetricsRegistry``); the
  router reads per-class burn rate per replica, and the cell sheds a
  class at the boundary once *every* routable replica is past that
  class's admission threshold — before any replica's own queue shed.
* **Fault routing** — a watchdog-stalled, breaker-open or draining
  replica never receives new work; a replica-level failure re-routes
  the request to a sibling (bounded attempts), so one dying replica
  reads as latency, not errors, at the cell boundary.

The creative rung: the host cold tier's spill format is also the
**transfer** format. ``migrate_session`` exports a session's KV lineage
from its owner (host entries move, device-resident panels/pages copy to
host numpy) and imports it into another replica's host tier — the
session's next turn restores there, byte-identical by the tier's parity
contract (same weights across replicas by construction). ``drain``
composes that with request re-admission for zero-downtime replica
removal: new work routes away instantly, pinned sessions migrate, and
in-flight unary requests past the grace window are cancelled and
re-admitted on a sibling (full greedy re-execution — the cell-level
analogue of PR 8's snapshot + re-admit). Mid-stream requests are the
non-migratable shape (their deltas are already on the wire; the drain
waits for them within grace), same boundary as PR 8's mid-stream
json/schema recovery rule — see docs/SERVING.md "Serving cell".

The cell duck-types ``LLMHandler`` (``generate_response`` / ``astream``
/ ``apredict`` / ``config`` / ``get_metrics``), so ``APIServer`` serves
a cell exactly like a single engine; ``/healthz`` and ``/slo.json``
aggregate across replicas via ``health_snapshot`` / ``slo_snapshot``.

Import cost: stdlib + numpy + handler/obs/reliability — no jax at
import time (the engines themselves import it lazily when they boot).
"""

from __future__ import annotations

import asyncio
import base64
import time
from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np

from pilottai_tpu.distributed.router import (
    CellOverloaded,
    ReplicaRouter,
    ReplicaSignals,
    RoutingTable,
    route_key,
)
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.kvcache.integrity import KV_FRAME_VERSION
from pilottai_tpu.obs import DEFAULT_CLASS, SLOTracker
from pilottai_tpu.reliability import (
    CircuitOpenError,
    DeadlineExceeded,
    EngineOverloaded,
    global_engine_health,
)
from pilottai_tpu.reliability.inject import global_injector
from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import MetricsRegistry, global_metrics


class CellReplica:
    """One replica: an ``LLMHandler`` plus the cell-side bookkeeping the
    router reads (per-replica SLO tracker on its own registry, in-flight
    count, draining flag)."""

    def __init__(
        self,
        replica_id: str,
        handler: LLMHandler,
        slo_classes=None,
        soft_inflight: Optional[int] = None,
    ) -> None:
        self.replica_id = replica_id
        self.handler = handler
        #: Per-replica obs registry: the replica's SLO series live here,
        #: namespaced by object instead of by string prefix — N replicas
        #: in one process can't collide on ``slo.interactive.*``.
        self.registry = MetricsRegistry()
        self.slo = SLOTracker(classes=slo_classes, registry=self.registry)
        self.draining = False
        self.inflight = 0
        #: Soft in-flight norm for queue_frac when the backend exposes no
        #: engine queue (mock replicas, engine not yet booted).
        self.soft_inflight = soft_inflight or max(
            getattr(handler.config, "max_concurrent_requests", 8) or 8, 1
        )
        self._calls: set = set()
        #: Tasks the DRAIN cancelled (vs the caller): the execute loop
        #: re-admits exactly these — inferring from the draining flag
        #: would misread a client disconnect racing a drain as a
        #: re-admission and resurrect an abandoned request.
        self._drain_cancelled: set = set()

    @property
    def health_source(self) -> Optional[str]:
        """This replica's ``EngineHealth`` source (the engine watchdog's
        name when it has one, else a cell-scoped name tests can trip)."""
        batcher = getattr(self.handler.backend, "batcher", None)
        src = getattr(batcher, "watchdog_source", None)
        return src if src is not None else f"cell:{self.replica_id}"

    def signals(self) -> ReplicaSignals:
        """The router's view of this replica, combining engine-side
        signals (queue/degrade/watchdog, when an engine is up) with
        cell-side ones (in-flight count, per-class burn, breaker,
        draining)."""
        raw = getattr(self.handler.backend, "routing_signals", None)
        sig = raw() if callable(raw) else {}
        depth = int(sig.get("queue_depth", 0)) + self.inflight
        queue_frac = max(
            float(sig.get("queue_frac", 0.0)),
            self.inflight / self.soft_inflight,
        )
        self.slo.refresh_gauges()
        burn = {
            cls: self.registry.get(f"slo.{cls}.burn_rate")
            for cls in self.slo.classes
        }
        breaker = self.handler.breaker
        breaker_open = breaker is not None and breaker.state == "open"
        healthy = bool(
            sig.get("healthy", True)
        ) and global_engine_health.source_healthy(self.health_source)
        return ReplicaSignals(
            replica_id=self.replica_id,
            queue_depth=depth,
            queue_frac=queue_frac,
            degrade_level=int(sig.get("degrade_level", 0)),
            mesh_rung=int(sig.get("mesh_rung", 0)),
            burn_rate=burn,
            healthy=healthy,
            breaker_open=breaker_open,
            draining=self.draining,
        )


class ServingCell:
    """The cell front door (see module docstring)."""

    def __init__(
        self,
        replicas: Iterable[CellReplica | LLMHandler],
        router: Optional[ReplicaRouter] = None,
        *,
        slo_classes=None,
        reroute_attempts: int = 2,
        table_capacity: int = 4096,
        max_sessions: int = 4096,
    ) -> None:
        self.replicas: Dict[str, CellReplica] = {}
        for i, rep in enumerate(replicas):
            if isinstance(rep, LLMHandler):
                rep = CellReplica(f"r{i}", rep, slo_classes=slo_classes)
            self.replicas[rep.replica_id] = rep
        if not self.replicas:
            raise ValueError("a serving cell needs at least one replica")
        self.router = router if router is not None else ReplicaRouter(
            RoutingTable(capacity=table_capacity)
        )
        self.reroute_attempts = max(0, int(reroute_attempts))
        #: session id → owning replica id (sticky affinity pins).
        #: Bounded LRU, same rationale as ``HostTier``'s session table:
        #: client-minted ids must not grow cell state without bound.
        self.sessions: "OrderedDict[str, str]" = OrderedDict()
        self.max_sessions = max(1, int(max_sessions))
        first = next(iter(self.replicas.values()))
        self._classes = set(first.slo.classes)
        for cls in self._classes:
            # Non-default classes: the cell's per-class counters must
            # exist in the exported surface too (obs/__init__ declares
            # the default interactive/batch pair at import).
            global_metrics.declare(f"cell.routed.{cls}", "counter")
            global_metrics.declare(f"cell.shed.{cls}", "counter")
        self._log = get_logger("cell")
        self._started = False
        global_metrics.set_gauge("cell.replicas", float(len(self.replicas)))

    # ------------------------------------------------------------------ #
    # LLMHandler duck-type surface (APIServer compatibility)
    # ------------------------------------------------------------------ #

    @property
    def config(self):
        return next(iter(self.replicas.values())).handler.config

    @property
    def backend(self):
        """First replica's backend — replicas are identical by
        construction, so schema-support checks hold cell-wide."""
        return next(iter(self.replicas.values())).handler.backend

    # ------------------------------------------------------------------ #
    # Lifecycle
    # ------------------------------------------------------------------ #

    async def start(self) -> None:
        if self._started:
            return
        for rep in self.replicas.values():
            await rep.handler.start()
            self._wire_eviction_decay(rep)
            if rep.handler.breaker is not None:
                # Scope the breaker's stall subscription to THIS
                # replica's engine: a sibling's watchdog stall must not
                # force-open every breaker in the process (one hung
                # replica would ground the whole cell).
                rep.handler.breaker.health_sources = {rep.health_source}
        self._started = True
        self._refresh_gauges()

    async def stop(self) -> None:
        for rep in self.replicas.values():
            await rep.handler.stop()
        self._started = False

    def _wire_eviction_decay(self, rep: CellReplica) -> None:
        """Affinity must not outlive the KV it points at: when a
        replica's host tier drops an entry for good (budget eviction —
        the KV is gone from BOTH tiers), ``HostTier.on_evict`` offers
        the evicted key to the routing table. The decay is EXACT when
        the table is keyed by the same token ids the engine caches
        (token-level router deployments; pinned by the unit test). The
        cell's own table keys are rendered-prompt bytes, which the
        engine's tokenization/chat rendering generally shifts — there
        the forget is a best-effort no-op and the table's LRU bound +
        ``forget_replica`` on drain/death are the decay that holds."""
        batcher = getattr(rep.handler.backend, "batcher", None)
        kvcache = getattr(batcher, "kvcache", None)
        host = getattr(kvcache, "host", None)
        if host is not None:
            # Ownership-checked: replica A evicting its copy of a shared
            # preamble must not decay an entry pointing at replica B,
            # whose copy is still live.
            rid = rep.replica_id
            host.on_evict = (
                lambda key, _rid=rid: self.router.table.forget_owned(
                    key, _rid
                )
            )

    # ------------------------------------------------------------------ #
    # Routing
    # ------------------------------------------------------------------ #

    @staticmethod
    def _route_text(messages) -> str:
        if isinstance(messages, str):
            return messages
        parts = []
        for m in messages:
            if isinstance(m, str):
                parts.append(m)
            elif isinstance(m, dict):
                parts.append(str(m.get("content", "")))
            else:
                parts.append(str(getattr(m, "content", "")))
        return "\n".join(parts)

    def _classify(self, slo_class: Optional[str]) -> str:
        return slo_class if slo_class in self._classes else DEFAULT_CLASS

    def signals(self) -> List[ReplicaSignals]:
        return [rep.signals() for rep in self.replicas.values()]

    def _refresh_gauges(
        self, sigs: Optional[List[ReplicaSignals]] = None
    ) -> None:
        # Callers on the routing hot path pass the sweep they already
        # computed — per-replica signals (SLO window refresh, health
        # lock, engine probe) are not free twice per request.
        if sigs is None:
            sigs = self.signals()
        global_metrics.set_gauge("cell.replicas", float(len(sigs)))
        global_metrics.set_gauge(
            "cell.replicas_routable",
            float(sum(s.routable() for s in sigs)),
        )
        # Replicas serving on a degraded mesh rung (shard loss survived
        # via re-plan): still routable, but the router down-scores them
        # and rebalance_degraded migrates sessions off.
        global_metrics.set_gauge(
            "cell.degraded_replicas",
            float(sum(s.mesh_rung > 0 for s in sigs)),
        )
        global_metrics.set_gauge("cell.sessions", float(len(self.sessions)))
        lookups = global_metrics.get("cell.affinity_lookups")
        if lookups:
            global_metrics.set_gauge(
                "cell.affinity_hit_rate",
                global_metrics.get("cell.affinity_hits") / lookups,
            )

    def _route(
        self,
        key: Sequence[int],
        cls: str,
        session_id: Optional[str],
        exclude: List[str],
    ) -> tuple:
        pinned = self.sessions.get(session_id) if session_id else None
        sigs = self.signals()
        try:
            rid, lcp = self.router.pick(
                key, sigs, slo_class=cls, pinned=pinned, exclude=exclude,
            )
        except CellOverloaded as exc:
            global_metrics.inc(f"cell.shed.{cls}")
            self._refresh_gauges(sigs)
            raise EngineOverloaded(str(exc)) from exc
        global_metrics.inc(f"cell.routed.{cls}")
        global_metrics.inc("cell.affinity_lookups")
        if lcp > 0 or (pinned is not None and pinned == rid):
            global_metrics.inc("cell.affinity_hits")
        self._refresh_gauges(sigs)
        return rid, lcp

    def _after_success(
        self, rid: str, key: Sequence[int], session_id: Optional[str]
    ) -> None:
        self.router.table.note(key, rid)
        if not session_id:
            return
        rep = self.replicas.get(rid)
        if rep is None or rep.draining:
            # Never (re-)pin to a draining/detached replica — a request
            # finishing inside the drain's grace window must not undo
            # the drain's migration.
            return
        cur = self.sessions.get(session_id)
        if cur is not None and cur != rid:
            cur_rep = self.replicas.get(cur)
            if cur_rep is not None and not cur_rep.draining:
                # The pin moved (migration/rebalance) while this request
                # was in flight: the newer LIVE pin owns the session's
                # KV now — a stale completion must not re-pin the old
                # owner and strand the migrated KV. (A dead/draining
                # current pin DOES yield: failover re-pins here.)
                return
        self.sessions[session_id] = rid
        self.sessions.move_to_end(session_id)
        while len(self.sessions) > self.max_sessions:
            self.sessions.popitem(last=False)

    # ------------------------------------------------------------------ #
    # Request execution
    # ------------------------------------------------------------------ #

    async def generate_response(
        self,
        messages,
        tools=None,
        params=None,
        json_mode=None,
        json_schema=None,
        slo_class: Optional[str] = None,
        session_id: Optional[str] = None,
        priority: Optional[int] = None,
        gang_id: Optional[str] = None,
        gang_size: int = 0,
    ):
        """Route-and-execute with bounded re-routing: replica faults
        (including a drain cancelling the in-flight call) re-admit on a
        sibling; client-semantic failures (deadline, cell shed) do not."""
        cls = self._classify(
            slo_class or getattr(params, "slo_class", None)
        )
        sid = session_id or getattr(params, "session_id", None)
        key = route_key(self._route_text(messages))
        excluded: List[str] = []
        attempts = 0
        # Client-observed clock: started ONCE, before any attempt — a
        # rerouted request's recorded e2e must include the failed
        # attempts the client also waited through, charged to the
        # replica that finally served it.
        t0 = time.perf_counter()
        while True:
            rid, _lcp = self._route(key, cls, sid, excluded)
            rep = self.replicas[rid]
            rep.inflight += 1
            task = asyncio.ensure_future(rep.handler.generate_response(
                messages, tools=tools, params=params, json_mode=json_mode,
                json_schema=json_schema, slo_class=cls, session_id=sid,
                priority=priority, gang_id=gang_id, gang_size=gang_size,
            ))
            rep._calls.add(task)
            try:
                response = await task
            except asyncio.CancelledError:
                was_drain = task in rep._drain_cancelled
                rep._drain_cancelled.discard(task)
                if task.cancelled() and was_drain:
                    # Drain re-admission: the DRAIN cancelled this task
                    # (explicit marker — a client disconnect racing the
                    # drain must keep propagating as a cancel, not
                    # resurrect the request on a sibling). Re-route the
                    # whole request: pure re-execution, byte-identical
                    # greedy output on an identical sibling. Routine
                    # operation — no SLO miss recorded.
                    global_metrics.inc("cell.rerouted")
                    excluded.append(rid)
                    continue
                task.cancel()
                raise
            except DeadlineExceeded:
                # Terminal client outcome: the budget is gone wherever
                # we'd route next.
                rep.slo.record(cls, ok=False)
                raise
            except (EngineOverloaded, CircuitOpenError):
                # Backpressure / fast-fail below the cell's threshold
                # (racy burst, breaker race): try a sibling. The queue
                # and breaker signals already carry this state — a miss
                # is recorded only when the request terminally fails,
                # else a retried-then-served request would count twice
                # (once as a phantom miss) and sink reported attainment
                # below what clients actually observed.
                excluded.append(rid)
                attempts += 1
                if attempts <= self.reroute_attempts:
                    global_metrics.inc("cell.rerouted")
                    continue
                rep.slo.record(cls, ok=False)
                raise
            except Exception:
                # Replica fault: burn THIS replica's budget (the router
                # reads it) and re-route, bounded.
                rep.slo.record(cls, ok=False)
                excluded.append(rid)
                attempts += 1
                if attempts <= self.reroute_attempts:
                    global_metrics.inc("cell.rerouted")
                    continue
                raise
            finally:
                rep.inflight -= 1
                rep._calls.discard(task)
            rep.slo.record(
                cls, e2e_s=time.perf_counter() - t0, ok=True
            )
            self._after_success(rid, key, sid)
            return response

    async def apredict(self, prompt: str, **kwargs: Any) -> str:
        response = await self.generate_response([prompt], **kwargs)
        return response.content

    async def astream(
        self,
        messages,
        tools=None,
        params=None,
        json_mode=None,
        json_schema=None,
        slo_class: Optional[str] = None,
        session_id: Optional[str] = None,
        info: Optional[Dict[str, Any]] = None,
    ):
        """Streaming path: routed once — a stream whose deltas reached
        the consumer is the non-migratable shape (drain waits for it
        within grace; docs/SERVING.md), so no mid-stream re-route."""
        cls = self._classify(
            slo_class or getattr(params, "slo_class", None)
        )
        sid = session_id or getattr(params, "session_id", None)
        key = route_key(self._route_text(messages))
        rid, _lcp = self._route(key, cls, sid, [])
        rep = self.replicas[rid]
        t0 = time.perf_counter()
        rep.inflight += 1
        ok = False
        abandoned = False
        try:
            async for delta in rep.handler.astream(
                messages, tools=tools, params=params, json_mode=json_mode,
                json_schema=json_schema, slo_class=cls, session_id=sid,
                info=info,
            ):
                yield delta
            ok = True
        except (GeneratorExit, asyncio.CancelledError):
            # Consumer walked away — not the replica's failure. Charging
            # it as a miss would raise this replica's burn rate and
            # steer the router away from a healthy replica that merely
            # served flaky clients.
            abandoned = True
            raise
        finally:
            rep.inflight -= 1
            if not abandoned:
                rep.slo.record(
                    cls, e2e_s=time.perf_counter() - t0, ok=ok
                )
            if ok:
                self._after_success(rid, key, sid)

    # ------------------------------------------------------------------ #
    # Session migration + drain (the transfer-format rung)
    # ------------------------------------------------------------------ #

    def _pick_target(self, exclude: Sequence[str]) -> str:
        """Migration target: the least-loaded ROUTABLE sibling, full-
        mesh replicas before degraded ones (a replica surviving shard
        loss on a sub-mesh rung is a worse home for a session than an
        intact sibling, whatever its queue says). This is a
        control-plane move, not an admission — class shed thresholds
        don't apply (a saturated-but-healthy sibling still accepts a
        session's KV; it just serves the next turn slower)."""
        excluded = set(exclude)
        candidates = [
            s for s in self.signals()
            if s.routable() and s.replica_id not in excluded
        ]
        if not candidates:
            raise CellOverloaded(
                "no routable replica to migrate the session to"
            )
        return min(
            candidates,
            key=lambda s: (s.mesh_rung > 0, s.queue_frac, s.replica_id),
        ).replica_id

    async def migrate_session(
        self, session_id: str, target_id: Optional[str] = None
    ) -> Dict[str, Any]:
        """Move a session's KV lineage (and its affinity pin) to another
        replica via the host tier's transfer format. Safe to call on a
        backend without the KV tier — only the pin moves and the target
        re-prefills (correct, just slower)."""
        src_id = self.sessions.get(session_id)
        if src_id is None:
            raise ValueError(f"unknown session {session_id!r}")
        if target_id is None:
            target_id = self._pick_target(exclude=[src_id])
        if target_id == src_id:
            raise ValueError("migration target is the session's owner")
        src = self.replicas[src_id]
        dst = self.replicas[target_id]
        t0 = time.perf_counter()
        loop = asyncio.get_running_loop()
        export = None
        exporter = getattr(src.handler.backend, "export_session_kv", None)
        if callable(exporter):
            # Blocking device→host gathers: off the event loop.
            export = await loop.run_in_executor(None, exporter, session_id)
        accepted = 0
        tokens = 0
        rejected = 0
        n_entries = len(export["entries"]) if export else 0
        if export:
            # The spill format is the transfer format, and the WIRE form
            # is its canonical frame: round-trip every migration through
            # it (even in-process) so the integrity framing — per-entry
            # header+CRC sealed at export, top-level frame version — is
            # exercised on the path that matters, and so the
            # ``cell.migrate.corrupt`` chaos point has a real payload to
            # rot. A corrupted or version-drifted frame rejects cleanly
            # at import (counted, dropped, session re-prefills on the
            # target) — never lands as silent wrong KV.
            wire = session_kv_to_wire(export)
            if global_injector.fire("cell.migrate.corrupt"):
                corrupt_wire_payload(wire)
            try:
                export = session_kv_from_wire(wire)
            except ValueError as exc:
                self._log.warning(
                    "migration frame for session %s rejected: %s",
                    session_id, exc,
                )
                export = None
                rejected = n_entries
                global_metrics.inc(
                    "engine.kvcache.integrity_failures", n_entries
                )
        if export:
            importer = getattr(dst.handler.backend, "import_session_kv", None)
            if callable(importer):
                landed = await loop.run_in_executor(None, importer, export)
                accepted = int(landed.get("accepted", 0))
                # Only KV that actually LANDED on the target counts as
                # migrated — budget-rejected entries stay source-side
                # copies and will re-prefill, and the metric must not
                # claim otherwise.
                tokens = int(landed.get("tokens", 0))
                rejected = int(landed.get("rejected", 0))
        self.sessions[session_id] = target_id
        wall_ms = (time.perf_counter() - t0) * 1e3
        global_metrics.inc("cell.migrations")
        global_metrics.inc("cell.migrated_entries", accepted)
        global_metrics.inc("cell.migrated_tokens", tokens)
        if rejected:
            global_metrics.inc("cell.migrate_rejected", rejected)
        global_metrics.observe("cell.migration_ms", wall_ms)
        self._log.info(
            "migrated session %s: %s -> %s (%d/%d entries, %d rejected, "
            "%d tokens, %.1f ms)",
            session_id, src_id, target_id, accepted, n_entries, rejected,
            tokens, wall_ms,
        )
        return {
            "session_id": session_id,
            "from": src_id,
            "to": target_id,
            "entries": n_entries,
            "accepted": accepted,
            "rejected": rejected,
            "tokens": tokens,
            "migration_ms": round(wall_ms, 3),
        }

    async def rebalance_degraded(self) -> Dict[str, Any]:
        """Migrate pinned sessions OFF replicas serving on a degraded
        mesh rung, onto intact siblings — the second half of the
        drain-then-restore runbook (degrade → rebalance → rebuild the
        replica at full mesh → sessions migrate back on the next
        rebalance). No-op when nothing is degraded or no full-mesh
        routable sibling exists (migrating between two degraded
        replicas helps nobody)."""
        sigs = {s.replica_id: s for s in self.signals()}
        degraded = sorted(
            rid for rid, s in sigs.items() if s.mesh_rung > 0
        )
        intact = [
            rid for rid, s in sigs.items()
            if s.mesh_rung == 0 and s.routable()
        ]
        moved: List[Dict[str, Any]] = []
        if degraded and intact:
            for sid, owner in list(self.sessions.items()):
                if owner not in degraded:
                    continue
                try:
                    moved.append(await self.migrate_session(sid))
                except Exception as exc:  # noqa: BLE001 — keep sweeping
                    self._log.warning(
                        "session %s could not rebalance off degraded "
                        "replica %s: %s", sid, owner, exc,
                    )
        self._refresh_gauges()
        return {
            "degraded": degraded,
            "moved": len(moved),
            "migrations": moved,
        }

    async def drain(
        self, replica_id: str, grace_s: float = 5.0,
    ) -> Dict[str, Any]:
        """Zero-downtime replica drain: stop routing to it immediately,
        migrate its pinned sessions, give in-flight work ``grace_s`` to
        finish, then cancel the stragglers — the cell's execute loop
        re-admits each cancelled unary request on a sibling (snapshot +
        re-admit at request granularity). The replica stays registered
        (and stopped-routable) until ``undrain`` or ``remove_replica``."""
        rep = self.replicas[replica_id]
        t0 = time.perf_counter()
        rep.draining = True
        self._refresh_gauges()
        migrated = []
        others = [r for r in self.replicas if r != replica_id]
        if others:
            for sid, owner in list(self.sessions.items()):
                if owner != replica_id:
                    continue
                try:
                    migrated.append(await self.migrate_session(sid))
                except Exception as exc:  # noqa: BLE001 — drain proceeds
                    # No routable target / export race: drop the pin so
                    # the session's next turn routes fresh (it
                    # re-prefills — correct, just slower) instead of
                    # sticking to a draining replica.
                    self.sessions.pop(sid, None)
                    self._log.warning(
                        "session %s could not migrate during drain of "
                        "%s: %s", sid, replica_id, exc,
                    )
        deadline = time.monotonic() + max(grace_s, 0.0)
        while rep.inflight and time.monotonic() < deadline:
            await asyncio.sleep(0.01)
        readmitted = 0
        for task in list(rep._calls):
            if not task.done():
                # Mark BEFORE cancelling: the execute loop re-admits
                # exactly the tasks the drain cancelled.
                rep._drain_cancelled.add(task)
                task.cancel()
                readmitted += 1
        # Let the re-admissions detach before reporting — bounded: a
        # straggler stuck in a non-cancellable section must not wedge
        # the drain (it finishes or fails on its own; routing to this
        # replica is already off either way).
        cancel_deadline = time.monotonic() + 30.0
        while rep.inflight and time.monotonic() < cancel_deadline:
            await asyncio.sleep(0.01)
        self.router.table.forget_replica(replica_id)
        wall = time.perf_counter() - t0
        global_metrics.inc("cell.drains")
        global_metrics.observe("cell.drain_s", wall)
        self._refresh_gauges()
        self._log.info(
            "drained %s in %.2fs (%d sessions migrated, %d re-admitted)",
            replica_id, wall, len(migrated), readmitted,
        )
        return {
            "replica_id": replica_id,
            "drain_s": round(wall, 3),
            "migrated_sessions": len(migrated),
            "migrations": migrated,
            "readmitted": readmitted,
        }

    def undrain(self, replica_id: str) -> None:
        self.replicas[replica_id].draining = False
        self._refresh_gauges()

    async def remove_replica(self, replica_id: str) -> Dict[str, Any]:
        """Drain then detach and stop a replica (rolling rebuild)."""
        report = await self.drain(replica_id)
        rep = self.replicas.pop(replica_id)
        await rep.handler.stop()
        self._refresh_gauges()
        return report

    # ------------------------------------------------------------------ #
    # Aggregated health / SLO / metrics surfaces
    # ------------------------------------------------------------------ #

    def health_snapshot(self) -> Dict[str, Any]:
        """The cell ``/healthz`` shape: ok while at least one replica is
        routable; per-replica verdicts attached so an operator sees
        WHICH replica grounded."""
        sigs = self.signals()
        routable = [s for s in sigs if s.routable()]
        # PR 8 503 contract: a grounded cell still hints when to come
        # back (the largest retry_after across stalled engine sources;
        # breakers' own recovery_timeout is the same order).
        health = global_engine_health.snapshot()
        return {
            "ok": bool(routable),
            "replicas": len(sigs),
            "routable": len(routable),
            "retry_after": health.get("retry_after", 0.0),
            "draining": sorted(
                s.replica_id for s in sigs if s.draining
            ),
            "stalled": sorted(
                s.replica_id for s in sigs if not s.healthy
            ),
            "per_replica": {s.replica_id: s.to_payload() for s in sigs},
        }

    def slo_snapshot(self) -> Dict[str, Any]:
        """The cell ``/slo.json`` shape: per-class aggregate (request-
        weighted attainment/burn, worst-replica p99) plus each replica's
        own tracker snapshot."""
        per: Dict[str, Any] = {
            rid: rep.slo.snapshot() for rid, rep in self.replicas.items()
        }
        agg: Dict[str, Any] = {}
        for cls in sorted(self._classes):
            entries = [
                snap[cls] for snap in per.values() if cls in snap
            ]
            if not entries:
                continue
            requests = sum(e["requests"] for e in entries)
            missed = sum(e["missed"] for e in entries)
            windows = sum(e["window"] for e in entries)
            # No traffic = no misses: an idle cell reports attainment
            # 1.0 / burn 0.0, matching the single-engine surface (a
            # zero-filled aggregate would fire attainment alerts on
            # every fresh boot).
            agg[cls] = {
                "requests": requests,
                "missed": missed,
                "attainment": round(sum(
                    e["attainment"] * e["window"] for e in entries
                ) / windows, 4) if windows else 1.0,
                "burn_rate": round(sum(
                    e["burn_rate"] * e["window"] for e in entries
                ) / windows, 4) if windows else 0.0,
                "ttft_p99_s": max(
                    (e["ttft_p99_s"] for e in entries
                     if e.get("ttft_p99_s") is not None), default=None,
                ),
                "e2e_p99_s": max(
                    (e["e2e_p99_s"] for e in entries
                     if e.get("e2e_p99_s") is not None), default=None,
                ),
                "targets": entries[0]["targets"],
            }
        return {"aggregate": True, "classes": agg, "replicas": per}

    def get_metrics(self) -> Dict[str, Any]:
        self._refresh_gauges()
        cell = {
            name.split("cell.", 1)[1]: global_metrics.get(name)
            for name in (
                "cell.affinity_lookups", "cell.affinity_hits",
                "cell.affinity_hit_rate", "cell.rerouted",
                "cell.migrations", "cell.migrated_tokens",
                "cell.migrate_rejected", "cell.degraded_replicas",
                "cell.drains",
            )
        }
        for cls in sorted(self._classes):
            cell[f"routed.{cls}"] = global_metrics.get(f"cell.routed.{cls}")
            cell[f"shed.{cls}"] = global_metrics.get(f"cell.shed.{cls}")
        return {
            "cell": cell,
            "sessions": len(self.sessions),
            "replicas": {
                rid: rep.handler.get_metrics()
                for rid, rep in self.replicas.items()
            },
        }


# --------------------------------------------------------------------- #
# Wire form of the transfer format (control-plane ready)
# --------------------------------------------------------------------- #

def session_kv_to_wire(export: Dict[str, Any]) -> Dict[str, Any]:
    """JSON-safe form of ``export_session_kv``'s record: arrays as
    base64 + dtype + shape — the shape a control-plane frame can carry
    to a remote worker's ``import_session_kv``. The integrity frame
    rides along verbatim: the top-level ``v`` (frame version) gates
    interpretation at ``session_kv_from_wire``, and each entry's sealed
    ``header``/``crc`` (from export) gate the bytes at import — a
    flipped bit anywhere between the two replicas rejects cleanly."""
    def pack(a: np.ndarray) -> Dict[str, Any]:
        a = np.ascontiguousarray(a)
        return {
            "dtype": str(a.dtype),
            "shape": list(a.shape),
            "data": base64.b64encode(a.tobytes()).decode("ascii"),
        }

    return {
        "v": KV_FRAME_VERSION,
        "session_id": export["session_id"],
        "ids": list(export["ids"]),
        "entries": [
            {
                "key": list(e["key"]),
                "tokens": e["tokens"], "rows": e["rows"],
                "meta": e["meta"], "kind": e["kind"],
                "header": e.get("header"), "crc": e.get("crc"),
                "k": pack(e["k"]), "v": pack(e["v"]),
            }
            for e in export["entries"]
        ],
    }


def session_kv_from_wire(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of :func:`session_kv_to_wire`. Raises ``ValueError`` on
    an unknown frame version — a replica on a different wire format
    must reject the whole payload before interpreting a byte (the
    per-entry header/crc checks at ``import_session`` then catch
    rot/drift inside a well-versioned frame)."""
    v = payload.get("v", KV_FRAME_VERSION)
    if v != KV_FRAME_VERSION:
        raise ValueError(
            f"unknown KV wire frame version {v!r} "
            f"(expected {KV_FRAME_VERSION})"
        )

    def unpack(p: Dict[str, Any]) -> np.ndarray:
        return np.frombuffer(
            base64.b64decode(p["data"]), dtype=np.dtype(p["dtype"])
        ).reshape(p["shape"])

    return {
        "session_id": payload["session_id"],
        "ids": list(payload["ids"]),
        "entries": [
            {
                "key": list(e["key"]),
                "tokens": e["tokens"], "rows": e["rows"],
                "meta": e["meta"], "kind": e["kind"],
                "header": e.get("header"), "crc": e.get("crc"),
                "k": unpack(e["k"]), "v": unpack(e["v"]),
            }
            for e in payload["entries"]
        ],
    }


def corrupt_wire_payload(wire: Dict[str, Any]) -> bool:
    """Chaos helper for ``cell.migrate.corrupt``: flip one byte of the
    first non-empty packed array IN the wire frame (after its CRC was
    sealed at export) — the canonical 'frame rotted in transit'
    injection. Returns True when a byte was flipped."""
    for e in wire.get("entries", ()):
        for part in ("k", "v"):
            raw = bytearray(base64.b64decode(e[part]["data"]))
            if not raw:
                continue
            raw[0] ^= 0xFF
            e[part]["data"] = base64.b64encode(bytes(raw)).decode("ascii")
            return True
    return False


__all__ = [
    "CellReplica",
    "ServingCell",
    "corrupt_wire_payload",
    "session_kv_from_wire",
    "session_kv_to_wire",
]
